"""City-scale digital twin — everything on at once (ISSUE 12 tentpole).

Every subsystem has its own bench leg; production systems break where
the legs meet: a churn mutation landing while a replica is being
killed while the admission queue is saturated.  The twin is ONE
sustained scenario that drives a replicated
:class:`~pydcop_tpu.serve.SolveFleet` under four concurrent pressures:

* **multi-tenant traffic** — seeded Poisson arrivals over a mixed
  workload pool (routing + tracking + graph coloring — the families
  that stress axes coloring never touches), each job mapped to a
  deadline tier (gold/silver/bronze → priority + ``deadline_s``;
  scenario/slo.py);
* **live churn** — one live problem held by a
  :class:`~pydcop_tpu.runtime.repair.WarmRepairController`, mutated by
  a scenario event stream (``churn_scenario`` jitter edits,
  ``tracking_scenario`` target motion, agent re-hosting) plus the
  fault plan's churn kinds — every ``change_factor`` a fixed-shape
  warm buffer write, time-to-recover-cost measured per mutation;
* **chaos** — ONE seeded :class:`~pydcop_tpu.runtime.faults.FaultPlan`
  whose fleet kinds (``kill_replica``/``stall_replica``/
  ``partition_replica``) fire in the fleet supervisor, serve kinds
  (``nan_lane``/``raise_in_step``/``torn_journal_write``/
  ``stall_tick``) fire inside every replica, and churn kinds
  (``edit_factor``/``*_agent_burst``) fire against the live problem —
  the combined plan no unit leg ever runs;
* **--auto** — optional portfolio selection per traffic instance
  (pydcop_tpu.portfolio.select; the heuristic fallback with no model),
  recording the chosen configs.

The run is **tick-driven and seeded**: arrivals, tier assignment,
chaos and churn are all functions of their seeds and the tick counter,
so the same configuration replays the same scenario; and because every
serve path is bit-deterministic, the FINISHED jobs of a chaos run are
bit-identical to an unfaulted replay (the twin bench pins this).

Scoring is the SLO scorecard (scenario/slo.py): per-tier deadline
attainment and p99, shed rate, time-to-recover-cost per mutation, and
the RTO of every injected kill — guarded by the degradation
:class:`~pydcop_tpu.scenario.slo.SloLadder` whose three rungs (shed
bronze → clamp silver chunks → reroute gold to the emptiest healthy
replica) are what keep gold at its floor while everything else burns.
"""
from __future__ import annotations

import dataclasses
import shutil
import tempfile
from time import monotonic
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.batch.engine import SUPPORTED_ALGOS
from pydcop_tpu.dcop.scenario import Scenario
from pydcop_tpu.runtime.events import send_slo
from pydcop_tpu.runtime.faults import Fault, FaultPlan
from pydcop_tpu.runtime.stats import SloCounters
from pydcop_tpu.scenario.slo import (
    JobScore,
    SloLadder,
    TierSpec,
    default_tiers,
    scorecard,
)
from pydcop_tpu.serve import ServeError, SolveFleet


@dataclasses.dataclass
class TwinJob:
    """One unit of twin traffic: an instance, its tier and its seeded
    arrival tick."""

    index: int
    dcop: Any
    family: str
    tier: str
    tenant: str
    seed: int
    arrival_tick: int
    algo: str
    algo_params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    label: str = ""
    config: Optional[Dict[str, Any]] = None  # --auto chosen config
    # runtime bookkeeping
    spec: Any = None  # pre-built adapter spec (instance compilation
    #                   happens off the measured scenario, like the
    #                   threaded service's prep pool)
    jid: Optional[str] = None
    submitted_at: Optional[float] = None
    scored: bool = False


def build_twin_traffic(
    n_jobs: int,
    tiers: Tuple[TierSpec, ...],
    seed: int = 0,
    algo: str = "mgm",
    mean_interarrival_ticks: float = 2.0,
    routing_tasks: int = 12,
    tracking_sensors: int = 16,
    coloring_vars: int = 40,
    auto: bool = False,
) -> List[TwinJob]:
    """Seeded twin traffic: instances cycle over the routing, tracking
    and graph-coloring families (distinct seeds each), tiers are drawn
    by their ``share`` weights, and arrivals follow a Poisson process
    measured in *ticks* (exponential inter-arrivals, so the schedule
    is a pure function of the seed — no wall clock).

    ``auto=True`` asks the learned portfolio (or its heuristic
    fallback when no model is trained) for each instance's config;
    batch-eligible picks override ``algo`` and the choice is recorded
    on the job (the ``--auto`` arm of the twin)."""
    from pydcop_tpu.generators import (
        generate_graph_coloring,
        generate_routing,
        generate_tracking,
    )

    rng = np.random.default_rng(seed)
    shares = np.array([t.share for t in tiers], np.float64)
    shares = shares / shares.sum()
    inter = rng.exponential(mean_interarrival_ticks, n_jobs)
    inter[0] = 0.0
    ticks = np.cumsum(inter).astype(int)
    jobs: List[TwinJob] = []
    for i in range(n_jobs):
        fam = ("routing", "tracking", "coloring")[i % 3]
        if fam == "routing":
            dcop = generate_routing(routing_tasks, seed=1000 + i)
        elif fam == "tracking":
            dcop = generate_tracking(tracking_sensors, n_targets=2,
                                     seed=2000 + i)
        else:
            dcop = generate_graph_coloring(
                n_variables=coloring_vars, n_colors=3,
                n_edges=coloring_vars * 3, soft=True, n_agents=1,
                seed=3000 + i,
            )
        tier = tiers[int(rng.choice(len(tiers), p=shares))]
        job = TwinJob(
            index=i, dcop=dcop, family=fam, tier=tier.name,
            tenant=tier.name, seed=i, arrival_tick=int(ticks[i]),
            algo=algo, label=f"{fam}:{i}",
        )
        if auto:
            from pydcop_tpu.portfolio.select import select_config

            sel = select_config(dcop)
            job.config = sel.config.as_dict()
            if sel.config.algo in SUPPORTED_ALGOS:
                job.algo = sel.config.algo
                job.algo_params = dict(sel.config.algo_params())
        jobs.append(job)
    return jobs


def default_chaos_plan(
    seed: int = 0,
    kill_tick: int = 8,
    kill_replica: int = 0,
    stall_tick_at: int = 4,
    nan_tick: int = 6,
    churn_edit_ticks: Sequence[int] = (10, 18),
    device_loss_tick: Optional[int] = 5,
    device_loss_replica: int = 1,
    process_kill_tick: Optional[int] = None,
    process_kill_replica: int = 0,
) -> FaultPlan:
    """The twin's combined chaos plan: one replica kill (fleet), one
    wedged scheduler tick + one transient NaN lane + one torn journal
    append (serve), seeded ``edit_factor`` churn against the live
    problem, and one device loss (ISSUE 14: a ``kill_device`` against
    a SURVIVING replica, which keeps serving but advertises reduced
    capacity to the router) — every layer's fault machinery armed by
    ONE plan.  With ``process_kill_tick`` set (ISSUE 16: the plan is
    feeding a :class:`~pydcop_tpu.serve.ProcessFleet`), a whole
    replica *process* is additionally SIGKILLed at that tick — the
    thread-mode default stays ``None`` so existing twin pins are
    untouched."""
    faults = [
        Fault(kind="kill_replica", replica=int(kill_replica),
              cycle=int(kill_tick)),
        Fault(kind="stall_tick", duration=0.05,
              cycle=int(stall_tick_at)),
        Fault(kind="nan_lane", cycle=int(nan_tick)),
        Fault(kind="torn_journal_write", cycle=2),
    ]
    if device_loss_tick is not None:
        faults.append(Fault(
            kind="kill_device", device=0,
            replica=int(device_loss_replica),
            cycle=int(device_loss_tick),
        ))
    if process_kill_tick is not None:
        faults.append(Fault(
            kind="kill_process", replica=int(process_kill_replica),
            cycle=int(process_kill_tick),
        ))
    for t in churn_edit_ticks:
        faults.append(Fault(kind="edit_factor", cycle=int(t)))
    return FaultPlan(faults=faults, seed=int(seed))


def standalone_results(jobs: Sequence[TwinJob],
                       max_cycles: int = 200) -> Dict[str, Any]:
    """The unfaulted anchor: each traffic instance solved standalone
    with its exact (algo, seed) — by the serve determinism contract,
    every FINISHED twin job must equal these bit for bit, chaos or
    not."""
    from pydcop_tpu.batch.engine import BatchItem, adapter_for

    out: Dict[str, Any] = {}
    for job in jobs:
        adapter = adapter_for(job.algo)
        spec = adapter.build_spec(BatchItem(
            job.dcop, job.algo, algo_params=job.algo_params,
            seed=job.seed,
        ))
        out[job.label] = spec.solver.run(max_cycles=max_cycles)
    return out


class TwinRunner:
    """Drive the combined scenario tick by tick and score it.

    >>> # sketch:
    >>> # jobs = build_twin_traffic(12, tiers, seed=7)
    >>> # twin = TwinRunner(jobs, tiers, fault_plan=default_chaos_plan())
    >>> # card = twin.run()
    >>> # card["tiers"]["gold"]["attainment"]

    ``live_dcop``/``live_scenario`` arm the churn pressure: the live
    problem solves warm (WarmRepairController) and the scenario's
    events fire one per ``churn_every`` ticks, each followed by a
    ``recover_cycles``-cycle warm re-convergence whose wall time is
    the mutation's time-to-recover-cost.  ``fault_plan`` arms all
    three chaos layers (see module docstring).  ``ladder=False`` keeps
    the full SLO accounting but never escalates — the honest OFF arm
    of the guardrail A/B."""

    def __init__(
        self,
        jobs: Sequence[TwinJob],
        tiers: Optional[Tuple[TierSpec, ...]] = None,
        replicas: int = 2,
        lanes: int = 4,
        max_buckets: Optional[int] = None,
        max_cycles: int = 200,
        fault_plan: Optional[FaultPlan] = None,
        journal_dir: Optional[str] = None,
        live_dcop: Any = None,
        live_scenario: Optional[Scenario] = None,
        live_algo: str = "mgm",
        churn_start: int = 3,
        churn_every: int = 2,
        recover_cycles: int = 24,
        ladder: bool = True,
        ladder_window: int = 12,
        ladder_min_samples: int = 4,
        ladder_hold: int = 3,
        silver_pressure: float = 0.5,
        stream: bool = False,
    ):
        self.jobs = list(jobs)
        self.tiers = tiers if tiers is not None else default_tiers()
        self.tier_by_name = {t.name: t for t in self.tiers}
        self.replicas = int(replicas)
        self.lanes = int(lanes)
        self.max_buckets = max_buckets
        self.max_cycles = int(max_cycles)
        self.fault_plan = fault_plan
        self.journal_dir = journal_dir
        self.live_dcop = live_dcop
        self.live_scenario = live_scenario
        self.live_algo = live_algo
        self.churn_start = int(churn_start)
        self.churn_every = max(1, int(churn_every))
        self.recover_cycles = int(recover_cycles)
        self.stream = bool(stream)
        self.counters = SloCounters()
        self.ladder = SloLadder(
            self.tiers, counters=self.counters, window=ladder_window,
            min_samples=ladder_min_samples, hold=ladder_hold,
            silver_pressure=silver_pressure, enabled=ladder,
        )
        self.scores: List[JobScore] = []
        self.results: Dict[str, Any] = {}  # label -> SolveResult
        self.recover_s: List[float] = []
        self.fleet: Optional[SolveFleet] = None
        self._ctl = None  # WarmRepairController over the live problem
        self._pressure_on = False

    # -- live-problem churn --------------------------------------------------

    def _start_live(self) -> None:
        if self.live_dcop is None:
            return
        from pydcop_tpu.runtime.repair import WarmRepairController

        self._ctl = WarmRepairController(
            self.live_dcop, self.live_algo,
            seed=self.fault_plan.seed if self.fault_plan else 0,
        )
        res = self._ctl.solver.run(chunk=self._ctl.chunk,
                                   cycles=self.recover_cycles)
        self._ctl.phase_done(res)

    def _recover(self) -> None:
        """One warm re-convergence phase after a mutation; its wall
        time lands in time_to_recover_s (RepairCounters) and the
        per-mutation list."""
        before = self._ctl.counters.counts["time_to_recover_s"]
        res = self._ctl.solver.run(
            resume=True, cycles=self.recover_cycles,
            chunk=self._ctl.chunk,
        )
        self._ctl.phase_done(res)
        after = self._ctl.counters.counts["time_to_recover_s"]
        if after > before:
            self.recover_s.append(after - before)

    def _apply_churn_event(self, event) -> None:
        """Apply one scenario event's actions through the warm
        controller: tracking motion and jitter edits are fixed-shape
        EditFactor writes; agent add/remove is the re-hosting
        handshake (state retained, recovery clock still runs)."""
        from pydcop_tpu.runtime.repair import perturbed_constraint

        if event.is_delay:
            return
        mutated = False
        for action in event.actions:
            p = action.parameters
            if action.type == "change_factor":
                name = p["constraint"]
                if p.get("family") == "tracking":
                    from pydcop_tpu.generators.tracking import (
                        moved_constraint,
                    )

                    new_c = moved_constraint(
                        self.live_dcop, name, int(p["step"])
                    )
                else:
                    new_c = perturbed_constraint(
                        self.live_dcop.constraints[name],
                        seed=int(p.get("seed", 0)),
                    )
                self._ctl.edit_factor(new_c)
                mutated = True
            elif action.type in ("remove_agent", "add_agent"):
                # re-hosting churn: the warm solver keeps its device
                # state; the run still re-converges, and the recovery
                # clock measures that
                self._ctl.mark_recovery()
                mutated = True
        if mutated:
            self._recover()

    def _apply_churn_fault(self, fault: Fault) -> None:
        seed = self.fault_plan.seed if self.fault_plan else 0
        if fault.kind == "edit_factor":
            self._ctl.edit_factor_fault(fault, seed)
        else:  # remove_agent_burst / add_agent_burst: re-hosting
            self._ctl.mark_recovery()
        self._recover()

    # -- ladder side effects -------------------------------------------------

    def _apply_rung(self) -> None:
        """Engage/release the rung-2 fleet lever on transitions (rungs
        1 and 3 act at submission time)."""
        gold = max(t.priority for t in self.tiers)
        if self.ladder.clamp_silver and not self._pressure_on:
            self._pressure_on = True
            self.counters.inc("silver_clamps")
            self.fleet.set_deadline_pressure(
                self.ladder.silver_pressure, exempt_priority=gold,
            )
            send_slo("clamp.silver", {
                "pressure": self.ladder.silver_pressure,
                "exempt_priority": gold,
            })
        elif not self.ladder.clamp_silver and self._pressure_on:
            self._pressure_on = False
            self.fleet.set_deadline_pressure(1.0)

    # -- traffic -------------------------------------------------------------

    def _submit_due(self, tick: int) -> None:
        for job in self.jobs:
            if job.jid is not None or job.scored:
                continue
            if job.arrival_tick > tick:
                continue
            tier = self.tier_by_name[job.tier]
            if tier.name == "bronze" and self.ladder.shed_bronze:
                self.counters.inc("bronze_sheds")
                send_slo("shed.bronze", {"label": job.label})
                job.scored = True
                self.scores.append(JobScore(
                    label=job.label, tier=tier.name, tenant=job.tenant,
                    status="SHED", latency_s=None,
                    deadline_s=tier.deadline_s, hit=False, shed=True,
                ))
                continue
            placement = None
            if tier.name == "gold" and self.ladder.reroute_gold:
                placement = "emptiest"
                self.counters.inc("gold_reroutes")
                send_slo("reroute.gold", {"label": job.label})
            try:
                job.jid = self.fleet.submit(
                    job.dcop, job.algo, algo_params=job.algo_params,
                    seed=job.seed, tenant=job.tenant,
                    priority=tier.priority,
                    deadline_s=tier.deadline_s, label=job.label,
                    placement=placement, stream=self.stream,
                    spec=job.spec,
                )
                job.submitted_at = monotonic()
            except ServeError:
                # fleet admission control said no: a shed, scored
                job.scored = True
                self.scores.append(JobScore(
                    label=job.label, tier=tier.name, tenant=job.tenant,
                    status="SHED", latency_s=None,
                    deadline_s=tier.deadline_s, hit=False, shed=True,
                ))

    def _job_lossy(self, job: TwinJob) -> bool:
        """Did this job's progress stream drop events?  Read from the
        serving replica's ServeJob (the per-job twin of the per-tenant
        ``events_dropped_by_tenant`` surface)."""
        fj = self.fleet._jobs.get(job.jid)
        if fj is None:
            return False
        for h in self.fleet._handles.values():
            sj = h.service._jobs.get(job.jid)
            if sj is not None and sj.lossy_notified:
                return True
        return False

    def _score_done(self) -> int:
        """Score every newly-completed job; returns how many."""
        n = 0
        for job in self.jobs:
            if job.jid is None or job.scored:
                continue
            fj = self.fleet._jobs.get(job.jid)
            if fj is None or not fj.done.is_set():
                continue
            res = self.fleet.result(job.jid, timeout=5)
            tier = self.tier_by_name[job.tier]
            latency = monotonic() - job.submitted_at
            lossy = self._job_lossy(job)
            hit = (
                res.status == "FINISHED"
                and (tier.deadline_s is None
                     or latency <= tier.deadline_s)
            )
            if hit and lossy and tier.name == "gold":
                # a lossy gold stream is a broken contract even when
                # the result was on time (ISSUE 12 satellite)
                hit = False
                self.counters.inc("lossy_stream_misses")
            job.scored = True
            n += 1
            self.results[job.label] = res
            self.scores.append(JobScore(
                label=job.label, tier=tier.name, tenant=job.tenant,
                status=res.status, latency_s=latency,
                deadline_s=tier.deadline_s, hit=hit, lossy=lossy,
            ))
            self.ladder.record(tier.name, hit)
        return n

    # -- the run -------------------------------------------------------------

    def run(self, max_ticks: int = 5000) -> Dict[str, Any]:
        tmp = None
        jd = self.journal_dir
        if jd is None:
            # failover re-seats need per-lane checkpoints on disk
            tmp = tempfile.mkdtemp(prefix="twin_")
            jd = tmp
        self.fleet = SolveFleet(
            replicas=self.replicas, lanes=self.lanes,
            max_buckets=self.max_buckets,
            max_cycles=self.max_cycles, journal_dir=jd,
            checkpoint_every=1, fault_plan=self.fault_plan,
        )
        try:
            # prewarm every family signature so admission never pays a
            # cold compile inside the measured scenario, and pre-build
            # every instance spec — tick-driven replicas have no prep
            # pool, and an inline 4000-var instance compile landing on
            # the scheduler thread mid-trace would charge seconds to
            # whatever jobs are in flight (the threaded service builds
            # specs off-thread for exactly this reason)
            self.fleet.prewarm(
                [(j.dcop, j.algo, j.algo_params) for j in self.jobs],
                block=True,
            )
            from pydcop_tpu.batch.engine import BatchItem, adapter_for

            for job in self.jobs:
                if job.spec is None and job.algo in SUPPORTED_ALGOS:
                    job.spec = adapter_for(job.algo).build_spec(
                        BatchItem(job.dcop, job.algo,
                                  algo_params=job.algo_params,
                                  seed=job.seed, label=job.label)
                    )
            self._start_live()
            churn_events = (
                [e for e in self.live_scenario if not e.is_delay]
                if (self.live_scenario is not None
                    and self._ctl is not None) else []
            )
            churn_faults = (
                list(self.fault_plan.churn_faults())
                if (self.fault_plan is not None
                    and self._ctl is not None) else []
            )
            next_churn = 0
            settle = 0
            # after the last completion the ladder still needs its
            # hysteresis ticks to step back down — give it a bounded
            # settle window instead of freezing it mid-rung
            settle_budget = 3 * self.ladder.hold + 5
            for tick in range(int(max_ticks)):
                self._submit_due(tick)
                # one churn pressure fires per churn window: scenario
                # events first, then the plan's churn kinds
                if (
                    tick >= self.churn_start
                    and (tick - self.churn_start) % self.churn_every == 0
                ):
                    if next_churn < len(churn_events):
                        self._apply_churn_event(churn_events[next_churn])
                        next_churn += 1
                    elif churn_faults and (
                        churn_faults[0].cycle <= tick
                    ):
                        self._apply_churn_fault(churn_faults.pop(0))
                self.fleet.tick()
                self._score_done()
                # evaluate every tick, completions or not: windows
                # reset on every rung change, so a quiet drain period
                # is `hold` clean evaluations and the ladder releases
                # — sustained misses keep re-feeding the windows and
                # re-escalating
                self.ladder.evaluate()
                self._apply_rung()
                done_traffic = all(j.scored for j in self.jobs)
                churn_done = (
                    next_churn >= len(churn_events)
                    and not churn_faults
                )
                if done_traffic and churn_done:
                    settle += 1
                    if self.ladder.rung == 0 or settle > settle_budget:
                        break
            return self._scorecard()
        finally:
            try:
                self.fleet.stop(drain=False)
            finally:
                if tmp is not None:
                    shutil.rmtree(tmp, ignore_errors=True)

    def _scorecard(self) -> Dict[str, Any]:
        m = self.fleet.metrics()
        rtos = [
            r["rto_s"] for r in m["recoveries"]
            if r.get("rto_s") is not None
        ]
        card = scorecard(self.scores, self.tiers, self.counters,
                         rtos, self.recover_s)
        card["ladder"] = {
            "enabled": self.ladder.enabled,
            "final_rung": self.ladder.rung,
            "max_rung": self.ladder.max_rung_reached,
            "engaged": self.counters.counts["ladder_escalations"] > 0,
            "released": (
                self.counters.counts["ladder_deescalations"] > 0
            ),
        }
        card["fleet"] = {
            k: m["fleet"][k] for k in (
                "jobs_routed", "jobs_reseated", "replicas_down",
                "reseat_checkpoint_hits", "faults_injected",
                "jobs_shed",
            )
        }
        card["serve"] = {
            "events_dropped_by_tenant": self._dropped_by_tenant(),
            "faults_injected": sum(
                h.service.counters.counts["faults_injected"]
                for h in self.fleet._handles.values()
            ),
        }
        if self._ctl is not None:
            card["churn"] = self._ctl.counters.as_dict()
        auto = [j.config for j in self.jobs if j.config is not None]
        if auto:
            card["auto"] = {"configs": auto}
        return card

    def _dropped_by_tenant(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for h in self.fleet._handles.values():
            for t, n in (
                h.service.counters.events_dropped_by_tenant.items()
            ):
                out[t] = out.get(t, 0) + n
        return out
