"""City-scale digital twin: the everything-on-at-once scenario tier
(ISSUE 12).

One sustained, seeded, tick-driven scenario drives a replicated
:class:`~pydcop_tpu.serve.SolveFleet` under multi-tenant deadline-tier
traffic, live warm-repair churn, a combined chaos plan and optional
``--auto`` portfolio selection — scored by SLO attainment (per-tier
deadline attainment, p99, time-to-recover-cost, shed rate, RTO per
kill) and guarded by the deterministic degradation ladder
(docs/scenarios.rst).

Entry points: ``pydcop_tpu twin`` (commands/twin.py), the ``twin``
bench leg (``make bench-twin``) and the classes below.
"""
from pydcop_tpu.scenario.slo import (
    RUNGS,
    JobScore,
    SloLadder,
    TierSpec,
    default_tiers,
    scorecard,
)
from pydcop_tpu.scenario.twin import (
    TwinJob,
    TwinRunner,
    build_twin_traffic,
    default_chaos_plan,
    standalone_results,
)

__all__ = [
    "RUNGS",
    "JobScore",
    "SloLadder",
    "TierSpec",
    "default_tiers",
    "scorecard",
    "TwinJob",
    "TwinRunner",
    "build_twin_traffic",
    "default_chaos_plan",
    "standalone_results",
]
