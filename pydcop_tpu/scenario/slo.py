"""Deadline tiers and the SLO guardrail ladder (ISSUE 12 tentpole).

A city-scale twin is scored by *SLO attainment*, not iters/s: every
tenant job belongs to a **tier** (gold/silver/bronze → admission
priority + deadline), a job *hits* its SLO when it finishes within its
tier deadline (a gold job additionally forfeits the hit when its
progress stream dropped events — a lossy stream is a broken contract
even if the result was on time), and each tier has a rolling
**attainment floor**.

When a floor is breached the :class:`SloLadder` escalates one rung —
deterministically, in severity order, each rung a *real* lever on the
serving stack:

1. ``shed_bronze`` — bronze admissions are refused at the twin's front
   door (counted + ``slo.shed.bronze``), freeing lanes for paying
   tiers;
2. ``clamp_silver`` — the fleet's deadline-pressure knob tightens
   (:meth:`~pydcop_tpu.serve.fleet.SolveFleet.set_deadline_pressure`):
   silver/bronze deadline lanes see a fraction of their remaining
   budget in :func:`~pydcop_tpu.algorithms.base.
   clamp_chunk_to_deadline`, shrinking their chunks so buckets reach
   their boundaries — the only admission/completion points — sooner;
   gold (>= the exempt priority) runs full chunks;
3. ``reroute_gold`` — gold placements bypass warm-affinity routing and
   land on the emptiest *healthy* replica
   (``FleetRouter.place(prefer_emptiest=True)``): the shortest queue
   wins even at the price of a compile.

De-escalation is hysteretic: only after ``hold`` consecutive clean
evaluations (no tier below floor) does the ladder step DOWN one rung
(``slo.ladder.released``).  Escalation resets every tier's rolling
window, so a rung is judged on the completions it actually governed,
not on the backlog of misses that triggered it — this is what makes
"engaged-and-released" deterministic in the smoke test.

Every rung transition and breach is counted in
:class:`~pydcop_tpu.runtime.stats.SloCounters` and emitted as
``slo.*`` events (runtime/events.send_slo), forwarded to ws/SSE
clients by runtime/ui.py like every lifecycle family.
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from pydcop_tpu.runtime.events import send_slo
from pydcop_tpu.runtime.stats import SloCounters

#: ladder rungs in escalation order (index == rung level)
RUNGS = ("normal", "shed_bronze", "clamp_silver", "reroute_gold")


@dataclasses.dataclass(frozen=True)
class TierSpec:
    """One deadline tier: admission priority, latency budget and the
    rolling-attainment floor the ladder guards."""

    name: str
    priority: int
    deadline_s: Optional[float]
    floor: float
    share: float  # fraction of generated twin traffic

    def scaled(self, deadline_s: Optional[float]) -> "TierSpec":
        return dataclasses.replace(self, deadline_s=deadline_s)


def default_tiers(
    gold_deadline: float = 30.0,
    silver_deadline: float = 10.0,
    bronze_deadline: float = 20.0,
) -> Tuple[TierSpec, ...]:
    """The twin's default 3-tier ladder.  Floors: gold 99% (the
    acceptance bar), silver 90%, bronze 50% — bronze exists to be
    shed."""
    return (
        TierSpec("gold", priority=2, deadline_s=gold_deadline,
                 floor=0.99, share=0.25),
        TierSpec("silver", priority=1, deadline_s=silver_deadline,
                 floor=0.90, share=0.25),
        TierSpec("bronze", priority=0, deadline_s=bronze_deadline,
                 floor=0.50, share=0.50),
    )


@dataclasses.dataclass
class JobScore:
    """One completed (or shed) twin job, as the scorecard sees it."""

    label: str
    tier: str
    tenant: str
    status: str  # FINISHED / TIMEOUT / ERROR / SHED
    latency_s: Optional[float]
    deadline_s: Optional[float]
    hit: bool
    shed: bool = False
    lossy: bool = False


class SloLadder:
    """The deterministic degradation ladder over a set of tiers.

    ``record`` feeds one completion into its tier's rolling window;
    ``evaluate`` (called by the twin on a fixed cadence) breach-checks
    every tier with at least ``min_samples`` fresh completions and
    moves the rung at most one step per call.  ``enabled=False`` keeps
    the full accounting (windows, breaches, scorecard) but never moves
    the rung — the honest OFF arm of the ladder A/B in the twin bench.
    """

    def __init__(
        self,
        tiers: Tuple[TierSpec, ...],
        counters: Optional[SloCounters] = None,
        window: int = 12,
        min_samples: int = 4,
        hold: int = 6,
        silver_pressure: float = 0.5,
        enabled: bool = True,
    ):
        self.tiers: Dict[str, TierSpec] = {t.name: t for t in tiers}
        self.counters = counters if counters is not None else SloCounters()
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.hold = int(hold)
        #: rung-2 factor handed to SolveFleet.set_deadline_pressure
        self.silver_pressure = float(silver_pressure)
        self.enabled = bool(enabled)
        self.rung = 0
        self.max_rung_reached = 0
        self._clean_evals = 0
        self._windows: Dict[str, Deque[bool]] = {
            t.name: deque(maxlen=self.window) for t in tiers
        }

    # -- levers the twin consults -------------------------------------------

    @property
    def shed_bronze(self) -> bool:
        return self.rung >= 1

    @property
    def clamp_silver(self) -> bool:
        return self.rung >= 2

    @property
    def reroute_gold(self) -> bool:
        return self.rung >= 3

    @property
    def rung_name(self) -> str:
        return RUNGS[self.rung]

    # -- accounting ----------------------------------------------------------

    def record(self, tier: str, hit: bool) -> None:
        """Feed one scored completion into its tier's rolling window."""
        self._windows[tier].append(bool(hit))
        self.counters.inc("jobs_scored")
        self.counters.inc("deadline_hits" if hit else "deadline_misses")

    def attainment(self, tier: str) -> Optional[float]:
        """Rolling attainment of ``tier`` since the last rung change,
        or None below ``min_samples`` (a rung is judged only on
        completions it governed)."""
        w = self._windows[tier]
        if len(w) < self.min_samples:
            return None
        return sum(w) / len(w)

    def breached(self) -> List[Tuple[str, float]]:
        out = []
        for name, spec in self.tiers.items():
            att = self.attainment(name)
            if att is not None and att < spec.floor:
                out.append((name, att))
        return out

    # -- the ladder ----------------------------------------------------------

    def evaluate(self) -> int:
        """One breach check; moves the rung at most one step.  Returns
        the (possibly new) rung.  Escalation resets every window —
        the new rung starts with a clean slate; de-escalation needs
        ``hold`` consecutive clean evaluations (hysteresis)."""
        breaches = self.breached()
        for name, att in breaches:
            self.counters.inc("tier_breaches")
            send_slo("tier.breach", {
                "tier": name, "attainment": round(att, 4),
                "floor": self.tiers[name].floor,
            })
        if not self.enabled:
            return self.rung
        if breaches:
            self._clean_evals = 0
            if self.rung < len(RUNGS) - 1:
                self.rung += 1
                self.max_rung_reached = max(self.max_rung_reached,
                                            self.rung)
                self.counters.inc("ladder_escalations")
                send_slo("ladder.escalated", {
                    "rung": self.rung, "rung_name": self.rung_name,
                    "tiers": [n for n, _ in breaches],
                })
                self._reset_windows()
        else:
            self._clean_evals += 1
            if self.rung > 0 and self._clean_evals >= self.hold:
                self.rung -= 1
                self.counters.inc("ladder_deescalations")
                send_slo("ladder.released", {
                    "rung": self.rung, "rung_name": self.rung_name,
                })
                self._reset_windows()
                self._clean_evals = 0
        return self.rung

    def _reset_windows(self) -> None:
        for w in self._windows.values():
            w.clear()


def scorecard(scores: List[JobScore], tiers: Tuple[TierSpec, ...],
              counters: SloCounters, rto_s: List[float],
              recover_s: List[float]) -> Dict:
    """The twin's SLO scorecard: per-tier deadline attainment and
    latency percentiles, shed rate, time-to-recover-cost after each
    live mutation, and the RTO of every injected replica kill
    (docs/scenarios.rst "Scoring")."""
    per_tier: Dict[str, Dict] = {}
    for t in tiers:
        mine = [s for s in scores if s.tier == t.name]
        shed = [s for s in mine if s.shed]
        scored = [s for s in mine if not s.shed]
        lat = [s.latency_s for s in scored if s.latency_s is not None]
        entry = {
            "jobs": len(mine),
            "scored": len(scored),
            "shed": len(shed),
            "hits": sum(1 for s in scored if s.hit),
            "misses": sum(1 for s in scored if not s.hit),
            "lossy_streams": sum(1 for s in scored if s.lossy),
            "deadline_s": t.deadline_s,
            "floor": t.floor,
            "attainment": (
                round(sum(1 for s in scored if s.hit) / len(scored), 4)
                if scored else None
            ),
        }
        if lat:
            entry["p50_ms"] = round(
                float(np.percentile(lat, 50)) * 1e3, 1)
            entry["p99_ms"] = round(
                float(np.percentile(lat, 99)) * 1e3, 1)
        per_tier[t.name] = entry
    total = len(scores)
    shed_total = sum(1 for s in scores if s.shed)
    out = {
        "tiers": per_tier,
        "jobs": total,
        "shed_rate": round(shed_total / total, 4) if total else 0.0,
        "slo": counters.as_dict(),
        "rto_s": [round(r, 4) for r in rto_s],
        "rto_max_s": round(max(rto_s), 4) if rto_s else None,
        "recover_s": [round(r, 4) for r in recover_s],
        "recover_s_mean": (
            round(float(np.mean(recover_s)), 4) if recover_s else None
        ),
    }
    send_slo("scorecard", {
        "tiers": {
            n: {"attainment": e["attainment"], "p99_ms": e.get("p99_ms")}
            for n, e in per_tier.items()
        },
        "shed_rate": out["shed_rate"],
        "rto_max_s": out["rto_max_s"],
    })
    return out
