"""pydcop_tpu — a TPU-native (JAX/XLA) framework for Distributed Constraint
Optimization Problems.

A from-scratch re-design of the capabilities of pyDCOP (reference:
Orange-OpenSource/pyDcop fork, see /root/reference) built TPU-first:

* the problem model (domains, variables, constraints, agents) compiles into
  **padded tensor graphs** (`pydcop_tpu.ops.compile`),
* every synchronous-round algorithm (MaxSum, DSA, MGM, MGM-2, DBA, GDBA, ...)
  is a **jitted step function** run under ``lax.scan`` instead of an actor
  system exchanging messages over queues,
* inference on trees (DPOP) is expressed as level-scheduled batched
  ``join``/``projection`` tensor contractions,
* scale-out uses ``jax.sharding`` meshes + ``shard_map`` with XLA collectives
  instead of per-agent threads/HTTP (reference:
  pydcop/infrastructure/communication.py).

The public API mirrors the reference's layering (see SURVEY.md):
model (`pydcop_tpu.dcop`), computation graphs (`pydcop_tpu.graph`),
algorithms (`pydcop_tpu.algorithms`), distribution (`pydcop_tpu.distribution`),
runtime (`pydcop_tpu.runtime`), CLI (`pydcop_tpu.cli`).
"""

from pydcop_tpu.version import __version__

__all__ = ["__version__"]
