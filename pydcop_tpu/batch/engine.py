"""BatchEngine — vmapped multi-instance solving.

One instance's solve is a ``lax.scan`` of a pure cycle function over a
compiled tensor graph (algorithms/base.py); B same-shaped instances are
the SAME program vmapped over stacked ``[B, ...]`` arrays — one trace,
one XLA compile and one dispatch chain per bucket instead of B.

Bit-identity with the sequential path is a hard contract (pinned per
algorithm in tests/unit/test_batch_engine.py), which drives three
design points:

* **randomness is drawn at each instance's TRUE shape** and padded
  afterwards — ``jax.random`` draws depend on the requested shape, so
  initial values come from each instance's own solver and the DSA/A-DSA
  per-cycle uniforms are pre-drawn from the exact key stream
  ``SynchronousTensorSolver.run`` would use (same per-chunk key splits,
  via the shared :func:`algorithms.base.default_chunk` policy) and fed
  to the vmapped cycle as scan inputs — the same trick the fused pallas
  kernels use (ops/pallas_local_search.uniforms_for_keys);
* **padding is inert by routing**: padded variables get a single valid
  value and no factors; padded factors hold all-zero cost tensors and
  point every position at a reserved dummy variable, so their messages
  and table rows land on the dummy only; padded neighbor pairs connect
  the dummy to itself.  Real variables' reductions see exactly the
  arrays they would see unpadded;
* **convergence mirrors the harness**: per-instance chunk-boundary
  comparison with the same prime chunk size and two-stable-chunks rule;
  converged instances are frozen (their state no longer advances) and
  the bucket exits early once every instance converged or the cycle
  limit is reached.  Like the sequential harness, the test itself runs
  ON DEVICE (a [B] bool vector per chunk instead of two state pulls),
  each bucket compiles ONE fixed-shape runner — remainder chunks run
  cycle-masked through it (``select_frozen``) — and state buffers are
  donated where the backend aliases them.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from time import perf_counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.algorithms.base import (
    SolveResult,
    default_chunk,
    donation_supported,
    select_frozen,
)
from pydcop_tpu.batch.bucketing import (
    BucketPlan,
    InstanceDims,
    dims_of,
    plan_buckets,
)
from pydcop_tpu.batch.cache import (
    CompileCache,
    enable_persistent_cache,
    global_compile_cache,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.ops.compile import (
    ConstraintGraphTensors,
    FactorBucket,
    FactorGraphTensors,
    PAD_COST,
)
from pydcop_tpu.runtime.events import send_batch
from pydcop_tpu.runtime.stats import BatchCounters

#: algorithms with a vmapped batched engine; anything else is solved
#: sequentially by the fallback path (counted, never silently dropped)
SUPPORTED_ALGOS = ("maxsum", "mgm", "dsa", "adsa", "gdba")

#: default cycle ceiling for run-to-convergence, mirroring
#: SynchronousTensorSolver.run(max_cycles=2000)
DEFAULT_MAX_CYCLES = 2000


@dataclasses.dataclass
class BatchItem:
    """One solve request: a problem plus how to solve it."""

    dcop: DCOP
    algo: Union[str, AlgorithmDef]
    algo_params: Optional[Dict[str, Any]] = None
    seed: int = 0
    label: Optional[str] = None

    def algo_def(self) -> AlgorithmDef:
        if isinstance(self.algo, AlgorithmDef):
            return self.algo
        return AlgorithmDef.build_with_default_params(
            self.algo, self.algo_params or {}, mode=self.dcop.objective
        )


@dataclasses.dataclass
class _Spec:
    """One compiled instance inside a group."""

    item: BatchItem
    solver: Any
    tensors: Any  # the solver's (possibly noise-adjusted) tensor graph
    dims: InstanceDims


# ---------------------------------------------------------------------------
# padding + stacking
# ---------------------------------------------------------------------------


def pad_instance(tensors, target: InstanceDims) -> Dict[str, np.ndarray]:
    """Pad one compiled instance's arrays to the bucket target shape.

    Returns the per-instance array dict the vmapped cycle functions are
    rebuilt from (:func:`rebuild_tensors`).  Padding is inert by
    construction — see the module docstring."""
    V, D = tensors.n_vars, tensors.max_domain_size
    Vp, Dp = target.V, target.D
    dummy = Vp - 1  # only ever routed to when factors/pairs pad

    mask = np.zeros((Vp, Dp), np.float32)
    mask[:V, :D] = np.asarray(tensors.domain_mask)
    mask[V:, 0] = 1.0  # padded vars: one valid value
    unary = np.full((Vp, Dp), PAD_COST, np.float32)
    unary[:V, :D] = np.asarray(tensors.unary_costs)
    unary[V:, :] = PAD_COST
    unary[V:, 0] = 0.0
    arr: Dict[str, np.ndarray] = {"mask": mask, "unary": unary}

    ev_parts: List[np.ndarray] = []
    for i, (a, fp) in enumerate(zip(target.arities, target.F)):
        b = tensors.buckets[i]
        F = b.n_factors
        t_src = np.asarray(b.tensors)
        if t_src.dtype == np.int8:
            from pydcop_tpu.ops.precision import PrecisionError

            raise PrecisionError(
                "batched lanes do not stack int8 quantized tables; run "
                "the single-device engine for precision=int8 or use "
                "precision=bf16 for batched lanes"
            )
        # bf16-staged instances keep their storage tier through the
        # lane stack (ISSUE 19) — PAD_COST is exactly representable
        t = np.full((fp,) + (Dp,) * a, PAD_COST, t_src.dtype)
        t[(slice(0, F),) + (slice(0, D),) * a] = t_src
        # padded factors: zero costs routed at the dummy var — zero
        # messages / zero table rows, landing on the dummy only
        t[F:] = 0.0
        vi = np.full((fp, a), dummy, np.int32)
        vi[:F] = b.var_idx
        arr[f"bt{i}"] = t
        arr[f"bv{i}"] = vi
        ev_parts.append(vi.reshape(-1))
    arr["edge_var"] = (
        np.concatenate(ev_parts) if ev_parts else np.zeros(0, np.int32)
    )

    if target.graph_type == "constraints_hypergraph":
        src = np.asarray(tensors.neighbor_src)
        dst = np.asarray(tensors.neighbor_dst)
        M = src.shape[0]
        nsrc = np.full(target.M, dummy, np.int32)
        ndst = np.full(target.M, dummy, np.int32)
        nsrc[:M] = src
        ndst[:M] = dst
        arr["nsrc"] = nsrc
        arr["ndst"] = ndst
    return arr


def pad_vec(x: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad a 1-D per-variable vector to length ``n``."""
    x = np.asarray(x)
    if x.shape[0] == n:
        return x
    out = np.full((n,) + x.shape[1:], fill, dtype=x.dtype)
    out[: x.shape[0]] = x
    return out


def _stack(dicts: Sequence[Dict[str, np.ndarray]]) -> Dict[str, jnp.ndarray]:
    return {
        k: jnp.asarray(np.stack([d[k] for d in dicts]))
        for k in dicts[0]
    }


def rebuild_tensors(meta: "BucketMeta", arr: Dict[str, jnp.ndarray]):
    """Per-instance tensor-graph dataclass from (traced) arrays, inside
    jit/vmap — the shared ops cycle functions (maxsum_cycle,
    local_cost_tables, gains_and_best, ...) then run on it unchanged.
    Host-only fields (names, domain values) are placeholders of the
    right LENGTH: device math reads only lengths and arrays."""
    buckets: List[FactorBucket] = []
    off = 0
    for i, (a, f) in enumerate(zip(meta.arities, meta.F)):
        buckets.append(
            FactorBucket(
                arity=a,
                tensors=arr[f"bt{i}"],
                var_idx=arr[f"bv{i}"],
                factor_ids=np.arange(f, dtype=np.int32),
                edge_offset=off,
            )
        )
        off += f * a
    common = dict(
        var_names=[""] * meta.V,
        domain_values=[()] * meta.V,
        domain_sizes=np.ones(meta.V, np.int32),
        domain_mask=arr["mask"],
        unary_costs=arr["unary"],
        buckets=buckets,
        edge_var=arr["edge_var"],
        factor_names=[""] * sum(meta.F),
        sign=1.0,
        initial_values=np.zeros(meta.V, np.int32),
        has_initial=np.zeros(meta.V, bool),
    )
    if meta.graph_type == "factor_graph":
        return FactorGraphTensors(**common)
    return ConstraintGraphTensors(
        **common, neighbor_src=arr["nsrc"], neighbor_dst=arr["ndst"]
    )


@dataclasses.dataclass(frozen=True)
class BucketMeta:
    """Static shape info a bucket's traced code closes over."""

    graph_type: str
    V: int
    D: int
    arities: Tuple[int, ...]
    F: Tuple[int, ...]

    @classmethod
    def of(cls, target: InstanceDims) -> "BucketMeta":
        return cls(target.graph_type, target.V, target.D,
                   target.arities, target.F)


# ---------------------------------------------------------------------------
# per-chunk PRNG streams (drawn at TRUE shapes, padded afterwards)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n", "V", "Vp"))
def _dsa_chunk_uniforms(key, n: int, V: int, Vp: int):
    """(next_key, [n, Vp] uniforms) reproducing the harness stream for
    one chunk: ``key, sub = split(key); cycle_keys = split(sub, n)``,
    then DsaSolver.cycle's ``uniform(cycle_key, (V,))`` — padded columns
    get 1.0 (never activate; padded vars cannot move anyway)."""
    key2, sub = jax.random.split(key)
    ks = jax.random.split(sub, n)

    def one(k):
        u = jax.random.uniform(k, (V,))
        return jnp.concatenate([u, jnp.ones((Vp - V,), jnp.float32)])

    return key2, jax.vmap(one)(ks)


@partial(jax.jit, static_argnames=("n", "V", "Vp"))
def _adsa_chunk_uniforms(key, n: int, V: int, Vp: int):
    """(next_key, ([n, Vp] wake, [n, Vp] move)) matching ADsaSolver's
    per-cycle ``k_wake, k_move = split(cycle_key)`` draws exactly."""
    key2, sub = jax.random.split(key)
    ks = jax.random.split(sub, n)
    pad = jnp.ones((Vp - V,), jnp.float32)

    def one(k):
        kw, km = jax.random.split(k)
        w = jnp.concatenate([jax.random.uniform(kw, (V,)), pad])
        m = jnp.concatenate([jax.random.uniform(km, (V,)), pad])
        return w, m

    return key2, jax.vmap(one)(ks)


# ---------------------------------------------------------------------------
# per-algorithm adapters
# ---------------------------------------------------------------------------


class _AdapterBase:
    """What the engine needs to batch one algorithm family."""

    algo: str = ""
    uses_keys = False

    def build_spec(self, item: BatchItem) -> _Spec:
        raise NotImplementedError

    def extra_arrays(self, spec: _Spec, target: InstanceDims
                     ) -> Dict[str, np.ndarray]:
        return {}

    def initial_state(self, spec: _Spec, target: InstanceDims):
        """Per-instance padded initial state (np pytree), computed from
        the instance's own solver at its TRUE shape."""
        raise NotImplementedError

    def make_cycle(self, params: Dict[str, Any]):
        """cycle(tensors, arr, state, xs) -> state, traced per instance
        inside the vmapped runner."""
        raise NotImplementedError

    def chunk_xs(self, keys: List[Any], n: int,
                 specs: Sequence[_Spec], target: InstanceDims):
        """(advanced keys, stacked per-cycle scan inputs or None)."""
        return keys, None

    def chunk_xs_per_lane(self, keys: List[Any], ns: Sequence[int],
                          specs: Sequence[Optional[_Spec]],
                          target: InstanceDims, chunk: int):
        """Per-lane variant of :meth:`chunk_xs` for the continuous-
        batching scheduler (pydcop_tpu.serve): lane ``i`` draws its
        ``ns[i]`` cycles of randomness from ITS OWN key at ITS true
        shape, padded to the fixed ``chunk`` scan length.  Idle lanes
        (``specs[i] is None`` or ``ns[i] <= 0``) keep their key
        untouched — their stream must not advance while no job occupies
        the lane — and contribute inert all-ones rows."""
        return list(keys), None

    def values_np(self, state) -> np.ndarray:
        """[B, Vp] value indices from a batched state."""
        return np.asarray(state[0])

    def make_converged(self, params: Dict[str, Any]):
        """conv(tensors, prev_state_i, state_i) -> bool scalar, traced
        per instance inside the vmapped runner — the device twin of the
        sequential solver's chunk_converged, so the host reads one [B]
        bool vector per chunk instead of pulling both boundary states."""

        def conv(t, prev, cur):
            return jnp.all(prev[0] == cur[0])

        return conv


class _LocalSearchAdapter(_AdapterBase):
    """mgm / dsa / adsa — state = (x,)."""

    def __init__(self, algo: str):
        self.algo = algo
        self.uses_keys = algo in ("dsa", "adsa")

    def build_spec(self, item: BatchItem) -> _Spec:
        from pydcop_tpu.ops.compile import compile_constraint_graph

        mod = load_algorithm_module(self.algo)
        tensors = compile_constraint_graph(item.dcop)
        solver_cls = {
            "mgm": "MgmSolver", "dsa": "DsaSolver", "adsa": "ADsaSolver",
        }[self.algo]
        solver = getattr(mod, solver_cls)(
            item.dcop, tensors, item.algo_def(), seed=item.seed,
            use_packed=False,
        )
        return _Spec(item, solver, solver.tensors,
                     dims_of(solver.tensors, "constraints_hypergraph"))

    def initial_state(self, spec: _Spec, target: InstanceDims):
        (x,) = spec.solver.initial_state()
        return (pad_vec(np.asarray(x), target.V, 0).astype(np.int32),)

    def make_cycle(self, params: Dict[str, Any]):
        if self.algo == "mgm":
            from pydcop_tpu.algorithms.mgm import mgm_cycle

            def cycle(t, arr, st, xs):
                return (mgm_cycle(t, st[0]),)
        elif self.algo == "dsa":
            from pydcop_tpu.algorithms.dsa import dsa_cycle

            p = float(params.get("probability", 0.7))
            variant = params.get("variant", "B")

            def cycle(t, arr, st, xs):
                return (dsa_cycle(t, st[0], xs, p, variant),)
        else:  # adsa
            from pydcop_tpu.algorithms.adsa import adsa_cycle

            p = float(params.get("probability", 0.7))
            variant = params.get("variant", "B")
            act = float(params.get("activation", 0.5))

            def cycle(t, arr, st, xs):
                wake, move = xs
                return (adsa_cycle(t, st[0], wake, move, p, variant,
                                   act),)
        return cycle

    def chunk_xs(self, keys, n, specs, target):
        if not self.uses_keys:
            return keys, None
        draw = (_dsa_chunk_uniforms if self.algo == "dsa"
                else _adsa_chunk_uniforms)
        new_keys, parts = [], []
        for key, spec in zip(keys, specs):
            key2, u = draw(key, n=n, V=spec.dims.V, Vp=target.V)
            new_keys.append(key2)
            parts.append(u)
        if self.algo == "dsa":
            xs = jnp.stack(parts)  # [B, n, Vp]
        else:
            xs = (jnp.stack([p[0] for p in parts]),
                  jnp.stack([p[1] for p in parts]))
        return new_keys, xs

    def chunk_xs_per_lane(self, keys, ns, specs, target, chunk):
        if not self.uses_keys:
            return list(keys), None
        draw = (_dsa_chunk_uniforms if self.algo == "dsa"
                else _adsa_chunk_uniforms)
        Vp = target.V
        idle = jnp.ones((chunk, Vp), jnp.float32)

        def pad_rows(u, n):
            # same "never activate" 1.0 padding as _pad_xs, along the
            # lane's own cycle axis
            if n == chunk:
                return u
            return jnp.concatenate(
                [u, jnp.ones((chunk - n, Vp), jnp.float32)]
            )

        new_keys, parts = [], []
        for key, n, spec in zip(keys, ns, specs):
            n = int(n)
            if spec is None or n <= 0:
                new_keys.append(key)
                parts.append(idle if self.algo == "dsa" else (idle, idle))
                continue
            key2, u = draw(key, n=n, V=spec.dims.V, Vp=Vp)
            new_keys.append(key2)
            if self.algo == "dsa":
                parts.append(pad_rows(u, n))
            else:
                parts.append((pad_rows(u[0], n), pad_rows(u[1], n)))
        if self.algo == "dsa":
            xs = jnp.stack(parts)
        else:
            xs = (jnp.stack([p[0] for p in parts]),
                  jnp.stack([p[1] for p in parts]))
        return new_keys, xs


class _GdbaAdapter(_AdapterBase):
    """gdba — state = (x, per-bucket weights)."""

    algo = "gdba"

    def build_spec(self, item: BatchItem) -> _Spec:
        from pydcop_tpu.algorithms.gdba import GdbaSolver
        from pydcop_tpu.ops.compile import compile_constraint_graph

        tensors = compile_constraint_graph(item.dcop)
        solver = GdbaSolver(item.dcop, tensors, item.algo_def(),
                            seed=item.seed)
        return _Spec(item, solver, solver.tensors,
                     dims_of(solver.tensors, "constraints_hypergraph"))

    def extra_arrays(self, spec, target):
        out = {}
        for i, (a, fp) in enumerate(zip(target.arities, target.F)):
            fmin = pad_vec(np.asarray(spec.solver._fmin[i]), fp, 0.0)
            fmax = pad_vec(np.asarray(spec.solver._fmax[i]), fp, 0.0)
            out[f"fmin{i}"] = fmin.astype(np.float32)
            out[f"fmax{i}"] = fmax.astype(np.float32)
        return out

    def initial_state(self, spec, target):
        x, ws = spec.solver.initial_state()
        init = 0.0 if spec.solver.modifier == "A" else 1.0
        ws_p = []
        for i, (a, fp) in enumerate(zip(target.arities, target.F)):
            w = np.full((fp,) + (target.D,) * a, init, np.float32)
            true = np.asarray(ws[i])
            w[(slice(0, true.shape[0]),)
              + (slice(0, true.shape[1]),) * a] = true
            ws_p.append(w)
        return (pad_vec(np.asarray(x), target.V, 0).astype(np.int32),
                tuple(ws_p))

    def make_cycle(self, params):
        from pydcop_tpu.algorithms.gdba import gdba_cycle

        modifier = params.get("modifier", "A")
        violation = params.get("violation", "NZ")
        increase_mode = params.get("increase_mode", "E")

        def cycle(t, arr, st, xs):
            x, ws = st
            fmins = [arr[f"fmin{i}"] for i in range(len(t.buckets))]
            fmaxs = [arr[f"fmax{i}"] for i in range(len(t.buckets))]
            return gdba_cycle(t, x, ws, fmins, fmaxs, modifier,
                              violation, increase_mode)

        return cycle


class _MaxSumAdapter(_AdapterBase):
    """maxsum — state = (q, r, values)."""

    algo = "maxsum"

    def build_spec(self, item: BatchItem) -> _Spec:
        from pydcop_tpu.algorithms.maxsum import MaxSumSolver
        from pydcop_tpu.ops.compile import compile_factor_graph

        tensors = compile_factor_graph(item.dcop)
        # use_packed=False: the batch engine vmaps the generic cycle;
        # solver construction bakes the symmetry-breaking noise into
        # unary costs at the instance's TRUE shape (bit-identity)
        solver = MaxSumSolver(item.dcop, tensors, item.algo_def(),
                              seed=item.seed, use_packed=False)
        return _Spec(item, solver, solver.tensors,
                     dims_of(solver.tensors, "factor_graph"))

    def initial_state(self, spec, target):
        q, r, values = spec.solver.initial_state()
        Ep = sum(f * a for f, a in zip(target.F, target.arities))
        # messages start at zero, so padding them is trivial — but edge
        # offsets shift when factor counts pad, so build fresh zeros at
        # the padded layout rather than padding the true arrays
        zq = np.zeros((Ep, target.D),
                      np.dtype(spec.solver._msg_dtype))
        return (
            zq,
            zq.copy(),
            pad_vec(np.asarray(values), target.V, 0).astype(np.int32),
        )

    def make_cycle(self, params):
        from pydcop_tpu.ops.maxsum_kernels import maxsum_cycle
        from pydcop_tpu.ops.precision import (
            message_dtype,
            resolve_precision,
        )

        damping = params.get("damping")
        damping = 0.5 if damping is None else float(damping)
        # params are uniform across a bucket (grouping key), so one
        # message dtype serves every lane; f32 emits the pre-PR jaxpr
        msg_dtype = message_dtype(
            resolve_precision(params.get("precision"))
        )

        def cycle(t, arr, st, xs):
            q, r, _ = st
            q2, r2, _beliefs, values = maxsum_cycle(
                t, q, r, damping=damping, msg_dtype=msg_dtype
            )
            return (q2, r2, values)

        return cycle

    def values_np(self, state) -> np.ndarray:
        return np.asarray(state[2])

    def make_converged(self, params):
        from pydcop_tpu.algorithms.maxsum import messages_stable

        # the reference's approx_match message-stability coefficient —
        # params are uniform across a bucket (grouping key), so one
        # closure serves every instance; padded message rows are zeros
        # on both sides and always compare stable
        stability = float(params.get("stability", 0.1))

        def conv(t, prev, cur):
            return jnp.all(prev[2] == cur[2]) | jnp.all(
                messages_stable(prev[1], cur[1], stability)
            )

        return conv


def adapter_for(algo: str) -> _AdapterBase:
    """Batching adapter for one algorithm family — shared by the
    engine's static ``solve`` path and the continuous-batching
    scheduler (pydcop_tpu.serve)."""
    if algo in ("mgm", "dsa", "adsa"):
        return _LocalSearchAdapter(algo)
    if algo == "gdba":
        return _GdbaAdapter()
    if algo == "maxsum":
        return _MaxSumAdapter()
    raise KeyError(algo)


#: back-compat private alias
_adapter_for = adapter_for


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


def _params_key(params: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in (params or {}).items()))


def runner_cache_key(algo: str, pkey: Tuple, signature: Tuple,
                     chunk: int) -> Tuple:
    """Compile-cache key of one bucket runner.  ``signature`` is the
    bucket's shape signature (BucketPlan.signature /
    bucketing.bucket_signature) — the serve scheduler builds the SAME
    key for its workers, so a prewarmed runner is a cache hit at
    admission time."""
    return (algo, pkey) + tuple(signature) + ("chunk", chunk)


#: structural slack for the runner's baked scan/iota constants
RUNNER_CONST_SLACK_BYTES = 1 << 16


def bucket_runner_budget():
    """Declared per-cycle budget of the vmapped bucket runner (audited
    by the ``pydcop_tpu.analysis`` registry sweep): like the
    single-device harness — no collectives, no host callbacks, f32
    tier — but with a near-ZERO constant budget: every instance array
    arrives as a stacked ARGUMENT (that is what makes the runner
    reusable across bucket fills and serve lane churn), so a closure
    that starts baking instance data in would break cache reuse and
    blows this cap."""
    from pydcop_tpu.algorithms.base import harness_budget

    return harness_budget(RUNNER_CONST_SLACK_BYTES)


def build_bucket_runner(adapter: _AdapterBase, meta: BucketMeta,
                        params: Dict[str, Any], chunk: int):
    """ONE fixed-shape runner per bucket signature: always scans
    ``chunk`` cycles, freezing each lane's cycles past its OWN dynamic
    ``n_active[i]`` (remainder chunks — and, in the serve scheduler,
    lanes at different ages or under deadline pressure — reuse the same
    XLA executable instead of compiling their own shape) and
    already-converged instances per ``done_mask`` — both through the
    harness's shared :func:`algorithms.base.select_frozen` helper.

    Returns ``(new_state, flags)`` where ``flags`` is a ``[2, B]`` bool
    matrix read in ONE device→host pull per chunk: ``flags[0]`` is the
    per-instance convergence vector and ``flags[1]`` a per-lane
    finiteness flag over the state's float leaves — the cheap
    device-side NaN/Inf check that lets the serve quarantine isolate a
    poisoned lane at the chunk boundary it goes bad, instead of
    shipping garbage assignments or crashing a whole bucket.  (Pure
    integer states — mgm/dsa/adsa — are trivially finite; their
    poison detection happens host-side on the final cost.)  State
    buffers are donated where the backend aliases them."""
    cycle = adapter.make_cycle(params)
    conv_fn = adapter.make_converged(params)

    def run_chunk(arrays, state, xs, n_active, done_mask):
        def one(arr_i, st_i, xs_i, n_i):
            t = rebuild_tensors(meta, arr_i)
            active = jnp.arange(chunk) < n_i

            def body(st, sc):
                a, x_in = sc
                st2 = cycle(t, arr_i, st, x_in)
                return select_frozen(~a, st, st2), None

            st, _ = jax.lax.scan(
                body, st_i, (active, xs_i), length=chunk
            )
            fin = jnp.asarray(True)
            for leaf in jax.tree_util.tree_leaves(st):
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    fin = fin & jnp.all(jnp.isfinite(leaf))
            return st, conv_fn(t, st_i, st), fin

        new_state, conv, finite = jax.vmap(one)(
            arrays, state, xs, n_active
        )
        new_state = select_frozen(done_mask, state, new_state)
        # frozen lanes hold their (already vetted) state
        finite = jnp.where(done_mask, True, finite)
        return new_state, jnp.stack([conv, finite])

    donate = (1,) if donation_supported() else ()
    return jax.jit(run_chunk, donate_argnums=donate)


def _pad_xs(xs, chunk: int):
    """Pad per-cycle scan inputs from their true cycle count to the
    fixed ``chunk`` length on axis 1 ([B, n, ...] → [B, chunk, ...]).
    The padded rows feed only frozen (masked) cycles; 1.0 keeps the
    uniforms in their "never activate" convention anyway."""
    if xs is None:
        return None

    def pad(a):
        if a.shape[1] == chunk:
            return a
        widths = [(0, 0)] * a.ndim
        widths[1] = (0, chunk - a.shape[1])
        return jnp.pad(a, widths, constant_values=1.0)

    return jax.tree_util.tree_map(pad, xs)


class BatchEngine:
    """Shape-bucketed vmapped solver for sweeps and services.

    >>> # doctest-free sketch:
    >>> # eng = BatchEngine()
    >>> # results = eng.solve([BatchItem(dcop, "mgm", seed=s) ...],
    >>> #                     cycles=30)

    ``cache=None`` shares the process-wide compile cache; pass a fresh
    :class:`CompileCache` to isolate (the tests do).
    ``persistent_cache_dir`` additionally turns on the on-disk XLA
    compilation cache (level 2) for compile reuse ACROSS processes.
    """

    def __init__(
        self,
        max_padding_waste: float = 0.25,
        cache: Optional[CompileCache] = None,
        persistent_cache_dir: Optional[str] = None,
        counters: Optional[BatchCounters] = None,
    ):
        self.max_padding_waste = float(max_padding_waste)
        self.cache = cache if cache is not None else global_compile_cache()
        self.counters = counters if counters is not None else BatchCounters()
        self.persistent_cache_enabled = False
        if persistent_cache_dir:
            self.persistent_cache_enabled = enable_persistent_cache(
                persistent_cache_dir
            )

    def metrics(self) -> Dict[str, Any]:
        out = self.counters.as_dict()
        out["padding_waste"] = round(self.counters.padding_waste, 4)
        out["cache"] = self.cache.stats()
        return out

    # -- public API ---------------------------------------------------------

    def solve(
        self,
        items: Sequence[BatchItem],
        cycles: Optional[int] = None,
        timeout: Optional[float] = None,
        max_cycles: int = DEFAULT_MAX_CYCLES,
        on_lane_release: Optional[Callable[[int, int, Any], None]] = None,
    ) -> List[SolveResult]:
        """Solve every item; results align with ``items`` by index.

        ``cycles`` set → every instance runs exactly that many cycles
        (the sequential harness's fixed-cycle mode: no early freeze, so
        results stay bit-identical to ``solver.run(cycles=n)``).
        ``cycles=None`` → run-to-convergence with per-instance freeze
        masks and early bucket exit.

        ``on_lane_release(lane, stop_cycle, final_state)`` fires the
        moment one instance of a bucket converges and stops advancing —
        the per-lane slot-release hook the continuous-batching
        scheduler (pydcop_tpu.serve) consumes, instead of only the
        bucket-level ``[B]`` mask.  It also fires for a lane frozen
        ``ERROR`` by the chunk-boundary NaN/Inf check (counted
        ``lanes_nonfinite`` — the corresponding result's status tells
        the two apart).  ``final_state`` is the lane's state pytree
        sliced on device (no host pull unless the callback reads it).
        """
        t0 = perf_counter()
        self.counters.inc("instances_enqueued", len(items))
        results: List[Optional[SolveResult]] = [None] * len(items)

        groups: Dict[Tuple, List[int]] = {}
        for i, item in enumerate(items):
            algo_def = item.algo_def()
            groups.setdefault(
                (algo_def.algo, _params_key(algo_def.params)), []
            ).append(i)

        n_buckets = 0
        for (algo, pkey), idxs in sorted(groups.items()):
            if algo not in SUPPORTED_ALGOS:
                self._solve_fallback(items, idxs, results, cycles, timeout)
                continue
            adapter = _adapter_for(algo)
            specs = [adapter.build_spec(items[i]) for i in idxs]
            plans = plan_buckets(
                [s.dims for s in specs], self.max_padding_waste
            )
            for plan in plans:
                n_buckets += 1
                self.counters.inc("buckets_formed")
                self.counters.inc(
                    "stacked_cells", plan.target.cells * plan.batch_size
                )
                self.counters.inc(
                    "padded_cells",
                    plan.target.cells * plan.batch_size
                    - sum(specs[j].dims.cells for j in plan.indices),
                )
                send_batch("bucket.formed", {
                    "algo": algo,
                    "signature": plan.signature(),
                    "size": plan.batch_size,
                    "waste": plan.waste,
                })
                bucket_specs = [specs[j] for j in plan.indices]
                bucket_results = self._solve_bucket(
                    adapter, bucket_specs, plan, cycles, timeout,
                    max_cycles, on_lane_release,
                )
                for j, res in zip(plan.indices, bucket_results):
                    results[idxs[j]] = res
        self.counters.inc("instances_solved", len(items))
        send_batch("run.done", {
            "instances": len(items),
            "buckets": n_buckets,
            "wall": round(perf_counter() - t0, 3),
            "cache": self.cache.stats(),
        })
        return results  # type: ignore[return-value]

    # -- internals ----------------------------------------------------------

    def _solve_fallback(self, items, idxs, results, cycles, timeout):
        """Sequential per-instance path for algorithms without a
        batched engine — counted, never silent."""
        from pydcop_tpu.runtime.run import solve_result

        for i in idxs:
            item = items[i]
            self.counters.inc("fallback_sequential")
            results[i] = solve_result(
                item.dcop, item.algo_def(), cycles=cycles,
                timeout=timeout, seed=item.seed,
            )

    def _runner_key(self, adapter, plan: BucketPlan, pkey: Tuple,
                    chunk: int) -> Tuple:
        return runner_cache_key(adapter.algo, pkey, plan.signature(),
                                chunk)

    def _build_runner(self, adapter: _AdapterBase, meta: BucketMeta,
                      params: Dict[str, Any], chunk: int):
        return build_bucket_runner(adapter, meta, params, chunk)

    def _solve_bucket(
        self,
        adapter: _AdapterBase,
        specs: List[_Spec],
        plan: BucketPlan,
        cycles: Optional[int],
        timeout: Optional[float],
        max_cycles: int,
        on_lane_release: Optional[Callable] = None,
    ) -> List[SolveResult]:
        t0 = perf_counter()
        B = len(specs)
        target = plan.target
        meta = BucketMeta.of(target)
        algo_def = specs[0].item.algo_def()
        params = algo_def.params
        pkey = _params_key(params)

        arrays = _stack([
            {**pad_instance(s.tensors, target),
             **adapter.extra_arrays(s, target)}
            for s in specs
        ])
        state = jax.tree_util.tree_map(
            lambda *leaves: jnp.asarray(np.stack(leaves)),
            *[adapter.initial_state(s, target) for s in specs],
        )
        keys = [jax.random.PRNGKey(s.item.seed) for s in specs]

        target_cycles = cycles if cycles else None
        limit = target_cycles if target_cycles is not None else max_cycles
        chunk = default_chunk(target_cycles, False, False, timeout, limit)

        done = 0
        done_mask = np.zeros(B, bool)
        stable = np.zeros(B, np.int64)
        stop_cycle = np.zeros(B, np.int64)
        statuses = ["FINISHED"] * B
        first_chunk = True

        # ONE fixed-shape runner per bucket: remainder chunk sizes run
        # cycle-masked through the same executable (randomness is still
        # drawn at the true cycle count, so the key stream — and with it
        # bit-identity to the sequential harness — is unchanged)
        key = self._runner_key(adapter, plan, pkey, chunk)
        runner, hit = self.cache.get_or_build(
            key,
            lambda: self._build_runner(adapter, meta, params, chunk),
        )
        self.counters.inc("compile_hits" if hit else "compile_misses")

        while done < limit:
            n = min(chunk, limit - done)
            keys, xs = adapter.chunk_xs(keys, n, specs, target)
            state, flags = runner(
                arrays, state, _pad_xs(xs, chunk),
                jnp.full((B,), n, jnp.int32),
                jnp.asarray(done_mask),
            )
            done += n
            stop_cycle[~done_mask] = done

            if target_cycles is None:
                # per-instance convergence + finiteness ride the
                # runner's [2, B] bool matrix — the only device→host
                # read of the chunk; the first chunk's convergence
                # flags (vs the initial state) are skipped, mirroring
                # the sequential harness
                flags_np = np.asarray(flags)
                conv_np, finite_np = flags_np[0], flags_np[1]
                for i in range(B):
                    # a lane whose float state went NaN/Inf is frozen
                    # ERROR at this boundary: one bad instance never
                    # poisons its bucket-mates' cycles
                    if done_mask[i] or finite_np[i]:
                        continue
                    done_mask[i] = True
                    statuses[i] = "ERROR"
                    self.counters.inc("lanes_nonfinite")
                    send_batch("lane.nonfinite", {
                        "label": specs[i].item.label or i,
                        "lane": i,
                        "cycle": int(stop_cycle[i]),
                    })
                    if on_lane_release is not None:
                        on_lane_release(
                            i, int(stop_cycle[i]),
                            jax.tree_util.tree_map(
                                lambda l, j=i: l[j], state
                            ),
                        )
                if not first_chunk:
                    for i in range(B):
                        if done_mask[i]:
                            continue
                        stable[i] = stable[i] + 1 if conv_np[i] else 0
                        if stable[i] >= 2:
                            done_mask[i] = True
                            self.counters.inc("instances_converged")
                            send_batch("instance.converged", {
                                "label": specs[i].item.label or i,
                                "cycle": int(stop_cycle[i]),
                            })
                            if on_lane_release is not None:
                                on_lane_release(
                                    i, int(stop_cycle[i]),
                                    jax.tree_util.tree_map(
                                        lambda l, j=i: l[j], state
                                    ),
                                )
                if done_mask.all():
                    break
            first_chunk = False
            if timeout is not None and perf_counter() - t0 > timeout:
                for i in range(B):
                    if not done_mask[i]:
                        statuses[i] = "TIMEOUT"
                break

        wall = perf_counter() - t0
        out: List[SolveResult] = []
        values = adapter.values_np(state)
        from pydcop_tpu.algorithms import DEFAULT_INFINITY

        for i, spec in enumerate(specs):
            V = spec.dims.V
            assignment = spec.tensors.assignment_from_indices(
                values[i][:V]
            )
            violation, cost = spec.item.dcop.solution_cost(
                assignment, DEFAULT_INFINITY
            )
            n_cyc = int(stop_cycle[i])
            solver = spec.solver
            out.append(SolveResult(
                status=statuses[i],
                assignment=assignment,
                cost=cost,
                violation=violation,
                cycle=n_cyc,
                msg_count=solver.msgs_per_cycle * n_cyc,
                msg_size=(solver.msgs_per_cycle * n_cyc
                          * solver.msg_size_per_msg),
                time=wall,
            ))
        return out
