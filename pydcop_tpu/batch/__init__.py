"""Batched multi-instance solve engine.

One compile and one device dispatch chain per *bucket* of
similarly-shaped instances instead of one per instance: compiled
tensor graphs are grouped by shape signature (pydcop_tpu.batch.bucketing),
padded to a common shape under a bounded padding-waste policy, stacked
into ``[B, ...]`` arrays and advanced with ``jax.vmap``-ed cycle
functions (pydcop_tpu.batch.engine).  A two-level compile cache
(pydcop_tpu.batch.cache) — in-memory jitted-runner cache keyed by bucket
signature plus the persistent XLA compilation cache on disk — makes
repeated sweeps and long-running services compile each (bucket, algo)
pair exactly once.

The design follows PGMax's batched factor-graph inference (PAPERS.md,
arxiv 2202.04110 — pad to uniform shapes, vmap across instances) and
the batched GPU DCOP kernels of Fioretto et al. (arxiv 1608.05288);
see docs/performance.rst "Batched solving".
"""
from pydcop_tpu.batch.bucketing import (  # noqa: F401
    BucketPlan,
    InstanceDims,
    dims_of,
    plan_buckets,
)
from pydcop_tpu.batch.cache import (  # noqa: F401
    CompileCache,
    enable_persistent_cache,
    global_compile_cache,
)
from pydcop_tpu.batch.engine import (  # noqa: F401
    BatchEngine,
    BatchItem,
    SUPPORTED_ALGOS,
)
