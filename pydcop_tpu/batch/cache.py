"""Two-level compile cache for the batched solve engine.

Level 1 — in-memory: jitted bucket runners keyed by the full bucket
signature (algo, params, padded shapes, batch size, chunk length).  A
hit returns the SAME callable object, so jax performs no re-trace and
no compile; a long-running service that keeps seeing the same traffic
shapes compiles each (bucket, algo) pair exactly once per process.

Level 2 — persistent: the XLA compilation cache directory
(``jax_compilation_cache_dir``).  A fresh process re-traces but XLA
re-loads the compiled executable from disk instead of recompiling, so
repeated sweeps across CLI invocations skip the expensive half too.

Hit/miss counts are exported both as ``batch.compile.hit|miss`` events
(runtime/events.py) and via :meth:`CompileCache.stats` — the bench's
``compile_cache`` record and the tests' one-compile-per-bucket pin
read them.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional, Tuple

log = logging.getLogger(__name__)


class CompileCache:
    """In-memory level of the two-level compile cache."""

    def __init__(self):
        self._fns: Dict[Tuple, Any] = {}
        self.hits = 0
        self.misses = 0

    def get_or_build(self, key: Tuple, builder: Callable[[], Any]
                     ) -> Tuple[Any, bool]:
        """(runner, was_hit) for ``key``; ``builder`` runs on a miss."""
        from pydcop_tpu.runtime.events import send_batch

        if key in self._fns:
            self.hits += 1
            send_batch("compile.hit", {"key": _printable(key)})
            return self._fns[key], True
        self.misses += 1
        send_batch("compile.miss", {"key": _printable(key)})
        fn = builder()
        self._fns[key] = fn
        return fn, False

    def stats(self) -> Dict[str, int]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "entries": len(self._fns),
        }

    def clear(self) -> None:
        self._fns.clear()
        self.hits = 0
        self.misses = 0


#: process-wide default cache: engines share it unless given their own,
#: so a service constructing one BatchEngine per request still compiles
#: each (bucket, algo) pair once per process
_GLOBAL_CACHE = CompileCache()


def global_compile_cache() -> CompileCache:
    return _GLOBAL_CACHE


def enable_persistent_cache(
    cache_dir: str,
    min_entry_size_bytes: int = -1,
    min_compile_time_secs: float = 0.0,
) -> bool:
    """Point the persistent XLA compilation cache at ``cache_dir``
    (level 2 of the cache).  The floor options are lowered so even the
    small bucket programs of test-scale sweeps persist.  Returns False
    (with a warning) when this jax build lacks the options instead of
    failing the solve — the engine works without level 2, it just
    recompiles per process."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            min_entry_size_bytes,
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            min_compile_time_secs,
        )
        return True
    except Exception as e:  # unsupported jax build: degrade, don't fail
        log.warning("persistent compile cache unavailable: %s", e)
        return False


def _printable(key: Tuple) -> str:
    return "/".join(str(k) for k in key)
