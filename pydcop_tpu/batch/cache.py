"""Two-level compile cache for the batched solve engine.

Level 1 — in-memory: jitted bucket runners keyed by the full bucket
signature (algo, params, padded shapes, batch size, chunk length).  A
hit returns the SAME callable object, so jax performs no re-trace and
no compile; a long-running service that keeps seeing the same traffic
shapes compiles each (bucket, algo) pair exactly once per process.

Level 2 — persistent: the XLA compilation cache directory
(``jax_compilation_cache_dir``).  A fresh process re-traces but XLA
re-loads the compiled executable from disk instead of recompiling, so
repeated sweeps across CLI invocations skip the expensive half too.

Hit/miss counts are exported both as ``batch.compile.hit|miss`` events
(runtime/events.py) and via :meth:`CompileCache.stats` — the bench's
``compile_cache`` record and the tests' one-compile-per-bucket pin
read them.
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

log = logging.getLogger(__name__)


class CompileCache:
    """In-memory level of the two-level compile cache.

    Safely shareable across threads: the solve service's scheduler
    thread, its prewarm thread and direct callers all funnel through
    :meth:`get_or_build`, which holds a lock around the whole
    get-or-compile — two threads racing on the same key can neither
    duplicate a compile nor observe a half-built entry.  The lock is
    re-entrant so a builder that itself consults the cache does not
    deadlock."""

    def __init__(self, artifacts: Optional[Any] = None):
        self._fns: Dict[Tuple, Any] = {}
        self.hits = 0
        self.misses = 0
        self.prewarmed = 0
        self.artifact_hits = 0
        #: optional level 1.5 — a serve.artifacts.ArtifactStore of
        #: AOT-serialized executables shared across processes: a miss
        #: here first tries to LOAD the compiled runner (zero XLA
        #: compiles, counted as ``artifact_hits`` not ``misses``), and
        #: a cold build that produced a serializable runner is exported
        #: so the NEXT process skips the compile too
        self.artifacts = artifacts
        self._lock = threading.RLock()

    @property
    def exports_artifacts(self) -> bool:
        """True when builders should compile ahead-of-time so their
        runners can be serialized into the artifact store."""
        return self.artifacts is not None

    def get_or_build(self, key: Tuple, builder: Callable[[], Any],
                     prewarm: bool = False) -> Tuple[Any, bool]:
        """(runner, was_hit) for ``key``; ``builder`` runs on a miss."""
        from pydcop_tpu.runtime.events import send_batch

        with self._lock:
            if key in self._fns:
                self.hits += 1
                send_batch("compile.hit", {"key": _printable(key)})
                return self._fns[key], True
            if self.artifacts is not None:
                fn = self.artifacts.load(key)
                if fn is not None:
                    # a peer already paid this compile: zero XLA work
                    self.artifact_hits += 1
                    self._fns[key] = fn
                    send_batch("compile.artifact_hit",
                               {"key": _printable(key)})
                    return fn, True
            self.misses += 1
            if prewarm:
                self.prewarmed += 1
            send_batch(
                "compile.prewarm" if prewarm else "compile.miss",
                {"key": _printable(key)},
            )
            fn = builder()
            self._fns[key] = fn
            if self.artifacts is not None:
                self.artifacts.save(key, fn)
            return fn, False

    def prewarm(self, entries: Iterable[Tuple[Tuple, Callable[[], Any]]],
                block: bool = False) -> threading.Thread:
        """Compile bucket runners AHEAD of arrival, off the hot path.

        ``entries`` is a sequence of ``(key, builder)`` pairs — the
        same pairs :meth:`get_or_build` takes; builders that should
        truly pay the XLA compile here (not just build a lazy
        ``jax.jit`` wrapper) must execute their runner once at the real
        shapes, like serve's ``warm_bucket_runner``.  Runs on a daemon
        thread (``block=True`` joins it — tests and warm-before-open
        services); already-cached keys count as hits, fresh ones as
        prewarmed misses in :meth:`stats`.  A failing builder is logged
        and skipped, never fatal: prewarming is an optimization."""
        entries = list(entries)

        def work():
            for key, builder in entries:
                try:
                    self.get_or_build(key, builder, prewarm=True)
                except Exception as e:  # optimization, never fatal
                    log.warning("prewarm failed for %s: %s",
                                _printable(key), e)

        t = threading.Thread(target=work, name="compile-prewarm",
                             daemon=True)
        t.start()
        if block:
            t.join()
        return t

    def has(self, key: Tuple) -> bool:
        """True when ``key``'s runner is already resident — the warmth
        probe behind the fleet router's placement decisions: the SAME
        compile-cache keys the bucket workers resolve double as routing
        keys, so 'is this replica warm for this signature' is one dict
        lookup, not a guess (serve/router.py)."""
        with self._lock:
            return key in self._fns

    def key_strings(self) -> list:
        """Printable forms of every resident runner key — what a
        replica process streams to the fleet head so the router's
        warmth probe has ground truth without a round-trip."""
        with self._lock:
            return sorted(_printable(k) for k in self._fns)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {
                "hits": self.hits,
                "misses": self.misses,
                "entries": len(self._fns),
                "prewarmed": self.prewarmed,
            }
            if self.artifacts is not None:
                out["artifact_hits"] = self.artifact_hits
                out["artifacts"] = self.artifacts.stats()
            return out

    def clear(self) -> None:
        with self._lock:
            self._fns.clear()
            self.hits = 0
            self.misses = 0
            self.prewarmed = 0
            self.artifact_hits = 0


#: process-wide default cache: engines share it unless given their own,
#: so a service constructing one BatchEngine per request still compiles
#: each (bucket, algo) pair once per process
_GLOBAL_CACHE = CompileCache()


def global_compile_cache() -> CompileCache:
    return _GLOBAL_CACHE


def enable_persistent_cache(
    cache_dir: str,
    min_entry_size_bytes: int = -1,
    min_compile_time_secs: float = 0.0,
) -> bool:
    """Point the persistent XLA compilation cache at ``cache_dir``
    (level 2 of the cache).  The floor options are lowered so even the
    small bucket programs of test-scale sweeps persist.  Returns False
    (with a warning) when this jax build lacks the options instead of
    failing the solve — the engine works without level 2, it just
    recompiles per process."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            min_entry_size_bytes,
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            min_compile_time_secs,
        )
        return True
    except Exception as e:  # unsupported jax build: degrade, don't fail
        log.warning("persistent compile cache unavailable: %s", e)
        return False


def _printable(key: Tuple) -> str:
    return "/".join(str(k) for k in key)
