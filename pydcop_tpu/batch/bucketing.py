"""Shape signatures and the bounded padding-waste bucketing policy.

Two compiled instances can share one vmapped solve program only if
their padded array shapes match exactly.  Forcing every instance of a
sweep into ONE shape would make the smallest instance pay the largest
instance's cost tables, so buckets are formed greedily under a waste
bound: an instance joins the current bucket only while the bucket-wide
padding waste — the fraction of padded array cells that hold no real
data — stays at or below ``max_waste``.

Everything here is pure host-side arithmetic over
:class:`InstanceDims`; the unit tests pin the policy
(tests/unit/test_batch_engine.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple


@dataclass(frozen=True)
class InstanceDims:
    """Shape signature of one compiled instance.

    * ``graph_type``: computation-graph family (``factor_graph`` for
      the BP algorithms, ``constraints_hypergraph`` for local search) —
      instances never bucket across families;
    * ``D``: padded domain-size axis;
    * ``arities``: sorted tuple of constraint arities present (the
      arity *set* must match exactly — a missing arity bucket cannot be
      padded in);
    * ``V`` / ``F`` / ``M``: variable count, factor count per arity
      (aligned with ``arities``), and directed neighbor-pair count
      (0 for factor graphs).
    """

    graph_type: str
    D: int
    arities: Tuple[int, ...]
    V: int
    F: Tuple[int, ...]
    M: int

    @property
    def family_key(self) -> Tuple:
        """Instances may only share a bucket within one family key."""
        return (self.graph_type, self.arities)

    @property
    def cells(self) -> int:
        """Data cells of the dominant per-instance arrays — the unit
        the waste bound is measured in.  Counts the [V, D] mask+unary
        pair, the stacked cost tensors ([F_a, D^a] per arity), the
        message state of the BP family (2 edge arrays of [E, D]) and
        the neighbor-pair lists."""
        c = 2 * self.V * self.D
        edges = 0
        for a, f in zip(self.arities, self.F):
            c += f * self.D ** a
            edges += f * a
        if self.graph_type == "factor_graph":
            c += 2 * edges * self.D
        return c + 2 * self.M


@dataclass
class BucketPlan:
    """One planned bucket: which instances (by input index), the padded
    target shape they are stacked at, and the resulting waste."""

    indices: List[int]
    target: InstanceDims
    waste: float

    @property
    def batch_size(self) -> int:
        return len(self.indices)

    def signature(self) -> Tuple:
        """Hashable bucket signature — the shape part of the compile
        cache key (pydcop_tpu.batch.cache)."""
        return bucket_signature(self.target, self.batch_size)


def bucket_signature(target: InstanceDims, batch_size: int) -> Tuple:
    """Hashable (padded shape, lane count) signature of one bucket —
    the shape part of the compile-cache key, shared by
    :meth:`BucketPlan.signature` and the serve scheduler's workers so
    both resolve to the SAME cached runner."""
    return (target.graph_type, target.D, target.arities, target.V,
            target.F, target.M, batch_size)


class StructuredBatchingUnsupported(NotImplementedError):
    """Typed refusal: structured (table-free) buckets reached the lane
    stacker, which cannot pad them (ISSUE 19 satellite).  Subclasses
    NotImplementedError so pre-existing handlers keep working; the
    message text is pinned by tests — it names the fallback path."""


def dims_of(tensors, graph_type: str) -> InstanceDims:
    """Shape signature of a compiled tensor graph
    (ops.compile.GraphTensorsBase subclass)."""
    if getattr(tensors, "sbuckets", None):
        raise StructuredBatchingUnsupported(
            "batched lanes do not yet pad table-free (structured) buckets; "
            "solve structured instances on a dedicated lane"
        )
    arities = tuple(b.arity for b in tensors.buckets)
    fs = tuple(b.n_factors for b in tensors.buckets)
    m = 0
    src = getattr(tensors, "neighbor_src", None)
    if src is not None:
        m = int(src.shape[0])
    return InstanceDims(
        graph_type=graph_type,
        D=tensors.max_domain_size,
        arities=arities,
        V=tensors.n_vars,
        F=fs,
        M=m,
    )


def padded_target(members: Sequence[InstanceDims]) -> InstanceDims:
    """Element-wise max of the members' dims, plus one dummy variable
    slot when any member needs factor or neighbor-pair padding: padded
    factors and padded neighbor pairs are routed to the dummy variable
    so they cannot perturb any real variable's tables, messages or
    neighborhood reductions (see engine.pad_instance)."""
    first = members[0]
    v = max(m.V for m in members)
    fs = tuple(
        max(m.F[i] for m in members) for i in range(len(first.arities))
    )
    mm = max(m.M for m in members)
    d = max(m.D for m in members)
    needs_dummy = any(m.F != fs or m.M != mm for m in members)
    if needs_dummy:
        v += 1
    return InstanceDims(
        graph_type=first.graph_type,
        D=d,
        arities=first.arities,
        V=v,
        F=fs,
        M=mm,
    )


def bucket_waste(members: Sequence[InstanceDims]) -> float:
    """Padding waste of stacking ``members`` at their padded target:
    1 − (real cells) / (padded cells × B)."""
    target = padded_target(members)
    real = sum(m.cells for m in members)
    padded = target.cells * len(members)
    return 1.0 - real / padded if padded else 0.0


def plan_buckets(
    dims: Sequence[InstanceDims], max_waste: float = 0.25
) -> List[BucketPlan]:
    """Greedy shape-bucketing under the waste bound.

    Instances are first partitioned by family key (graph type + arity
    set — hard compatibility), then sorted by descending cell count
    (ties broken by input index, so the plan is deterministic) and
    packed sequentially: each instance joins the open bucket if the
    bucket's waste with it stays ≤ ``max_waste``, otherwise it opens a
    new bucket.  Sorting big-to-small means the open bucket's target
    rarely grows when a member joins, which keeps the greedy bound
    tight.
    """
    by_family = {}
    for i, dm in enumerate(dims):
        by_family.setdefault(dm.family_key, []).append(i)

    plans: List[BucketPlan] = []
    for fam in sorted(by_family):
        idxs = sorted(
            by_family[fam], key=lambda i: (-dims[i].cells, i)
        )
        open_idx: List[int] = []
        for i in idxs:
            if not open_idx:
                open_idx = [i]
                continue
            cand = [dims[j] for j in open_idx] + [dims[i]]
            if bucket_waste(cand) <= max_waste:
                open_idx.append(i)
            else:
                plans.append(_finalize(open_idx, dims))
                open_idx = [i]
        if open_idx:
            plans.append(_finalize(open_idx, dims))
    return plans


def _finalize(indices: List[int], dims: Sequence[InstanceDims]
              ) -> BucketPlan:
    members = [dims[i] for i in indices]
    return BucketPlan(
        indices=list(indices),
        target=padded_target(members),
        waste=round(bucket_waste(members), 6),
    )
