"""Self-labeling dataset harness for the portfolio cost model.

Unlike the pretrained DCOP cost model of arXiv:2112.04187, this
framework can generate labeled training data endlessly for free: the
``generators/`` families produce seeded instances, the config grid
enumerates the engine knobs, and every (instance, config) cell is one
ordinary in-process solve whose anytime cost curve yields the label —
**drift-normalized time-to-target-cost**, where the target is derived
from the best final cost any config reached on that instance (the
same self-relative discipline the bench's convergence legs use) and
normalization multiplies wall seconds by an adjacent calibration
probe rate so host/tunnel drift cancels (BENCHREF.md).

On-disk format (versioned, append-only, resumable):

* ``rows.jsonl`` — one JSON object per completed cell: the cell key,
  instance provenance (family/size/seed/params), the instance feature
  vector, the config dict, the measured wall/cycles/final-cost, a
  downsampled monotone best-cost-so-far curve ``[[t, cost], ...]``
  and the probe rate measured adjacent to the run.  Interrupted
  sweeps resume by cell key: existing rows are skipped, labels are
  (re)derived at READ time over each instance's full row group, so a
  partially-swept instance needs no rewriting;
* ``dataset.npz`` — the materialized training matrix
  (:func:`training_matrix`): X = instance features ++ config
  encoding, y = ``log1p(norm time-to-target)``, plus group ids and
  keys (written by :meth:`PortfolioDataset.write_npz`);
* ``meta.json`` — format version, grid, sweep parameters.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from time import perf_counter
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.portfolio.features import featurize_detail, pair_vector
from pydcop_tpu.portfolio.select import (
    PortfolioConfig,
    feasible_grid,
)

DATASET_VERSION = 1

#: label derivation defaults: a config "reaches the target" when its
#: running best cost enters the best-final + slack*span band; a config
#: that never reaches it is charged ``penalty`` x the group's slowest
#: observed time (reached time or full wall — rank-preserving, bounded)
TARGET_SLACK = 0.05
MISS_PENALTY = 3.0


# ---------------------------------------------------------------------------
# generator families
# ---------------------------------------------------------------------------


def _gc(size: int, seed: int, **kw) -> Any:
    from pydcop_tpu.generators import generate_graph_coloring

    kw.setdefault("n_colors", 3)
    kw.setdefault("n_edges", size * 2)
    return generate_graph_coloring(
        n_variables=size, soft=True, n_agents=1, seed=seed, **kw
    )


def _ising(size: int, seed: int, **kw) -> Any:
    from pydcop_tpu.generators import generate_ising

    dcop, _, _ = generate_ising(rows=max(3, size), seed=seed, **kw)
    return dcop


def _smallworld(size: int, seed: int, **kw) -> Any:
    from pydcop_tpu.generators import generate_smallworld

    return generate_smallworld(n_variables=size, seed=seed, **kw)


def _iot(size: int, seed: int, **kw) -> Any:
    from pydcop_tpu.generators import generate_iot

    return generate_iot(n_devices=size, seed=seed, **kw)


def _secp(size: int, seed: int, **kw) -> Any:
    from pydcop_tpu.generators import generate_secp

    return generate_secp(n_lights=size, seed=seed, **kw)


def _meetings(size: int, seed: int, **kw) -> Any:
    from pydcop_tpu.generators import generate_meeting_scheduling

    kw.setdefault("n_meetings", max(2, size // 2))
    return generate_meeting_scheduling(n_agents=size, seed=seed, **kw)


#: family name → builder(size, seed, **params); the sweep's single
#: "size" knob maps to each family's natural scale parameter
FAMILIES: Dict[str, Callable[..., Any]] = {
    "graphcoloring": _gc,
    "ising": _ising,
    "smallworld": _smallworld,
    "iot": _iot,
    "secp": _secp,
    "meetingscheduling": _meetings,
}


@dataclasses.dataclass(frozen=True)
class InstanceSpec:
    family: str
    size: int
    seed: int
    params: Tuple[Tuple[str, Any], ...] = ()

    def build(self):
        if self.family not in FAMILIES:
            raise ValueError(
                f"unknown generator family {self.family!r}; known: "
                f"{sorted(FAMILIES)}"
            )
        return FAMILIES[self.family](
            self.size, self.seed, **dict(self.params)
        )

    def key(self) -> str:
        tail = ""
        if self.params:
            blob = json.dumps(sorted(self.params), sort_keys=True)
            tail = "/" + hashlib.sha1(blob.encode()).hexdigest()[:8]
        return f"{self.family}/s{self.size}/seed{self.seed}{tail}"


@dataclasses.dataclass
class SweepSpec:
    """One declared sweep: instances x grid, with the per-cell solve
    budget.  ``cycles`` bounds every iterative solve (DPOP ignores
    it); ``timeout_s`` is the per-cell wall cap."""

    instances: Sequence[InstanceSpec]
    grid: Sequence[PortfolioConfig]
    cycles: int = 200
    timeout_s: Optional[float] = 30.0


def sweep_spec(
    families: Sequence[str],
    sizes: Sequence[int],
    seeds: Sequence[int],
    grid: Sequence[PortfolioConfig],
    cycles: int = 200,
    timeout_s: Optional[float] = 30.0,
) -> SweepSpec:
    """Cartesian helper: every family x size x seed."""
    instances = [
        InstanceSpec(f, s, sd)
        for f in families for s in sizes for sd in seeds
    ]
    return SweepSpec(instances, grid, cycles=cycles,
                     timeout_s=timeout_s)


def cell_key(inst: InstanceSpec, cfg: PortfolioConfig) -> str:
    return f"{inst.key()}::{cfg.key()}"


# ---------------------------------------------------------------------------
# calibration probe (local twin of bench.make_drift_probe — the bench
# script is not an importable package module)
# ---------------------------------------------------------------------------


def make_probe(dim: int = 256, chain: int = 40, repeat: int = 2):
    """Small fixed matmul chain timed on the default backend; returns
    a ``probe() -> rate`` callable (chain steps per second).  Wall
    seconds x this rate is dimensionless and cancels host drift —
    the same normalization discipline as the bench's primary."""
    import jax
    import jax.numpy as jnp

    x0 = jnp.eye(dim, dtype=jnp.float32) * 0.5 + 0.01

    @jax.jit
    def run(x):
        def body(c, _):
            c = c @ x0
            c = c / (1.0 + jnp.max(jnp.abs(c)))
            return c, ()

        c, _ = jax.lax.scan(body, x, None, length=chain)
        return c

    jax.block_until_ready(run(x0))  # pay the compile outside timing

    def probe() -> float:
        best = float("inf")
        for _ in range(max(1, repeat)):
            t0 = perf_counter()
            jax.block_until_ready(run(x0))
            best = min(best, perf_counter() - t0)
        return chain / best if best > 0 else 0.0

    return probe


# ---------------------------------------------------------------------------
# on-disk dataset
# ---------------------------------------------------------------------------


class PortfolioDataset:
    """Append-only JSONL + npz dataset directory."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.rows_path = os.path.join(path, "rows.jsonl")
        self.meta_path = os.path.join(path, "meta.json")
        self.npz_path = os.path.join(path, "dataset.npz")

    def write_meta(self, extra: Optional[Dict[str, Any]] = None) -> None:
        meta = {"version": DATASET_VERSION}
        meta.update(extra or {})
        with open(self.meta_path, "w", encoding="utf-8") as f:
            json.dump(meta, f, indent=2, sort_keys=True)

    def read_meta(self) -> Dict[str, Any]:
        if not os.path.exists(self.meta_path):
            return {}
        with open(self.meta_path, encoding="utf-8") as f:
            return json.load(f)

    def append(self, row: Dict[str, Any]) -> None:
        with open(self.rows_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")

    def rows(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        if not os.path.exists(self.rows_path):
            return out
        with open(self.rows_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # torn tail line of an interrupted sweep: the cell
                    # will re-run on resume, skipping is safe
                    continue
        return out

    def existing_keys(self) -> set:
        return {r["key"] for r in self.rows() if "key" in r}

    def write_npz(self, slack: float = TARGET_SLACK,
                  penalty: float = MISS_PENALTY) -> Dict[str, Any]:
        X, y, group_ids, keys = training_matrix(
            self.rows(), slack=slack, penalty=penalty
        )
        np.savez(
            self.npz_path,
            X=X, y=y,
            group_ids=np.asarray(group_ids),
            keys=np.asarray(keys),
        )
        return {"rows": int(X.shape[0]),
                "groups": len(set(group_ids))}


# ---------------------------------------------------------------------------
# labels
# ---------------------------------------------------------------------------


def _sign(objective: str) -> float:
    return -1.0 if objective == "max" else 1.0


def _downsample_curve(history, sign: float,
                      max_points: int = 64) -> List[List[float]]:
    """Monotone best-cost-so-far envelope of a metrics history, kept
    only where the best improves (plus the final point), capped."""
    curve: List[List[float]] = []
    best = float("inf")
    for h in history or []:
        if h.get("cost") is None:
            # anytime exact-search chunks before the first incumbent
            # have no assignment yet — nothing to envelope
            continue
        c = sign * float(h["cost"])
        if c < best:
            best = c
            curve.append([round(float(h["time"]), 6), best])
    if len(curve) > max_points:
        idx = np.linspace(0, len(curve) - 1, max_points).astype(int)
        curve = [curve[i] for i in idx]
    return curve


def time_to_target(row: Dict[str, Any], target: float) -> Optional[float]:
    """Earliest wall second the row's running best cost entered the
    target band, None if it never did.  Costs in the curve are already
    sign-adjusted (minimization convention)."""
    for t, c in row.get("curve") or []:
        if c <= target:
            return float(t)
    final = row.get("final_cost_signed")
    if final is not None and float(final) <= target:
        return float(row["wall_s"])
    return None


def training_matrix(
    rows: Iterable[Dict[str, Any]],
    slack: float = TARGET_SLACK,
    penalty: float = MISS_PENALTY,
) -> Tuple[np.ndarray, np.ndarray, List[str], List[str]]:
    """Derive (X, y, group ids, cell keys) from raw rows.

    Labels are group-relative (the target is defined by the best final
    cost ANY config reached on that instance), so they are computed
    here at read time — a resumed sweep that adds rows to an instance
    group changes every sibling's label consistently without
    rewriting the JSONL."""
    by_group: Dict[str, List[Dict[str, Any]]] = {}
    for r in rows:
        if r.get("status") not in ("FINISHED", "TIMEOUT"):
            continue
        by_group.setdefault(r["instance"], []).append(r)

    X_rows: List[np.ndarray] = []
    y_rows: List[float] = []
    group_ids: List[str] = []
    keys: List[str] = []
    for gid in sorted(by_group):
        group = by_group[gid]
        finals = np.asarray(
            [float(r["final_cost_signed"]) for r in group]
        )
        best = float(finals.min())
        span = float(finals.max()) - best
        target = best + slack * span + 1e-9
        hits = [time_to_target(r, target) for r in group]
        reach_base = max(
            (h if h is not None else float(r["wall_s"]))
            for h, r in zip(hits, group)
        )
        for r, hit in zip(group, hits):
            t = hit if hit is not None else penalty * reach_base
            norm = t * float(r.get("probe_rate") or 1.0)
            cfg = PortfolioConfig.from_dict(r["config"])
            X_rows.append(pair_vector(
                np.asarray(r["features"], dtype=np.float32), cfg
            ))
            y_rows.append(float(np.log1p(max(0.0, norm))))
            group_ids.append(gid)
            keys.append(r["key"])
    if not X_rows:
        return (np.zeros((0, 1), np.float32),
                np.zeros((0,), np.float32), [], [])
    return (np.stack(X_rows).astype(np.float32),
            np.asarray(y_rows, dtype=np.float32), group_ids, keys)


def split_holdout(
    X: np.ndarray, y: np.ndarray, group_ids: List[str],
    holdout: Sequence[str],
) -> Tuple[Tuple[np.ndarray, np.ndarray, List[str]],
           List[Tuple[np.ndarray, np.ndarray]]]:
    """((train X, train y, train group ids), held-out per-instance
    groups).  ``holdout`` names generator families (matched against
    the group id's family prefix) — held-out families never
    contribute a training row.  The train group ids feed the ranking
    loss of :func:`portfolio.model.train_model`."""
    hold = set(holdout)
    train_idx = []
    held: Dict[str, List[int]] = {}
    for i, gid in enumerate(group_ids):
        fam = gid.split("/", 1)[0]
        if fam in hold:
            held.setdefault(gid, []).append(i)
        else:
            train_idx.append(i)
    groups = [
        (X[idx], y[idx]) for _, idx in sorted(held.items())
    ]
    ti = np.asarray(train_idx, dtype=int)
    return (X[ti], y[ti], [group_ids[i] for i in train_idx]), groups


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------


def run_cell(
    dcop,
    cfg: PortfolioConfig,
    cycles: int,
    timeout_s: Optional[float],
    seed: int,
) -> Dict[str, Any]:
    """One labeled solve: run ``cfg`` on ``dcop`` with the metrics
    history collected, return the raw measurement fields of a row."""
    from pydcop_tpu.runtime.run import solve_result

    sign = _sign(dcop.objective)
    t0 = perf_counter()
    try:
        res = solve_result(
            dcop,
            cfg.algo,
            cycles=cycles if cfg.algo != "dpop" else None,
            timeout=timeout_s,
            algo_params=cfg.algo_params(),
            seed=seed,
            collect_cycles=True,
            **cfg.solve_kwargs(),
        )
        wall = perf_counter() - t0
        return {
            "status": res.status,
            "wall_s": round(wall, 6),
            "cycles": res.cycle,
            "final_cost": res.cost,
            "final_cost_signed": (
                sign * float(res.cost) if res.cost is not None
                else None
            ),
            "curve": _downsample_curve(res.history, sign) or (
                [[round(wall, 6), sign * float(res.cost)]]
                if res.cost is not None else []
            ),
        }
    except Exception as e:
        return {
            "status": "ERROR",
            "error": f"{type(e).__name__}: {e}",
            "wall_s": round(perf_counter() - t0, 6),
            "cycles": 0,
            "final_cost": None,
            "final_cost_signed": None,
            "curve": [],
        }


def run_sweep(
    spec: SweepSpec,
    out_dir: str,
    probe=None,
    resume: bool = True,
) -> Dict[str, Any]:
    """Execute (or resume) a sweep into ``out_dir``.

    Every completed cell appends one JSONL row immediately, so an
    interrupted sweep loses at most the in-flight cell; with
    ``resume=True`` (default) existing cell keys are skipped.  Emits
    ``portfolio.dataset.progress`` per cell and a final
    ``portfolio.dataset.done``; returns the summary dict."""
    from pydcop_tpu.runtime.events import send_portfolio

    ds = PortfolioDataset(out_dir)
    ds.write_meta({
        "grid": [c.as_dict() for c in spec.grid],
        "cycles": spec.cycles,
        "timeout_s": spec.timeout_s,
        "n_instances": len(list(spec.instances)),
    })
    existing = ds.existing_keys() if resume else set()
    if probe is None:
        probe = make_probe()
    done = skipped = errors = 0
    masked_total = 0
    t_start = perf_counter()
    for inst in spec.instances:
        dcop = inst.build()
        features, info = featurize_detail(dcop)
        feasible, masked = feasible_grid(spec.grid, info)
        masked_total += len(masked)
        for cfg in feasible:
            key = cell_key(inst, cfg)
            if key in existing:
                skipped += 1
                continue
            rate = probe()
            cell = run_cell(dcop, cfg, spec.cycles, spec.timeout_s,
                            inst.seed)
            row = {
                "v": DATASET_VERSION,
                "key": key,
                "instance": inst.key(),
                "family": inst.family,
                "size": inst.size,
                "seed": inst.seed,
                "objective": dcop.objective,
                "config": cfg.as_dict(),
                "features": [round(float(x), 6) for x in features],
                "probe_rate": round(rate, 3),
                **cell,
            }
            ds.append(row)
            done += 1
            if cell["status"] == "ERROR":
                errors += 1
            send_portfolio("dataset.progress", {
                "key": key,
                "status": cell["status"],
                "done": done,
                "skipped": skipped,
                "wall_s": cell["wall_s"],
            })
    summary = {
        "out_dir": out_dir,
        "cells_run": done,
        "cells_skipped": skipped,
        "cells_error": errors,
        "cells_masked": masked_total,
        "wall_s": round(perf_counter() - t_start, 3),
    }
    summary.update(ds.write_npz())
    send_portfolio("dataset.done", summary)
    return summary
