"""Auto-selection policy behind ``solve --auto``.

The selector scores a declared config grid for an incoming instance in
three strict stages:

1. **hard feasibility masks** — configs the backend cannot run are
   removed BEFORE any scoring: DPOP exact tiers whose planner byte
   estimate (:func:`ops.dpop_shard.estimate_sweep_bytes`, a pure shape
   pass) exceeds the budget on the available device count, sharded
   tiers without a mesh to shard over, and — for instances carrying
   table-free structured constraints (ISSUE 17) — the weighted
   local-search cells (no tensors to weight) plus every table-bound
   DPOP tier when a structured factor can never densify, so the
   selector lands on a table-free path.  Masking is advisory routing
   only — a user who *forces* an infeasible config still gets the
   typed refusal (:class:`ops.dpop_shard.UtilTableTooLarge`), never a
   silent downgrade;
2. **model argmin** — with a trained :class:`portfolio.model.CostModel`
   present, every feasible (instance, config) pair is scored and the
   predicted-fastest config wins;
3. **heuristic fallback** — with no model, selection degrades to the
   pre-existing hand heuristics (pinned by test): the PR 9
   byte-estimate routing decides exact-vs-iterative (DPOP when the
   planner says the sweep is cheap, MGM otherwise), DPOP's own
   ``engine="auto"`` tiering keeps routing inside the exact family,
   and ``overlap="default"`` leaves the sharded engines' PR 5
   cut-fraction auto-policy in charge of the collective path.

Every auto solve records the chosen config AND the predicted-vs-actual
gap in ``SolveResult.metrics()["portfolio"]`` so the model's honesty
is itself benchmarked (the bench's ``auto`` leg aggregates exactly
this section).
"""
from __future__ import annotations

import dataclasses
import logging
from time import perf_counter
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from pydcop_tpu.portfolio.features import (
    featurize_detail,
    pair_vector,
)

log = logging.getLogger("pydcop_tpu.portfolio")

#: per-device DPOP table budget the auto grid routes on (MiB) — grid
#: cells carry their own value; this is the default written into them
AUTO_DPOP_BUDGET_MB = 64.0

#: no-model fallback: run exact DPOP when the planner's byte estimate
#: for the whole sweep stays under this (the PR 9 routing signal)
HEURISTIC_EXACT_BYTES = 16 * 2**20
#: ... and the per-node refusal cap would not fire either
HEURISTIC_EXACT_ENTRIES = 10_000_000

#: feasibility ceiling of the frontier exact-search arm: branch and
#: bound is for the hard-instance regime (high induced width, SMALL
#: n) — past these shape limits the slab/bound tables stop paying and
#: the cell is masked like an over-budget DPOP tier
FRONTIER_MAX_VARS = 256
FRONTIER_MAX_DOMAIN = 32


@dataclasses.dataclass(frozen=True)
class PortfolioConfig:
    """One cell of the config grid — the fully-resolved knob set a
    solve executes under.  The same field schema is recorded by every
    solver in ``SolveResult.metrics()["config"]``
    (:func:`runtime.stats.resolved_config`), which is what lets the
    dataset harness and the gap audit share one label space."""

    algo: str
    engine: str = "harness"    # harness | auto | minibucket | sharded
    chunk: int = 0             # 0 = the harness's own chunk policy
    overlap: str = "default"   # default = PR 5 cut-fraction auto-policy
    boundary_threshold: float = 0.5
    budget_mb: float = 0.0     # 0 = engine caps (dpop only)
    i_bound: int = 0           # 0 = off (dpop only)
    precision: str = "f32"     # f32 | bf16 | int8 (ISSUE 19 tiers)

    def key(self) -> str:
        # the f32 default keeps the pre-tier key format so the label
        # space of existing datasets/benchmarks stays joinable
        base = (
            f"{self.algo}|{self.engine}|c{self.chunk}|{self.overlap}"
            f"|t{self.boundary_threshold:g}|b{self.budget_mb:g}"
            f"|i{self.i_bound}"
        )
        if self.precision != "f32":
            base += f"|p{self.precision}"
        return base

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PortfolioConfig":
        return cls(**{
            f.name: d[f.name]
            for f in dataclasses.fields(cls) if f.name in d
        })

    # -- execution mapping --------------------------------------------------

    def algo_params(self) -> Dict[str, Any]:
        """The ``-p``-style algo params this config resolves to."""
        if self.algo in ("syncbb", "ncbb"):
            # the exact-search family: only the frontier arm is in the
            # grid (the host loops are never a throughput pick)
            params = {"engine": self.engine}
            if self.i_bound > 0:
                params["i_bound"] = int(self.i_bound)
            if self.budget_mb > 0:
                params["budget_mb"] = float(self.budget_mb)
            return params
        if self.algo != "dpop":
            # the iterative engines take the tier as an algo param;
            # f32 stays parameterless so pre-tier resolved configs
            # (and their cache keys) are byte-identical
            if self.precision != "f32":
                return {"precision": self.precision}
            return {}
        params: Dict[str, Any] = {"engine": self.engine}
        if self.budget_mb > 0:
            params["budget_mb"] = float(self.budget_mb)
        if self.i_bound > 0:
            params["i_bound"] = int(self.i_bound)
        return params

    def solve_kwargs(self) -> Dict[str, Any]:
        """Extra :func:`runtime.run.solve_result` kwargs."""
        kw: Dict[str, Any] = {}
        if self.chunk > 0:
            kw["chunk"] = int(self.chunk)
        if self.overlap != "default":
            kw["shard_overlap"] = self.overlap
            kw["shard_boundary_threshold"] = float(
                self.boundary_threshold
            )
        return kw


#: the declared default grid ``solve --auto`` scores: the iterative
#: engines at both chunk policies (the chunk size changes the PRNG
#: stream AND the dispatch amortization) plus the exact family's
#: budgeted auto tier and the bounded mini-bucket fallback
DEFAULT_GRID: Tuple[PortfolioConfig, ...] = (
    PortfolioConfig("maxsum"),
    PortfolioConfig("maxsum", chunk=100),
    PortfolioConfig("mgm"),
    PortfolioConfig("mgm", chunk=100),
    PortfolioConfig("dsa"),
    PortfolioConfig("dsa", chunk=100),
    PortfolioConfig("adsa"),
    PortfolioConfig("gdba"),
    PortfolioConfig("dpop", engine="auto",
                    budget_mb=AUTO_DPOP_BUDGET_MB),
    PortfolioConfig("dpop", engine="minibucket", i_bound=2),
    # the anytime exact-search arm (ISSUE 15): proves optimality in
    # the high-width small-n regime where the DPOP tiers refuse and
    # local search stalls (docs/performance.rst "Frontier-batched
    # exact search")
    PortfolioConfig("syncbb", engine="frontier",
                    budget_mb=AUTO_DPOP_BUDGET_MB),
    # mixed-precision tiers (ISSUE 19): the cheap tiers ride the grid
    # behind hard feasibility masks — int8 only where the featurizer
    # proved it lossless (integer-valued small-range soft tables, no
    # hard/BIG entries), bf16 under the statistical-equivalence gate
    PortfolioConfig("maxsum", precision="bf16"),
    PortfolioConfig("mgm", precision="bf16"),
    PortfolioConfig("dsa", precision="bf16"),
    PortfolioConfig("maxsum", precision="int8"),
    PortfolioConfig("mgm", precision="int8"),
)

#: 3-cell grid for smokes/tests: one BP engine, one local-search
#: engine, one exact engine — enough to exercise every selector path
#: in under a minute on CPU
TINY_GRID: Tuple[PortfolioConfig, ...] = (
    PortfolioConfig("mgm"),
    PortfolioConfig("dsa", chunk=40),
    PortfolioConfig("dpop", engine="auto",
                    budget_mb=AUTO_DPOP_BUDGET_MB),
)

GRIDS: Dict[str, Tuple[PortfolioConfig, ...]] = {
    "default": DEFAULT_GRID,
    "tiny": TINY_GRID,
}


def _n_devices() -> int:
    try:
        import jax

        return len(jax.devices())
    except Exception:  # pragma: no cover - backend probing never fatal
        return 1


def feasible_grid(
    grid: Sequence[PortfolioConfig],
    info: Dict[str, Any],
    n_devices: Optional[int] = None,
) -> Tuple[List[PortfolioConfig], List[Tuple[PortfolioConfig, str]]]:
    """Split a grid into (feasible, masked-with-reason) for one
    instance, from the featurizer's raw structural numbers alone.

    The masks mirror the engines' own typed refusals so the selector
    never *picks* a config that would refuse — but they do not
    replace those refusals: forcing a masked config still raises the
    typed error."""
    n_dev = n_devices if n_devices is not None else _n_devices()
    feasible: List[PortfolioConfig] = []
    masked: List[Tuple[PortfolioConfig, str]] = []
    sweep_bytes = int(info.get("sweep_bytes", 0))
    max_entries = int(info.get("max_node_entries", 0))
    n_structured = int(info.get("n_structured", 0))
    structured_over_cap = bool(
        info.get("structured_over_table_cap", False)
    )
    for cfg in grid:
        prec = getattr(cfg, "precision", "f32")
        if prec != "f32":
            # mixed-precision masks (ISSUE 19): the cheap tiers are
            # only ROUTED where the engines declared them safe — a
            # forced pick still gets the engines' typed PrecisionError
            if cfg.algo in ("dpop", "syncbb", "ncbb"):
                masked.append((cfg, (
                    "the exact engines compute util tables in f32 only"
                )))
                continue
            if n_structured > 0:
                masked.append((cfg, (
                    "precision tiers re-encode cost tables; structured "
                    "(table-free) constraints keep their closed-form "
                    "f32 kernels"
                )))
                continue
        if prec == "int8":
            if cfg.algo in ("dba", "gdba"):
                masked.append((cfg, (
                    "per-factor weighting rescales cost tables every "
                    "cycle; frozen int8 codes cannot follow"
                )))
                continue
            if not bool(info.get("int8_safe", False)):
                # conservative by construction: unknown table contents
                # (or any hard/BIG entry, non-integer values, range
                # past the 253 code levels) keep int8 OFF the menu
                masked.append((cfg, (
                    "int8 is only safe on integer-valued cost tables "
                    "with range <= 253 and no hard/BIG entries"
                )))
                continue
        if cfg.algo in ("gdba", "dba") and n_structured > 0:
            # the weighted local-search family substitutes per-factor
            # cost tensors — structured factors have none and the
            # compile layer refuses rather than silently ignoring the
            # weights (ISSUE 17)
            masked.append((cfg, (
                "per-factor weighting is not supported on structured "
                "constraints"
            )))
            continue
        if cfg.algo in ("syncbb", "ncbb"):
            # the frontier exact-search arm: its regime is high width
            # at SMALL n — mask it out of bulk instances where the
            # search space dwarfs any node budget
            n_vars = int(info.get("n_vars", 0))
            max_dom = int(info.get("max_domain", 0))
            if n_vars > FRONTIER_MAX_VARS:
                masked.append((cfg, (
                    f"frontier exact search targets small-n hard "
                    f"instances (n={n_vars} > {FRONTIER_MAX_VARS})"
                )))
                continue
            if max_dom > FRONTIER_MAX_DOMAIN:
                masked.append((cfg, (
                    f"domain size {max_dom} exceeds the frontier "
                    f"slab cap {FRONTIER_MAX_DOMAIN}"
                )))
                continue
            feasible.append(cfg)
            continue
        if cfg.algo != "dpop":
            feasible.append(cfg)
            continue
        if structured_over_cap:
            # a structured constraint past the densify cap can NEVER
            # materialize a util table: only the table-free frontier
            # arm (which engine="auto" routes to, within its shape
            # limits) keeps this cell runnable — everything else ends
            # in a typed UtilTableTooLarge
            n_vars = int(info.get("n_vars", 0))
            max_dom = int(info.get("max_domain", 0))
            if (cfg.engine != "auto" or n_vars > FRONTIER_MAX_VARS
                    or max_dom > FRONTIER_MAX_DOMAIN):
                masked.append((cfg, (
                    "a structured constraint would densify past the "
                    "table cap; only the table-free engines can run it"
                )))
                continue
        if cfg.engine == "sharded" and n_dev < 2:
            masked.append((cfg, "sharded DPOP needs a multi-device "
                           "mesh"))
            continue
        if cfg.engine in ("auto", "sweep", "sharded"):
            budget = (
                int(cfg.budget_mb * 2**20) if cfg.budget_mb > 0
                else None
            )
            cap = (budget or 400 * 2**20) * max(1, n_dev)
            if sweep_bytes > cap and cfg.i_bound <= 0:
                masked.append((cfg, (
                    f"util tables ~{sweep_bytes / 2**20:.0f} MiB "
                    f"exceed the budget on {n_dev} device(s)"
                )))
                continue
            if max_entries > 100_000_000 * max(1, n_dev):
                masked.append((cfg, "widest joint table exceeds the "
                               "per-node entry cap"))
                continue
        feasible.append(cfg)
    return feasible, masked


def heuristic_config(info: Dict[str, Any]) -> PortfolioConfig:
    """The no-model fallback policy — the pre-portfolio hand
    heuristics, unchanged: exact DPOP when the PR 9 planner estimate
    says the whole sweep is cheap (its ``engine="auto"`` tiering keeps
    routing from there), the monotone MGM harness otherwise; in both
    cases ``overlap="default"`` leaves the PR 5 cut-fraction
    auto-policy in charge of any sharded collective."""
    if (info.get("sweep_bytes", 0) <= HEURISTIC_EXACT_BYTES
            and info.get("max_node_entries", 0)
            <= HEURISTIC_EXACT_ENTRIES):
        return PortfolioConfig("dpop", engine="auto",
                               budget_mb=AUTO_DPOP_BUDGET_MB)
    if (info.get("n_vars", 10**9) <= FRONTIER_MAX_VARS // 4
            and info.get("max_domain", 10**9) <= FRONTIER_MAX_DOMAIN):
        # the hard-instance regime DPOP just refused: high induced
        # width at small n — exactly where the anytime frontier
        # search proves optima local search never reaches
        return PortfolioConfig("syncbb", engine="frontier",
                               budget_mb=AUTO_DPOP_BUDGET_MB)
    return PortfolioConfig("mgm")


@dataclasses.dataclass
class Selection:
    """Outcome of one grid scoring."""

    config: PortfolioConfig
    fallback: bool
    predicted_label: Optional[float]      # model output (log space)
    predicted_norm_time: Optional[float]  # expm1(label), probe units
    predicted_s: Optional[float]          # / calibration probe rate
    scores: Dict[str, float]
    masked: List[Tuple[str, str]]
    features: np.ndarray
    info: Dict[str, Any]

    def as_event(self) -> Dict[str, Any]:
        return {
            "config": self.config.as_dict(),
            "fallback": self.fallback,
            "predicted_norm_time": self.predicted_norm_time,
            "n_feasible": len(self.scores) or None,
            "n_masked": len(self.masked),
        }


def select_config(
    dcop,
    grid: Optional[Sequence[PortfolioConfig]] = None,
    model=None,
    features: Optional[np.ndarray] = None,
    info: Optional[Dict[str, Any]] = None,
    n_devices: Optional[int] = None,
) -> Selection:
    """Score the feasible grid for one instance and pick the argmin.

    ``model`` is a loaded :class:`portfolio.model.CostModel` or None
    (→ heuristic fallback).  ``features``/``info`` can be passed when
    the caller already featurized (the dataset harness and the serve
    prewarm path reuse one featurization across calls)."""
    from pydcop_tpu.runtime.events import send_portfolio

    grid = tuple(grid) if grid is not None else DEFAULT_GRID
    if features is None or info is None:
        features, info = featurize_detail(dcop)
    feasible, masked = feasible_grid(grid, info, n_devices=n_devices)
    masked_keys = [(c.key(), reason) for c, reason in masked]
    if not feasible:
        # every cell masked: fall back to the heuristic pick rather
        # than refusing a solvable instance (MGM is always runnable)
        cfg = heuristic_config(info)
        if cfg.algo == "dpop":
            cfg = PortfolioConfig("mgm")
        sel = Selection(cfg, True, None, None, None, {}, masked_keys,
                        features, info)
        send_portfolio("config.selected", sel.as_event())
        return sel

    scores: Dict[str, float] = {}
    if model is not None:
        X = np.stack([pair_vector(features, c) for c in feasible])
        pred = np.asarray(model.predict(X), dtype=np.float64)
        scores = {
            c.key(): round(float(p), 6)
            for c, p in zip(feasible, pred)
        }
        best = int(np.argmin(pred))
        label = float(pred[best])
        norm_time = float(np.expm1(label))
        probe_rate = float(model.meta.get("probe_rate") or 0.0)
        sel = Selection(
            feasible[best], False, label, norm_time,
            (norm_time / probe_rate) if probe_rate > 0 else None,
            scores, masked_keys, features, info,
        )
    else:
        cfg = heuristic_config(info)
        if cfg not in feasible:
            cfg = next(
                (c for c in feasible if c.algo != "dpop"), feasible[0]
            )
        sel = Selection(cfg, True, None, None, None, {}, masked_keys,
                        features, info)
    send_portfolio("config.selected", sel.as_event())
    return sel


def load_model(model: Union[None, str, Any]):
    """Normalize the ``model`` argument: None, a path (loaded, with a
    ``portfolio.model.loaded`` event), or an already-loaded
    :class:`CostModel` (returned as-is).  A path that fails to load
    degrades to the heuristic fallback with a warning — an auto solve
    must never die on a stale model file."""
    from pydcop_tpu.portfolio.model import CostModel
    from pydcop_tpu.runtime.events import send_portfolio

    if model is None or isinstance(model, CostModel):
        return model
    try:
        loaded = CostModel.load(model)
        send_portfolio("model.loaded", {
            "path": str(model),
            "n_in": loaded.n_in,
            "meta": {k: v for k, v in loaded.meta.items()
                     if k in ("version", "probe_rate", "trained_rows",
                              "holdout")},
        })
        return loaded
    except Exception as e:
        log.warning(
            "portfolio model %r failed to load (%s); degrading to the "
            "heuristic fallback", model, e,
        )
        return None


def solve_auto(
    dcop,
    model: Union[None, str, Any] = None,
    grid: Optional[Sequence[PortfolioConfig]] = None,
    seed: int = 0,
    timeout: Optional[float] = None,
    cycles: Optional[int] = None,
    collect_cycles: bool = False,
    n_devices: Optional[int] = None,
):
    """``solve --auto``: featurize → mask → score → run the winner.

    Returns the winner's :class:`SolveResult` with
    ``metrics()["portfolio"]`` carrying the chosen config, the model
    provenance and the predicted-vs-actual audit: ``predicted_*`` is
    the model's drift-normalized time-to-target estimate,
    ``actual_solve_s`` the measured wall of this solve (normalized
    with the model's calibration probe rate when available), and the
    gap between them is the honesty number the bench tracks.  With no
    model the prediction fields are None and ``fallback`` is True —
    the selection is exactly the pre-portfolio heuristics."""
    from pydcop_tpu.runtime.events import send_portfolio
    from pydcop_tpu.runtime.run import solve_result

    model_path = model if isinstance(model, str) else None
    loaded = load_model(model)
    sel = select_config(dcop, grid=grid, model=loaded,
                        n_devices=n_devices)
    cfg = sel.config
    t0 = perf_counter()
    res = solve_result(
        dcop,
        cfg.algo,
        timeout=timeout,
        cycles=cycles,
        algo_params=cfg.algo_params(),
        seed=seed,
        collect_cycles=collect_cycles,
        **cfg.solve_kwargs(),
    )
    wall = perf_counter() - t0
    probe_rate = (
        float(loaded.meta.get("probe_rate") or 0.0) if loaded else 0.0
    )
    portfolio: Dict[str, Any] = {
        "config": cfg.as_dict(),
        "fallback": sel.fallback,
        "model": model_path or ("<in-memory>" if loaded else None),
        "predicted_norm_time": sel.predicted_norm_time,
        "predicted_time_to_target_s": sel.predicted_s,
        "actual_solve_s": round(wall, 6),
        "actual_norm_time": (
            round(wall * probe_rate, 6) if probe_rate > 0 else None
        ),
        "n_feasible": len(sel.scores) if sel.scores else None,
        "n_masked": len(sel.masked),
        "masked": sel.masked[:8],
    }
    if sel.predicted_s is not None:
        portfolio["gap_s"] = round(wall - sel.predicted_s, 6)
        if sel.predicted_s > 0:
            portfolio["gap_ratio"] = round(wall / sel.predicted_s, 4)
    res.portfolio = portfolio
    send_portfolio("solve.done", {
        "config": cfg.as_dict(),
        "fallback": sel.fallback,
        "status": res.status,
        "actual_solve_s": portfolio["actual_solve_s"],
        "predicted_time_to_target_s": sel.predicted_s,
    })
    return res


def prewarm_predicted(
    service,
    dcops: Sequence[Any],
    model: Union[None, str, Any] = None,
    grid: Optional[Sequence[PortfolioConfig]] = None,
    block: bool = False,
) -> List[PortfolioConfig]:
    """Serve-layer hook: pick the predicted config for each expected
    instance and prewarm the service's bucket runners for the
    batch-eligible ones — the compile the admission path would
    otherwise pay cold happens ahead of arrival, keyed by the SAME
    bucket signatures the scheduler derives later.  Returns the chosen
    configs (one per dcop, order preserved)."""
    from pydcop_tpu.batch.engine import SUPPORTED_ALGOS

    loaded = load_model(model)
    chosen: List[PortfolioConfig] = []
    items = []
    for dcop in dcops:
        sel = select_config(dcop, grid=grid, model=loaded)
        chosen.append(sel.config)
        if sel.config.algo in SUPPORTED_ALGOS:
            items.append(
                (dcop, sel.config.algo, sel.config.algo_params())
            )
    if items:
        service.prewarm(items, block=block)
    return chosen
