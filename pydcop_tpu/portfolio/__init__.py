"""Learned algorithm-portfolio layer (ROADMAP item 4, arXiv:2112.04187).

The framework exposes ~8 engines x {single-chip, sharded, batched, warm}
x {overlap modes, chunk sizes, boundary thresholds, DPOP budgets /
i-bounds}; every choice used to be a hand-set CLI flag or a hand-tuned
heuristic.  This package replaces that with a small learned performance
model and an auto-selection policy behind ``solve --auto``:

* :mod:`pydcop_tpu.portfolio.features` — cheap structural featurizer:
  one fixed-length vector per instance, computed WITHOUT building any
  cost/util table (counts, domains, arity histogram, pseudo-tree
  separator profile, boundary cut fractions, planner byte estimates);
* :mod:`pydcop_tpu.portfolio.dataset` — seeded self-labeling sweep:
  ``generators/`` families x a declared config grid, labeled with
  drift-normalized time-to-target-cost, appended to a versioned
  resumable on-disk dataset (JSONL + npz);
* :mod:`pydcop_tpu.portfolio.model` — pure-JAX featurized MLP with a
  hand-rolled Adam (no new deps), save/load of weights +
  normalization stats, held-out-family evaluation (rank correlation +
  top-1 regret, not just MSE);
* :mod:`pydcop_tpu.portfolio.select` — feasibility-masked grid scoring
  behind ``solve --auto``: hard masks first (memory estimates, backend
  capabilities — typed refusals stay typed), model argmin second, the
  pre-existing hand heuristics third (the no-model fallback), with the
  predicted-vs-actual gap recorded in
  ``SolveResult.metrics()["portfolio"]`` so the model's honesty is
  itself benchmarked.

See docs/portfolio.rst for the dataset format, the feature list, the
training/eval recipe and the ``--auto`` semantics.
"""
from pydcop_tpu.portfolio.features import (
    FEATURE_NAMES,
    N_FEATURES,
    encode_config,
    featurize,
    featurize_detail,
)
from pydcop_tpu.portfolio.select import (
    DEFAULT_GRID,
    TINY_GRID,
    PortfolioConfig,
    Selection,
    feasible_grid,
    heuristic_config,
    select_config,
    solve_auto,
)

__all__ = [
    "FEATURE_NAMES",
    "N_FEATURES",
    "featurize",
    "featurize_detail",
    "encode_config",
    "PortfolioConfig",
    "Selection",
    "DEFAULT_GRID",
    "TINY_GRID",
    "feasible_grid",
    "heuristic_config",
    "select_config",
    "solve_auto",
]
