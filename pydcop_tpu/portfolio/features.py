"""Structural instance featurizer for the learned portfolio.

One fixed-length feature vector per DCOP instance, computed WITHOUT
building any cost or util table: everything here is derived from the
problem's *shape* — variable/factor counts, domain sizes, the arity
histogram, degree statistics, the pseudo-tree's induced width and
separator-size profile (:meth:`graph.pseudotree.separators`), the
reference-partition boundary/cut fractions
(:func:`parallel.boundary.analyze_boundary` over an 8-shard locality
partition) and the DPOP planner's byte estimates
(:func:`ops.dpop_shard.estimate_sweep_bytes`, itself a pure shape
pass).  That makes featurization cheap enough to run inline in
``solve --auto`` on a 100k-variable instance (pinned by test) while
still carrying the signals every routing heuristic in the framework
has historically keyed on.

Table-free constraints (ISSUE 17) add three structure signals — the
structured-constraint fraction and the log dense-table byte totals
(overall and structured-only).  Both byte numbers are ANALYTIC
(:meth:`dcop.structured.StructuredConstraint.dense_bytes` and a domain
product for dense factors): a 100-arity window contributes its 4^100
hypothetical bytes to the feature without any table ever existing.

Config encoding lives here too (:func:`encode_config`): the model
scores (instance, config) PAIRS, so a candidate config is embedded as
a small fixed vector (algo/engine/overlap one-hots + the numeric
knobs) and concatenated with the instance features.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

#: reference shard count for the boundary/cut features: the partition
#: quality signal must be comparable across instances, so it is always
#: measured against the same hypothetical mesh width (the boundary
#: analysis is a pure host shape pass — no device mesh is built)
REFERENCE_SHARDS = 8

FEATURE_NAMES: Tuple[str, ...] = (
    "log1p_n_vars",
    "log1p_n_factors",
    "log1p_n_agents",
    "factor_var_ratio",
    "dom_min",
    "dom_mean",
    "dom_max",
    "arity1_frac",
    "arity2_frac",
    "arity3p_frac",
    "max_arity",
    "deg_mean",
    "log1p_deg_max",
    "tree_depth_frac",
    "induced_width",
    "sep_mean",
    "sep_p90",
    "log10_sweep_bytes",
    "log10_max_node_entries",
    "cut_fraction_8",
    "boundary_fraction_8",
    "objective_is_max",
    "structured_frac",
    "log10_dense_table_bytes",
    "log10_structured_dense_bytes",
)

N_FEATURES = len(FEATURE_NAMES)

#: config-encoding vocabularies (one-hot blocks of
#: :func:`encode_config`); "harness" is the chunked-scan engine every
#: round-based solver runs through, the rest are the DPOP engine tiers
#: plus "frontier" — the anytime exact-search arm (ISSUE 15) the
#: syncbb/ncbb family exposes for the high-width small-n regime
ALGO_CHOICES: Tuple[str, ...] = (
    "maxsum", "mgm", "dsa", "adsa", "gdba", "dpop", "syncbb", "ncbb",
)
ENGINE_CHOICES: Tuple[str, ...] = (
    "harness", "auto", "minibucket", "sharded", "frontier",
)
OVERLAP_CHOICES: Tuple[str, ...] = ("default", "off", "exact", "stale")
#: mixed-precision tiers (ISSUE 19) — the tier changes both the cost
#: profile (collective bytes, table footprint) and the result quality
#: class, so the model sees it as its own one-hot block
PRECISION_CHOICES: Tuple[str, ...] = ("f32", "bf16", "int8")

#: length of the config-encoding vector
CONFIG_ENC_LEN = (
    len(ALGO_CHOICES) + len(ENGINE_CHOICES) + len(OVERLAP_CHOICES)
    + len(PRECISION_CHOICES) + 4
)

CONFIG_ENC_NAMES: Tuple[str, ...] = tuple(
    [f"algo={a}" for a in ALGO_CHOICES]
    + [f"engine={e}" for e in ENGINE_CHOICES]
    + [f"overlap={o}" for o in OVERLAP_CHOICES]
    + [f"precision={p}" for p in PRECISION_CHOICES]
    + ["log1p_chunk", "boundary_threshold", "i_bound", "log1p_budget_mb"]
)


def structural_buckets(dcop) -> Tuple[List[np.ndarray], int]:
    """Arity-bucketed factor scopes as variable-index arrays — the
    SAME shape the partitioner and boundary analysis consume, built
    straight from the constraint scopes (no table extraction).
    Returns ``(var_idx_per_bucket, n_vars)``; each bucket is an
    ``[n_factors, arity]`` int32 array."""
    var_index = {name: i for i, name in enumerate(dcop.variables)}
    by_arity: Dict[int, List[List[int]]] = {}
    for c in dcop.constraints.values():
        idx = [
            var_index[v.name] for v in c.dimensions
            if v.name in var_index
        ]
        if idx:
            by_arity.setdefault(len(idx), []).append(idx)
    buckets = [
        np.asarray(rows, dtype=np.int32)
        for _, rows in sorted(by_arity.items())
    ]
    return buckets, len(var_index)


def featurize_detail(dcop, n_shards: int = REFERENCE_SHARDS):
    """Compute the feature vector AND the raw structural numbers the
    selection policy needs (planner byte estimates, induced width,
    cut fraction, ...).  Returns ``(vector [N_FEATURES] float32,
    info dict)``.  Never builds a cost or util table."""
    from pydcop_tpu.dcop.structured import (
        MAX_DENSIFY_ENTRIES,
        StructuredConstraint,
    )
    from pydcop_tpu.graph import pseudotree as pt
    from pydcop_tpu.ops.dpop_shard import estimate_sweep_bytes
    from pydcop_tpu.parallel.boundary import analyze_boundary
    from pydcop_tpu.parallel.partition import partition_factors

    n_vars = len(dcop.variables)
    n_factors = len(dcop.constraints)
    n_agents = len(dcop.agents)

    # table-free structure census: counts per structured kind and the
    # ANALYTIC dense-table byte totals — pure arithmetic on domain
    # sizes, so a 4^100 window costs one float multiply, not a table
    n_structured = 0
    structured_kinds: Dict[str, int] = {}
    dense_table_bytes = 0.0
    structured_dense_bytes = 0.0
    structured_over_cap = False
    # int8 routing signal (ISSUE 19): per-factor quantization is
    # LOSSLESS exactly when every table is integer-valued with its
    # value range inside the 253 usable code levels (scale <= 1 →
    # round-trip error < 0.5 → argmins preserved) and free of
    # hard/BIG entries, which would pin to the saturation code.
    # Anything unknown — a structured constraint, a relation type
    # that exposes no materialized matrix — keeps the signal False:
    # the `solve --auto` mask is conservative by construction.
    # Scans only matrices the relations ALREADY hold; builds nothing.
    from pydcop_tpu.ops.compile import QUANT_THRESHOLD

    int8_safe = True
    for c in dcop.constraints.values():
        if isinstance(c, StructuredConstraint):
            n_structured += 1
            structured_kinds[c.kind] = (
                structured_kinds.get(c.kind, 0) + 1
            )
            b = c.dense_bytes()
            structured_dense_bytes += b
            dense_table_bytes += b
            if c.dense_entries() > MAX_DENSIFY_ENTRIES:
                structured_over_cap = True
            int8_safe = False
        else:
            b = 4.0
            for v in c.dimensions:
                b *= len(v.domain)
            dense_table_bytes += b
            if int8_safe:
                m = getattr(c, "matrix", None)
                if m is None:
                    int8_safe = False
                else:
                    m = np.asarray(m, dtype=np.float64)
                    if (m.size == 0
                            or not np.all(np.isfinite(m))
                            or float(m.max()) >= QUANT_THRESHOLD
                            or not np.allclose(
                                m, np.round(m), atol=1e-6)
                            or float(m.max() - m.min()) > 253.0):
                        int8_safe = False

    dom_sizes = np.asarray(
        [len(v.domain) for v in dcop.variables.values()] or [1],
        dtype=np.float64,
    )

    arities = np.zeros(3, dtype=np.float64)  # [1, 2, 3+]
    max_arity = 0
    degree = np.zeros(max(1, n_vars), dtype=np.int64)
    buckets, _nv = structural_buckets(dcop)
    for b in buckets:
        a = int(b.shape[1])
        max_arity = max(max_arity, a)
        arities[min(a, 3) - 1] += b.shape[0]
        np.add.at(degree, b.reshape(-1), 1)
    total_f = max(1.0, float(arities.sum()))

    tree = pt.build_computation_graph(dcop)
    sep = tree.separators()
    sep_sizes = np.asarray(
        [len(s) for s in sep.values()] or [0], dtype=np.float64
    )
    induced_width = float(sep_sizes.max())
    est = estimate_sweep_bytes(tree)

    cut_fraction = 0.0
    boundary_fraction = 0.0
    if buckets and n_vars:
        assigns = partition_factors(buckets, n_vars, n_shards)
        info_b = analyze_boundary(buckets, assigns, n_vars, n_shards)
        cut_fraction = float(info_b.cut_fraction)
        boundary_fraction = float(info_b.boundary_fraction)

    vec = np.asarray([
        np.log1p(n_vars),
        np.log1p(n_factors),
        np.log1p(n_agents),
        n_factors / max(1, n_vars),
        float(dom_sizes.min()),
        float(dom_sizes.mean()),
        float(dom_sizes.max()),
        arities[0] / total_f,
        arities[1] / total_f,
        arities[2] / total_f,
        float(max_arity),
        float(degree.mean()),
        np.log1p(float(degree.max())),
        (tree.height + 1) / max(1, n_vars),
        induced_width,
        float(sep_sizes.mean()),
        float(np.percentile(sep_sizes, 90)),
        np.log10(max(4.0, float(est["bytes"]))),
        np.log10(max(1.0, float(est["max_node_entries"]))),
        cut_fraction,
        boundary_fraction,
        1.0 if dcop.objective == "max" else 0.0,
        n_structured / max(1, n_factors),
        np.log10(max(4.0, dense_table_bytes)),
        np.log10(max(4.0, structured_dense_bytes)),
    ], dtype=np.float32)
    assert vec.shape == (N_FEATURES,)

    info = {
        "n_vars": n_vars,
        "n_factors": n_factors,
        "max_arity": max_arity,
        "max_domain": int(dom_sizes.max()),
        "induced_width": int(induced_width),
        "sweep_bytes": int(est["bytes"]),
        "max_node_entries": int(est["max_node_entries"]),
        "cut_fraction": float(cut_fraction),
        "boundary_fraction": float(boundary_fraction),
        "objective": dcop.objective,
        "n_structured": n_structured,
        "structured_kinds": structured_kinds,
        "structured_frac": n_structured / max(1, n_factors),
        "dense_table_bytes": float(dense_table_bytes),
        "structured_dense_bytes": float(structured_dense_bytes),
        "structured_over_table_cap": structured_over_cap,
        "int8_safe": bool(int8_safe and n_factors > 0),
    }
    return vec, info


def featurize(dcop, n_shards: int = REFERENCE_SHARDS) -> np.ndarray:
    """The fixed-length instance feature vector (float32,
    ``N_FEATURES`` entries, always finite)."""
    vec, _ = featurize_detail(dcop, n_shards=n_shards)
    return vec


def _one_hot(choices: Tuple[str, ...], value: str) -> List[float]:
    return [1.0 if value == c else 0.0 for c in choices]


def encode_config(cfg: Any) -> np.ndarray:
    """Fixed-length embedding of a candidate config.

    ``cfg`` is duck-typed (any object with ``algo``, ``engine``,
    ``chunk``, ``overlap``, ``boundary_threshold``, ``i_bound`` and
    ``budget_mb`` attributes — :class:`portfolio.select.PortfolioConfig`
    in practice).  Unknown algos/engines encode as all-zero one-hot
    blocks, so a grid extension degrades to "some signal" instead of
    crashing on an old model."""
    vec = (
        _one_hot(ALGO_CHOICES, cfg.algo)
        + _one_hot(ENGINE_CHOICES, cfg.engine)
        + _one_hot(OVERLAP_CHOICES, cfg.overlap)
        + _one_hot(PRECISION_CHOICES, getattr(cfg, "precision", "f32"))
        + [
            float(np.log1p(max(0, int(cfg.chunk)))),
            float(cfg.boundary_threshold),
            float(cfg.i_bound),
            float(np.log1p(max(0.0, float(cfg.budget_mb)))),
        ]
    )
    out = np.asarray(vec, dtype=np.float32)
    assert out.shape == (CONFIG_ENC_LEN,)
    return out


def pair_vector(instance_vec: np.ndarray, cfg: Any) -> np.ndarray:
    """Model input: instance features ++ config encoding."""
    return np.concatenate(
        [np.asarray(instance_vec, dtype=np.float32), encode_config(cfg)]
    )
