"""Learned cost model: a small pure-JAX MLP over (instance, config)
feature pairs, trained to predict drift-normalized
log-time-to-target-cost.

Deliberately dependency-free: the MLP forward pass is a few matmuls,
Adam is hand-rolled over the parameter pytree (no optax), and the
whole train step is one jitted function — the model has to load and
score a ~dozen-config grid in milliseconds inside ``solve --auto``,
not pull in a training framework.

Evaluation is ranking-first (the selector only ever takes an argmin):
:func:`evaluate` reports Spearman rank correlation between predicted
and true labels WITHIN each instance's config group plus the top-1
regret of the predicted argmin vs the per-instance oracle — MSE rides
along for debugging but is not the acceptance number.

Persistence: one ``.npz`` holding the layer weights, the
feature/label normalization statistics and a JSON metadata blob
(feature names, config vocabularies, calibration probe rate) so a
loaded model refuses feature vectors of the wrong shape loudly.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

MODEL_VERSION = 1


def _init_params(n_in: int, hidden: Sequence[int], seed: int):
    rng = np.random.default_rng(seed)
    sizes = [n_in] + list(hidden) + [1]
    params = []
    for a, b in zip(sizes[:-1], sizes[1:]):
        scale = np.sqrt(2.0 / a)
        params.append((
            (rng.standard_normal((a, b)) * scale).astype(np.float32),
            np.zeros((b,), dtype=np.float32),
        ))
    return params


def _forward(params, x):
    import jax.numpy as jnp

    h = x
    for W, b in params[:-1]:
        h = jnp.maximum(h @ W + b, 0.0)
    W, b = params[-1]
    return (h @ W + b)[..., 0]


class CostModel:
    """Trained predictor: ``predict(X)`` maps normalized-at-entry raw
    feature rows to predicted labels in LABEL space (log1p of the
    drift-normalized time-to-target — see dataset.training_matrix)."""

    def __init__(self, params, x_mean, x_std, y_mean, y_std,
                 meta: Optional[Dict[str, Any]] = None):
        self.params = params
        self.x_mean = np.asarray(x_mean, dtype=np.float32)
        self.x_std = np.asarray(x_std, dtype=np.float32)
        self.y_mean = float(y_mean)
        self.y_std = float(y_std)
        self.meta = dict(meta or {})

    @property
    def n_in(self) -> int:
        return int(self.params[0][0].shape[0])

    def predict(self, X: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        X = np.atleast_2d(np.asarray(X, dtype=np.float32))
        if X.shape[1] != self.n_in:
            raise ValueError(
                f"feature width {X.shape[1]} does not match the "
                f"model's input width {self.n_in}; the model was "
                f"trained on a different feature/config schema"
            )
        Xn = (X - self.x_mean) / self.x_std
        y = _forward(self.params, jnp.asarray(Xn))
        return np.asarray(y) * self.y_std + self.y_mean

    # -- persistence --------------------------------------------------------

    def save(self, path: str) -> None:
        arrays: Dict[str, np.ndarray] = {
            "x_mean": self.x_mean,
            "x_std": self.x_std,
            "y_stats": np.asarray([self.y_mean, self.y_std],
                                  dtype=np.float32),
        }
        for i, (W, b) in enumerate(self.params):
            arrays[f"W{i}"] = np.asarray(W)
            arrays[f"b{i}"] = np.asarray(b)
        meta = dict(self.meta)
        meta["version"] = MODEL_VERSION
        meta["n_layers"] = len(self.params)
        arrays["meta_json"] = np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
        )
        np.savez(path, **arrays)

    @classmethod
    def load(cls, path: str) -> "CostModel":
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta_json"].tobytes()).decode())
            if meta.get("version") != MODEL_VERSION:
                raise ValueError(
                    f"portfolio model {path!r} has version "
                    f"{meta.get('version')}, this build reads "
                    f"{MODEL_VERSION}"
                )
            params = [
                (z[f"W{i}"], z[f"b{i}"])
                for i in range(int(meta["n_layers"]))
            ]
            y_mean, y_std = (float(v) for v in z["y_stats"])
            return cls(params, z["x_mean"], z["x_std"], y_mean, y_std,
                       meta)


def _group_pairs(
    y: np.ndarray, group_ids: Sequence[str], min_gap: float
) -> np.ndarray:
    """Within-group (faster, slower) index pairs whose label gap
    exceeds ``min_gap`` — the supervision set of the ranking loss.
    Pairs whose faster side is the group's WINNER are emitted twice:
    the selector acts on the argmin alone, so getting the winner
    above everything else matters more than ordering the mid-field."""
    by_g: Dict[str, List[int]] = {}
    for i, g in enumerate(group_ids):
        by_g.setdefault(g, []).append(i)
    pairs: List[Tuple[int, int]] = []
    for idx in by_g.values():
        winner = min(idx, key=lambda i: y[i])
        for a in idx:
            for b in idx:
                if y[a] + min_gap < y[b]:
                    pairs.append((a, b))
                    if a == winner:
                        pairs.append((a, b))
    return np.asarray(pairs, dtype=np.int32).reshape(-1, 2)


def train_model(
    X: np.ndarray,
    y: np.ndarray,
    hidden: Sequence[int] = (48, 48),
    epochs: int = 300,
    lr: float = 3e-3,
    batch: int = 64,
    l2: float = 1e-4,
    seed: int = 0,
    meta: Optional[Dict[str, Any]] = None,
    group_ids: Optional[Sequence[str]] = None,
    rank_weight: float = 1.0,
    rank_margin: float = 0.3,
) -> Tuple[CostModel, Dict[str, Any]]:
    """Fit the MLP with hand-rolled Adam.  Inputs are RAW feature rows
    and RAW labels; normalization statistics are computed here and
    stored with the model.  Returns ``(model, history)`` where history
    carries the per-epoch training loss for the eval report.

    With ``group_ids`` (one instance id per row, as produced by
    ``dataset.training_matrix``) the loss adds a **within-group
    pairwise ranking hinge**: for every same-instance pair where
    config *a*'s label beats config *b*'s, the model is pushed to
    keep ``pred(b) - pred(a)`` above ``rank_margin`` (in normalized
    label units).  The selector only ever takes a per-instance argmin,
    so within-group ordering IS the objective — the MSE term alone
    spends most of its capacity explaining cross-instance scale,
    which is exactly the variance the argmin never sees.  The MSE
    term stays in the loss so predictions remain calibrated times for
    the honesty audit."""
    import jax
    import jax.numpy as jnp

    X = np.asarray(X, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    if X.ndim != 2 or X.shape[0] != y.shape[0] or X.shape[0] == 0:
        raise ValueError(
            f"bad training set: X {X.shape}, y {y.shape}"
        )
    x_mean = X.mean(axis=0)
    x_std = X.std(axis=0)
    x_std = np.where(x_std < 1e-6, 1.0, x_std).astype(np.float32)
    y_mean = float(y.mean())
    y_std = float(y.std()) or 1.0
    Xn = jnp.asarray((X - x_mean) / x_std)
    yn = jnp.asarray((y - y_mean) / y_std)

    pairs = np.zeros((0, 2), dtype=np.int32)
    if group_ids is not None and rank_weight > 0:
        # min label gap 0.05 in normalized units skips effective ties
        pairs = _group_pairs(
            np.asarray((y - y_mean) / y_std), group_ids, 0.05
        )

    params = [
        (jnp.asarray(W), jnp.asarray(b))
        for W, b in _init_params(X.shape[1], hidden, seed)
    ]
    m_state = jax.tree_util.tree_map(jnp.zeros_like, params)
    v_state = jax.tree_util.tree_map(jnp.zeros_like, params)

    use_rank = pairs.shape[0] > 0

    def loss_fn(p, xb, yb, pa, pb):
        pred = _forward(p, xb)
        mse = jnp.mean((pred - yb) ** 2)
        reg = sum(jnp.sum(W ** 2) for W, _ in p)
        loss = mse + l2 * reg
        if use_rank:
            sa = _forward(p, pa)
            sb = _forward(p, pb)
            loss = loss + rank_weight * jnp.mean(
                jnp.maximum(0.0, rank_margin - (sb - sa))
            )
        return loss

    b1, b2, eps = 0.9, 0.999, 1e-8

    @jax.jit
    def step(p, m, v, t, xb, yb, pa, pb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb, pa, pb)
        m = jax.tree_util.tree_map(
            lambda a, g: b1 * a + (1 - b1) * g, m, grads)
        v = jax.tree_util.tree_map(
            lambda a, g: b2 * a + (1 - b2) * g * g, v, grads)
        mh = jax.tree_util.tree_map(lambda a: a / (1 - b1 ** t), m)
        vh = jax.tree_util.tree_map(lambda a: a / (1 - b2 ** t), v)
        p = jax.tree_util.tree_map(
            lambda a, mm, vv: a - lr * mm / (jnp.sqrt(vv) + eps),
            p, mh, vh)
        return p, m, v, loss

    rng = np.random.default_rng(seed + 1)
    n = X.shape[0]
    bs = min(batch, n)
    pair_bs = min(256, pairs.shape[0]) if use_rank else 1
    empty = jnp.zeros((1, X.shape[1]), jnp.float32)
    losses: List[float] = []
    t = 0
    for epoch in range(epochs):
        order = rng.permutation(n)
        ep_loss = 0.0
        n_b = 0
        for s in range(0, n, bs):
            idx = jnp.asarray(order[s:s + bs])
            if use_rank:
                pi = pairs[rng.integers(0, pairs.shape[0], pair_bs)]
                pa, pb = Xn[jnp.asarray(pi[:, 0])], Xn[
                    jnp.asarray(pi[:, 1])]
            else:
                pa = pb = empty
            t += 1
            params, m_state, v_state, loss = step(
                params, m_state, v_state, float(t), Xn[idx], yn[idx],
                pa, pb,
            )
            ep_loss += float(loss)
            n_b += 1
        losses.append(ep_loss / max(1, n_b))
    model = CostModel(
        [(np.asarray(W), np.asarray(b)) for W, b in params],
        x_mean, x_std, y_mean, y_std, meta,
    )
    return model, {"epochs": epochs, "final_loss": losses[-1],
                   "losses": losses, "rank_pairs": int(pairs.shape[0])}


# ---------------------------------------------------------------------------
# ranking evaluation
# ---------------------------------------------------------------------------


def spearman(a: np.ndarray, b: np.ndarray) -> float:
    """Spearman rank correlation, numpy-only (no scipy dep)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.size < 2:
        return 0.0

    def ranks(x):
        order = np.argsort(x, kind="stable")
        r = np.empty_like(order, dtype=np.float64)
        r[order] = np.arange(len(x))
        # average ties so constant vectors do not fake correlation
        for val in np.unique(x):
            sel = x == val
            if sel.sum() > 1:
                r[sel] = r[sel].mean()
        return r

    ra, rb = ranks(a), ranks(b)
    sa, sb = ra.std(), rb.std()
    if sa == 0 or sb == 0:
        return 0.0
    return float(((ra - ra.mean()) * (rb - rb.mean())).mean() / (sa * sb))


def evaluate(
    model: CostModel,
    groups: Sequence[Tuple[np.ndarray, np.ndarray]],
) -> Dict[str, Any]:
    """Ranking report over held-out instance groups.

    ``groups`` is a sequence of ``(X_group, y_group)`` pairs — one per
    held-out instance, rows = that instance's grid configs, labels in
    the same (log) space the model was trained in.  Reports:

    * ``rank_correlation`` — mean within-group Spearman;
    * ``top1_regret`` — mean of ``expm1(y[argmin pred]) -
      expm1(y[oracle])`` in normalized-time units (0 = the model's
      pick IS the oracle pick);
    * ``top1_regret_ratio`` — mean multiplicative regret
      ``time(pick)/time(oracle)`` (1.0 = oracle);
    * ``top1_hits`` — fraction of groups where the pick = oracle;
    * ``mse`` — plain regression error, for debugging only.
    """
    corrs: List[float] = []
    regrets: List[float] = []
    ratios: List[float] = []
    hits = 0
    sq = 0.0
    n_rows = 0
    for Xg, yg in groups:
        yg = np.asarray(yg, dtype=np.float64)
        pred = np.asarray(model.predict(Xg), dtype=np.float64)
        corrs.append(spearman(pred, yg))
        pick = int(np.argmin(pred))
        oracle = int(np.argmin(yg))
        t_pick = float(np.expm1(yg[pick]))
        t_best = float(np.expm1(yg[oracle]))
        regrets.append(t_pick - t_best)
        ratios.append(t_pick / t_best if t_best > 0 else 1.0)
        hits += 1 if pick == oracle else 0
        sq += float(((pred - yg) ** 2).sum())
        n_rows += len(yg)
    n_g = max(1, len(list(groups)))
    return {
        "n_groups": len(corrs),
        "rank_correlation": round(float(np.mean(corrs or [0.0])), 4),
        "top1_regret": round(float(np.mean(regrets or [0.0])), 6),
        "top1_regret_ratio": round(float(np.mean(ratios or [1.0])), 4),
        "top1_hits": round(hits / n_g, 4),
        "mse": round(sq / max(1, n_rows), 6),
    }
