"""Small graph helpers used by computation-graph builders and generators.

Equivalent capability to the reference's pydcop/utils/graphs.py, implemented
on plain adjacency dicts (networkx is only used by the problem generators).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Hashable, Iterable, List, Set, Tuple


def as_adjacency(edges: Iterable[Tuple[Hashable, Hashable]]) -> Dict:
    adj: Dict[Hashable, Set] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set()).add(a)
    return adj


def connected_components(adj: Dict[Hashable, Set]) -> List[Set]:
    seen: Set = set()
    comps: List[Set] = []
    for start in adj:
        if start in seen:
            continue
        comp = {start}
        q = deque([start])
        while q:
            n = q.popleft()
            for m in adj.get(n, ()):
                if m not in comp:
                    comp.add(m)
                    q.append(m)
        seen |= comp
        comps.append(comp)
    return comps


def is_connected(adj: Dict[Hashable, Set]) -> bool:
    if not adj:
        return True
    return len(connected_components(adj)) == 1


def has_cycle(adj: Dict[Hashable, Set]) -> bool:
    """True if the undirected graph contains at least one cycle."""
    seen: Set = set()
    for start in adj:
        if start in seen:
            continue
        stack = [(start, None)]
        local: Set = set()
        while stack:
            n, parent = stack.pop()
            if n in local:
                return True
            local.add(n)
            for m in adj.get(n, ()):
                if m != parent:
                    stack.append((m, n))
        seen |= local
    return False


def bfs_order(adj: Dict[Hashable, Set], root: Hashable) -> List[Hashable]:
    order, seen, q = [], {root}, deque([root])
    while q:
        n = q.popleft()
        order.append(n)
        for m in sorted(adj.get(n, ()), key=str):
            if m not in seen:
                seen.add(m)
                q.append(m)
    return order
