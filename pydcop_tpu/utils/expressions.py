"""Constraints from python expression strings.

Equivalent capability to the reference's ``ExpressionFunction``
(reference: pydcop/utils/expressionfunction.py:37): a cost function defined by
a python expression over variable names, e.g. ``"1 if v1 == v2 else 0"``.
These appear in the YAML problem format as ``intention`` constraints and
variable ``cost_function`` entries.

TPU relevance: expressions are *compile-time only* — the tensorization layer
(`pydcop_tpu.ops.compile`) materializes them over the full domain product into
dense cost tensors once, after which only XLA array ops run.  We therefore
optimise for safe, deterministic evaluation rather than speed.

Safety: the expression is parsed with :mod:`ast` and evaluated with an empty
``__builtins__`` plus an explicit whitelist of math helpers, so YAML files
cannot run arbitrary code (import, attribute access to dunders, etc. are
rejected at parse time).
"""
from __future__ import annotations

import ast
import math
from typing import Callable, Iterable

from pydcop_tpu.utils.serialization import SimpleRepr

_SAFE_NAMES: dict = {
    "abs": abs,
    "min": min,
    "max": max,
    "round": round,
    "len": len,
    "sum": sum,
    "all": all,
    "any": any,
    "int": int,
    "float": float,
    "str": str,
    "bool": bool,
    "pow": pow,
    "divmod": divmod,
    "sorted": sorted,
    "math": math,
    "sqrt": math.sqrt,
    "log": math.log,
    "exp": math.exp,
    "floor": math.floor,
    "ceil": math.ceil,
    "pi": math.pi,
    "inf": math.inf,
    "True": True,
    "False": False,
    "None": None,
}

_FORBIDDEN_NODES = (
    ast.Import,
    ast.ImportFrom,
    ast.Lambda,
    ast.Await,
    ast.Yield,
    ast.YieldFrom,
    ast.Global,
    ast.Nonlocal,
    ast.Delete,
    ast.With,
    ast.Raise,
    ast.Try,
    ast.ClassDef,
    ast.FunctionDef,
    ast.AsyncFunctionDef,
)


class ExpressionFunctionError(Exception):
    pass


def _check_safe(tree: ast.AST, expression: str) -> None:
    for node in ast.walk(tree):
        if isinstance(node, _FORBIDDEN_NODES):
            raise ExpressionFunctionError(
                f"Forbidden construct {type(node).__name__} in expression "
                f"{expression!r}"
            )
        if isinstance(node, ast.Attribute) and node.attr.startswith("__"):
            raise ExpressionFunctionError(
                f"Dunder attribute access forbidden in expression {expression!r}"
            )
        if isinstance(node, ast.Name) and node.id.startswith("__"):
            raise ExpressionFunctionError(
                f"Dunder name forbidden in expression {expression!r}"
            )


class ExpressionFunction(SimpleRepr):
    """A callable built from a python expression string.

    The free variable names of the expression (names that are neither
    whitelisted helpers nor fixed) are exposed as :attr:`variable_names`;
    the function is called with keyword arguments for those names.

    >>> f = ExpressionFunction('v1 + 2 * v2')
    >>> sorted(f.variable_names)
    ['v1', 'v2']
    >>> f(v1=1, v2=3)
    7
    >>> g = f.partial(v2=1)
    >>> g(v1=2)
    4
    """

    def __init__(self, expression: str, **fixed_vars):
        self._expression = expression
        self._fixed_vars = dict(fixed_vars)
        # Multi-line function bodies with a `return` are accepted, as the
        # reference format allows them for intention constraints
        # (reference: pydcop/utils/expressionfunction.py docstring).
        src = expression.strip()
        if "return" in src:
            self._mode = "exec"
            tree = ast.parse(src, mode="exec")
        else:
            self._mode = "eval"
            tree = ast.parse(src, mode="eval")
        _check_safe(tree, expression)
        names = {
            n.id
            for n in ast.walk(tree)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        }
        assigned = {
            n.id
            for n in ast.walk(tree)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        }
        self._all_vars = names - set(_SAFE_NAMES) - assigned
        if self._mode == "exec":
            # wrap statements in a function so `return` works
            fn_src = "def __expr_fn__():\n" + "\n".join(
                "    " + line for line in src.splitlines()
            )
            fn_tree = ast.parse(fn_src, mode="exec")
            self._code = compile(fn_tree, "<expression_function>", "exec")
        else:
            self._code = compile(tree, "<expression_function>", "eval")

    @property
    def expression(self) -> str:
        return self._expression

    @property
    def variable_names(self) -> frozenset:
        """Free variables still needed at call time (fixed vars excluded)."""
        return frozenset(self._all_vars - set(self._fixed_vars))

    def partial(self, **kwargs) -> "ExpressionFunction":
        unknown = set(kwargs) - self._all_vars
        if unknown:
            raise ExpressionFunctionError(
                f"partial() got names {unknown} not used by {self._expression!r}"
            )
        return ExpressionFunction(
            self._expression, **{**self._fixed_vars, **kwargs}
        )

    def __call__(self, *args, **kwargs):
        if args:
            raise ExpressionFunctionError(
                "ExpressionFunction must be called with keyword arguments"
            )
        scope = {**self._fixed_vars, **kwargs}
        missing = self.variable_names - set(scope)
        if missing:
            raise ExpressionFunctionError(
                f"Missing variables {missing} for {self._expression!r}"
            )
        env = {"__builtins__": {}, **_SAFE_NAMES, **scope}
        if self._mode == "eval":
            return eval(self._code, env)  # noqa: S307 - sandboxed, see _check_safe
        exec(self._code, env)  # noqa: S102 - sandboxed, see _check_safe
        return env["__expr_fn__"]()

    def __repr__(self):
        return f"ExpressionFunction({self._expression!r})"

    def __eq__(self, other):
        return (
            isinstance(other, ExpressionFunction)
            and self._expression == other._expression
            and self._fixed_vars == other._fixed_vars
        )

    def __hash__(self):
        return hash((self._expression, tuple(sorted(self._fixed_vars.items()))))

    def _simple_repr(self):
        from pydcop_tpu.utils.serialization import REPR_MODULE, REPR_QUALNAME, simple_repr

        return {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "expression": self._expression,
            "fixed_vars": simple_repr(self._fixed_vars),
        }

    @classmethod
    def _from_repr(cls, r):
        from pydcop_tpu.utils.serialization import from_repr

        return cls(r["expression"], **from_repr(r.get("fixed_vars", {})))


def expression_function_from_callable(
    fn: Callable, names: Iterable[str]
) -> Callable:
    """Adapter giving a plain callable the ExpressionFunction interface."""
    fn.variable_names = frozenset(names)  # type: ignore[attr-defined]
    return fn
