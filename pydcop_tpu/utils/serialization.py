"""JSON-able serialization for model objects.

Equivalent capability to the reference's ``SimpleRepr`` mixin
(reference: pydcop/utils/simple_repr.py:68,133,175): any object whose
constructor arguments map to attributes can be turned into a plain
dict-of-builtins and back.  Used by the YAML reader/writer, checkpointing and
the (optional) HTTP control plane.

Design: rather than the reference's name-mangling convention alone, we resolve
each constructor parameter ``p`` by looking for, in order, ``self._p``,
``self.p``, then a class-level default.  Classes can override ``_simple_repr``
/ ``_from_repr`` hooks for irregular shapes.
"""
from __future__ import annotations

import importlib
import inspect
from typing import Any

REPR_MODULE = "__module__"
REPR_QUALNAME = "__qualname__"

_MISSING = object()


class SimpleReprException(Exception):
    pass


class SimpleRepr:
    """Mixin providing ``simple_repr(obj)`` / ``from_repr(repr)`` support."""

    def _simple_repr(self) -> dict:
        r: dict[str, Any] = {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
        }
        sig = inspect.signature(type(self).__init__)
        for name, param in sig.parameters.items():
            if name == "self":
                continue
            if param.kind in (param.VAR_POSITIONAL, param.VAR_KEYWORD):
                continue
            val = getattr(self, "_" + name, _MISSING)
            if val is _MISSING:
                val = getattr(self, name, _MISSING)
            if val is _MISSING:
                if param.default is not param.empty:
                    val = param.default
                else:
                    raise SimpleReprException(
                        f"Cannot build repr for {self!r}: no attribute "
                        f"matching constructor argument {name!r}"
                    )
            r[name] = simple_repr(val)
        return r

    @classmethod
    def _from_repr(cls, r: dict) -> "SimpleRepr":
        kwargs = {
            k: from_repr(v)
            for k, v in r.items()
            if k not in (REPR_MODULE, REPR_QUALNAME)
        }
        return cls(**kwargs)


def simple_repr(obj: Any) -> Any:
    """Return a composition of builtins (dict/list/str/num) describing obj."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [simple_repr(o) for o in obj]
    if isinstance(obj, dict):
        return {k: simple_repr(v) for k, v in obj.items()}
    # numpy scalars / arrays
    if hasattr(obj, "tolist") and type(obj).__module__.startswith(("numpy", "jax")):
        return simple_repr(obj.tolist())
    if isinstance(obj, SimpleRepr):
        return obj._simple_repr()
    raise SimpleReprException(f"Object has no simple repr: {obj!r} ({type(obj)})")


def from_repr(r: Any) -> Any:
    """Rebuild an object from its :func:`simple_repr` output."""
    if r is None or isinstance(r, (str, int, float, bool)):
        return r
    if isinstance(r, list):
        return [from_repr(o) for o in r]
    if isinstance(r, dict):
        if REPR_QUALNAME in r:
            cls = _resolve(r[REPR_MODULE], r[REPR_QUALNAME])
            return cls._from_repr(r)
        return {k: from_repr(v) for k, v in r.items()}
    raise SimpleReprException(f"Cannot rebuild object from {r!r}")


def _resolve(module: str, qualname: str):
    mod = importlib.import_module(module)
    obj: Any = mod
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj
