from pydcop_tpu.utils.serialization import SimpleRepr, simple_repr, from_repr
from pydcop_tpu.utils.expressions import ExpressionFunction

__all__ = ["SimpleRepr", "simple_repr", "from_repr", "ExpressionFunction"]
