"""`pydcop_tpu generate` — problem generators.

Equivalent capability to the reference's pydcop/commands/generate.py +
generators/* (`pydcop generate {graphcoloring, ising, secp,
meetingscheduling, iot, smallworld, agents, scenario}`).  Output is the
problem YAML on stdout or --output.
"""
from __future__ import annotations

import sys


def set_parser(subparsers):
    parser = subparsers.add_parser("generate", help="generate problems")
    gen_sub = parser.add_subparsers(dest="generator", required=True)

    p = gen_sub.add_parser("graphcoloring")
    p.set_defaults(func=_graphcoloring)
    p.add_argument("--variables_count", "-V", type=int, required=True)
    p.add_argument("--colors_count", "-C", type=int, default=3)
    p.add_argument("--graph", choices=["random", "scalefree", "grid"],
                   default="random")
    p.add_argument("--p_edge", type=float, default=None)
    p.add_argument("--edges_count", type=int, default=None)
    p.add_argument("--soft", action="store_true")
    p.add_argument("--noise", type=float, default=0.02)
    p.add_argument("--agents_count", type=int, default=None)
    p.add_argument("--capacity", type=float, default=100)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("ising")
    p.set_defaults(func=_ising)
    p.add_argument("--row_count", type=int, required=True)
    p.add_argument("--col_count", type=int, default=None)
    p.add_argument("--bin_range", type=float, default=1.6)
    p.add_argument("--un_range", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("secp")
    p.set_defaults(func=_secp)
    p.add_argument("--lights", type=int, default=9)
    p.add_argument("--models", type=int, default=3)
    p.add_argument("--rules", type=int, default=2)
    p.add_argument("--max_model_size", type=int, default=4)
    p.add_argument("--light_levels", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("meetingscheduling")
    p.set_defaults(func=_meetings)
    p.add_argument("--agents_count", type=int, default=4)
    p.add_argument("--meetings_count", type=int, default=3)
    p.add_argument("--slots_count", type=int, default=8)
    p.add_argument("--participants_count", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("iot")
    p.set_defaults(func=_iot)
    p.add_argument("--num_device", "-n", type=int, default=10)
    p.add_argument("--domain_size", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("smallworld")
    p.set_defaults(func=_smallworld)
    p.add_argument("--variables_count", "-V", type=int, default=20)
    p.add_argument("--k_neighbors", type=int, default=4)
    p.add_argument("--rewire_p", type=float, default=0.1)
    p.add_argument("--colors_count", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("agents")
    p.set_defaults(func=_agents)
    p.add_argument("--count", type=int, required=True)
    p.add_argument("--capacity", type=float, default=100)
    p.add_argument("--hosting_default", type=float, default=0)
    p.add_argument("--routes_default", type=float, default=1)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("scenario")
    p.set_defaults(func=_scenario)
    p.add_argument("--evts_count", type=int, default=3)
    p.add_argument("--actions_count", type=int, default=1)
    p.add_argument("--delay", type=float, default=10)
    p.add_argument("--dcop_files", nargs="*", default=None,
                   help="take agent names from this DCOP")
    p.add_argument("--agents_count", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)

    return parser


def _write(args, text: str):
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def _graphcoloring(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_graph_coloring

    dcop = generate_graph_coloring(
        n_variables=args.variables_count,
        n_colors=args.colors_count,
        graph_type=args.graph,
        p_edge=args.p_edge,
        n_edges=args.edges_count,
        soft=args.soft,
        noise_level=args.noise,
        n_agents=args.agents_count,
        capacity=args.capacity,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _ising(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_ising

    dcop = generate_ising(
        rows=args.row_count,
        cols=args.col_count or args.row_count,
        bin_range=args.bin_range,
        un_range=args.un_range,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _secp(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_secp

    dcop = generate_secp(
        n_lights=args.lights,
        n_models=args.models,
        n_rules=args.rules,
        max_model_size=args.max_model_size,
        light_levels=args.light_levels,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _meetings(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_meeting_scheduling

    dcop = generate_meeting_scheduling(
        n_agents=args.agents_count,
        n_meetings=args.meetings_count,
        n_slots=args.slots_count,
        participants_per_meeting=args.participants_count,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _iot(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_iot

    dcop = generate_iot(
        n_devices=args.num_device, n_states=args.domain_size, seed=args.seed
    )
    return _write(args, dcop_yaml(dcop))


def _smallworld(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_smallworld

    dcop = generate_smallworld(
        n_variables=args.variables_count,
        k_neighbors=args.k_neighbors,
        rewire_p=args.rewire_p,
        n_colors=args.colors_count,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _agents(args):
    from pydcop_tpu.dcop import yaml_agents
    from pydcop_tpu.generators import generate_agents

    agents = generate_agents(
        args.count,
        capacity=args.capacity,
        hosting_default=args.hosting_default,
        routes_default=args.routes_default,
        seed=args.seed,
    )
    return _write(args, yaml_agents(agents))


def _scenario(args):
    from pydcop_tpu.dcop import yaml_scenario
    from pydcop_tpu.generators import generate_scenario

    if args.dcop_files:
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(args.dcop_files)
        agent_names = list(dcop.agents)
    elif args.agents_count:
        agent_names = [f"a{i:04d}" for i in range(args.agents_count)]
    else:
        raise SystemExit("scenario: need --dcop_files or --agents_count")
    scenario = generate_scenario(
        agent_names,
        n_events=args.evts_count,
        removals_per_event=args.actions_count,
        delay=args.delay,
        seed=args.seed,
    )
    return _write(args, yaml_scenario(scenario))
