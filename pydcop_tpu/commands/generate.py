"""`pydcop_tpu generate` — problem generators.

Equivalent capability to the reference's pydcop/commands/generate.py +
generators/* (`pydcop generate {graphcoloring, ising, secp,
meetingscheduling, iot, smallworld, agents, scenario}`).  Output is the
problem YAML on stdout or --output.
"""
from __future__ import annotations

import sys


def set_parser(subparsers):
    parser = subparsers.add_parser("generate", help="generate problems")
    gen_sub = parser.add_subparsers(dest="generator", required=True)

    # both spellings exist in the reference (graphcoloring in the docs'
    # synopsis, graph_coloring in the generators package registration)
    for alias in ("graphcoloring", "graph_coloring"):
        p = gen_sub.add_parser(alias)
        p.set_defaults(func=_graphcoloring)
        p.add_argument("--variables_count", "-v", "-V", type=int,
                       required=True)
        p.add_argument("--colors_count", "-c", "-C", type=int, default=3)
        p.add_argument("--graph", "-g",
                       choices=["random", "scalefree", "grid"],
                       default="random")
        p.add_argument("--p_edge", "-p", type=float, default=None,
                       help="edge probability (Erdős–Rényi random graphs)")
        p.add_argument("--m_edge", "-m", type=int, default=None,
                       help="edges attached per new variable "
                       "(scale-free graphs)")
        p.add_argument("--edges_count", type=int, default=None)
        p.add_argument("--soft", action="store_true")
        p.add_argument("--intentional", action="store_true",
                       help="intentional (expression) constraints — hard "
                       "coloring only, like the reference")
        p.add_argument("--allow_subgraph", action="store_true",
                       help="skip the connected-graph filter")
        p.add_argument("--noagents", action="store_true",
                       help="do not generate agents")
        p.add_argument("--noise", type=float, default=0.02)
        p.add_argument("--agents_count", type=int, default=None)
        p.add_argument("--capacity", type=float, default=100)
        p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("ising")
    p.set_defaults(func=_ising)
    p.add_argument("--row_count", type=int, required=True)
    p.add_argument("--col_count", type=int, default=None)
    p.add_argument("--bin_range", type=float, default=1.6)
    p.add_argument("--un_range", type=float, default=0.05)
    p.add_argument("--intentional", action="store_true",
                   help="intentional (expression) constraints "
                   "(default is extensive form)")
    p.add_argument("--no_agents", action="store_true",
                   help="generate the problem without agents")
    p.add_argument("--fg_dist", action="store_true",
                   help="also emit a factor-graph distribution (one "
                   "variable + 3 factors per agent)")
    p.add_argument("--var_dist", action="store_true",
                   help="also emit a one-variable-per-agent distribution")
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("secp")
    p.set_defaults(func=_secp)
    p.add_argument("--lights", type=int, default=9)
    p.add_argument("--models", type=int, default=3)
    p.add_argument("--rules", type=int, default=2)
    p.add_argument("--max_model_size", type=int, default=4)
    p.add_argument("--light_levels", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("meetingscheduling")
    p.set_defaults(func=_meetings)
    p.add_argument("--agents_count", type=int, default=4)
    p.add_argument("--meetings_count", type=int, default=3)
    p.add_argument("--slots_count", type=int, default=8)
    p.add_argument("--participants_count", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)

    # the reference's resource-based PEAV generator (meetingscheduling.py
    # :125-192) — emits a DCOP AND its PEAV distribution
    p = gen_sub.add_parser("meetings")
    p.set_defaults(func=_meetings_peav)
    p.add_argument("--slots_count", type=int, required=True)
    p.add_argument("--events_count", type=int, required=True)
    p.add_argument("--resources_count", type=int, required=True)
    p.add_argument("--max_resources_event", type=int, required=True)
    p.add_argument("--max_length_event", type=int, default=1)
    p.add_argument("--max_resource_value", type=int, default=10)
    p.add_argument("--no_agents", action="store_true")
    p.add_argument("--routes_default", type=int, default=None)
    p.add_argument("--hosting_default", type=int, default=None)
    p.add_argument("--capacity", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("iot")
    p.set_defaults(func=_iot)
    p.add_argument("--num", "--num_device", "-n", dest="num_device",
                   type=int, default=10,
                   help="number of devices/variables")
    p.add_argument("--domain", "--domain_size", "-d", dest="domain_size",
                   type=int, default=3,
                   help="variable domain size: 0..d-1")
    p.add_argument("--range", "-r", dest="cost_range", type=float,
                   default=10, help="range of the constraint costs")
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("smallworld")
    p.set_defaults(func=_smallworld)
    p.add_argument("--variables_count", "-V", type=int, default=20)
    p.add_argument("--k_neighbors", type=int, default=4)
    p.add_argument("--rewire_p", type=float, default=0.1)
    p.add_argument("--colors_count", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)

    # hard-constraint-dense routing/scheduling (ISSUE 12): BIG hard
    # mutual-exclusion tables on overlapping resource windows — the
    # CEC-pruning / genuine-infeasibility family (docs/scenarios.rst)
    p = gen_sub.add_parser("routing")
    p.set_defaults(func=_routing)
    p.add_argument("--tasks_count", "-V", type=int, required=True)
    p.add_argument("--slots_count", type=int, default=4)
    p.add_argument("--tasks_per_resource", type=int, default=3)
    p.add_argument("--p_soft", type=float, default=0.15,
                   help="fraction of tasks given an extra soft "
                   "cross-resource affinity pair")
    p.add_argument("--infeasible", action="store_true",
                   help="over-constrain the first resource window so "
                   "the instance is pigeonhole-infeasible (optimum "
                   ">= the hard cost)")
    p.add_argument("--agents_count", type=int, default=None)
    p.add_argument("--capacity", type=float, default=100)
    p.add_argument("--seed", type=int, default=0)

    # table-free routing (ISSUE 17): the same window family emitted
    # as STRUCTURED resource constraints — arity-100 windows dump as
    # a few KB of parameters where the dense form would be a 4^100
    # table (docs/performance.rst "Table-free constraints")
    p = gen_sub.add_parser("routing_structured")
    p.set_defaults(func=_routing_structured)
    p.add_argument("--tasks_count", "-V", type=int, required=True)
    p.add_argument("--slots_count", type=int, default=4)
    p.add_argument("--window", type=int, default=None,
                   help="tasks per resource window (default "
                   "slots_count; window == tasks_count gives one "
                   "full-arity constraint)")
    p.add_argument("--slot_capacity", type=int, default=None)
    p.add_argument("--p_soft", type=float, default=0.15)
    p.add_argument("--infeasible", action="store_true")
    p.add_argument("--agents_count", type=int, default=None)
    p.add_argument("--capacity", type=float, default=100)
    p.add_argument("--seed", type=int, default=0)

    # moving-target tracking (ISSUE 12): the classic dynamic-DCOP
    # benchmark; --steps also emits the target walk's change_factor
    # scenario next to the DCOP (docs/scenarios.rst)
    p = gen_sub.add_parser("tracking")
    p.set_defaults(func=_tracking)
    p.add_argument("--sensors_count", "-V", type=int, required=True,
                   help="sensor count (must be a square: the grid)")
    p.add_argument("--targets_count", type=int, default=3)
    p.add_argument("--radius", type=float, default=2.5)
    p.add_argument("--weight", type=float, default=10.0)
    p.add_argument("--steps", type=int, default=0,
                   help="emit the n-step target-walk churn scenario "
                   "alongside the DCOP (to <output>_scenario<ext>, or "
                   "as an extra YAML document on stdout)")
    p.add_argument("--agents_count", type=int, default=None)
    p.add_argument("--capacity", type=float, default=100)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("agents")
    p.set_defaults(func=_agents)
    p.add_argument("--count", type=int, required=True)
    p.add_argument("--capacity", type=float, default=100)
    p.add_argument("--hosting_default", type=float, default=0)
    p.add_argument("--routes_default", type=float, default=1)
    p.add_argument("--seed", type=int, default=0)

    p = gen_sub.add_parser("scenario")
    p.set_defaults(func=_scenario)
    p.add_argument("--evts_count", type=int, default=3)
    p.add_argument("--actions_count", type=int, default=1)
    p.add_argument("--delay", type=float, default=10)
    p.add_argument("--dcop_files", nargs="*", default=None,
                   help="take agent names from this DCOP")
    p.add_argument("--agents_count", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)

    return parser


def _write(args, text: str):
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def _write_dist(args, mapping, tag: str, graph: str, dist_algo: str = "NA"):
    """Emit a distribution next to the generated DCOP: to
    ``<output>_<tag><ext>`` when --output is set, else as an extra YAML
    document on stdout (the reference prints both to stdout,
    ising.py:249-271)."""
    import yaml as _yaml

    text = _yaml.dump({
        "inputs": {
            "dist_algo": dist_algo,
            "dcop": args.output or "NA",
            "graph": graph,
            "algo": "NA",
        },
        "distribution": mapping,
        "cost": None,
    })
    if args.output:
        import os as _os

        path, ext = _os.path.splitext(args.output)
        with open(f"{path}_{tag}{ext}", "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write("---\n" + text)


def _graphcoloring(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_graph_coloring

    dcop = generate_graph_coloring(
        n_variables=args.variables_count,
        n_colors=args.colors_count,
        graph_type=args.graph,
        p_edge=args.p_edge,
        n_edges=args.edges_count,
        m_edge=args.m_edge,
        soft=args.soft,
        intentional=args.intentional,
        allow_subgraph=args.allow_subgraph,
        no_agents=args.noagents,
        noise_level=args.noise,
        n_agents=args.agents_count,
        capacity=args.capacity,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _meetings_peav(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_meetings_peav

    dcop, mapping = generate_meetings_peav(
        slots_count=args.slots_count,
        events_count=args.events_count,
        resources_count=args.resources_count,
        max_resources_event=args.max_resources_event,
        max_length_event=args.max_length_event,
        max_resource_value=args.max_resource_value,
        seed=args.seed,
        no_agents=args.no_agents,
        hosting_default=args.hosting_default,
        routes_default=args.routes_default,
        capacity=args.capacity,
    )
    rc = _write(args, dcop_yaml(dcop))
    if mapping is not None:
        _write_dist(args, mapping, "dist", "constraints_graph",
                    dist_algo="peav")
    return rc


def _ising(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_ising

    dcop, var_mapping, fg_mapping = generate_ising(
        rows=args.row_count,
        cols=args.col_count or args.row_count,
        bin_range=args.bin_range,
        un_range=args.un_range,
        seed=args.seed,
        intentional=args.intentional,
        no_agents=args.no_agents,
        fg_dist=args.fg_dist,
        var_dist=args.var_dist,
    )
    rc = _write(args, dcop_yaml(dcop))

    # emit the requested distribution(s) next to the DCOP, as
    # <name>_fgdist / <name>_vardist files (reference ising.py:249-271)
    if args.fg_dist:
        _write_dist(args, fg_mapping, "fgdist", "factor_graph")
    if args.var_dist:
        _write_dist(args, var_mapping, "vardist", "constraints_graph")
    return rc


def _secp(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_secp

    dcop = generate_secp(
        n_lights=args.lights,
        n_models=args.models,
        n_rules=args.rules,
        max_model_size=args.max_model_size,
        light_levels=args.light_levels,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _meetings(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_meeting_scheduling

    dcop = generate_meeting_scheduling(
        n_agents=args.agents_count,
        n_meetings=args.meetings_count,
        n_slots=args.slots_count,
        participants_per_meeting=args.participants_count,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _iot(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_iot

    dcop = generate_iot(
        n_devices=args.num_device, n_states=args.domain_size,
        seed=args.seed, cost_range=args.cost_range,
    )
    rc = _write(args, dcop_yaml(dcop))
    if args.output:
        # the reference iot generator emits the DCOP *and* its initial
        # ilp_compref distribution (iot.py:30-33, "generates both a dcop
        # and its initial distribution")
        import os as _os

        from pydcop_tpu.algorithms import load_algorithm_module
        from pydcop_tpu.distribution import load_distribution_module
        from pydcop_tpu.distribution.yamlformat import yaml_dist
        from pydcop_tpu.graph import constraints_hypergraph

        cg = constraints_hypergraph.build_computation_graph(dcop)
        algo = load_algorithm_module("dsa")
        dist = load_distribution_module("ilp_compref").distribute(
            cg, dcop.agents.values(),
            computation_memory=algo.computation_memory,
            communication_load=algo.communication_load,
        )
        path, ext = _os.path.splitext(args.output)
        with open(f"{path}_dist{ext}", "w", encoding="utf-8") as f:
            f.write(yaml_dist(dist))
    return rc


def _smallworld(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_smallworld

    dcop = generate_smallworld(
        n_variables=args.variables_count,
        k_neighbors=args.k_neighbors,
        rewire_p=args.rewire_p,
        n_colors=args.colors_count,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _routing(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_routing

    dcop = generate_routing(
        n_tasks=args.tasks_count,
        n_slots=args.slots_count,
        tasks_per_resource=args.tasks_per_resource,
        p_soft=args.p_soft,
        infeasible=args.infeasible,
        n_agents=args.agents_count,
        capacity=args.capacity,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _routing_structured(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_routing_structured

    dcop = generate_routing_structured(
        n_tasks=args.tasks_count,
        n_slots=args.slots_count,
        window=args.window,
        slot_capacity=args.slot_capacity,
        p_soft=args.p_soft,
        infeasible=args.infeasible,
        n_agents=args.agents_count,
        capacity=args.capacity,
        seed=args.seed,
    )
    return _write(args, dcop_yaml(dcop))


def _tracking(args):
    from pydcop_tpu.dcop import dcop_yaml
    from pydcop_tpu.generators import generate_tracking, tracking_scenario

    dcop = generate_tracking(
        n_sensors=args.sensors_count,
        n_targets=args.targets_count,
        radius=args.radius,
        weight=args.weight,
        n_agents=args.agents_count,
        capacity=args.capacity,
        seed=args.seed,
    )
    rc = _write(args, dcop_yaml(dcop))
    if args.steps:
        from pydcop_tpu.dcop import yaml_scenario

        text = yaml_scenario(tracking_scenario(dcop, args.steps))
        if args.output:
            import os as _os

            path, ext = _os.path.splitext(args.output)
            with open(f"{path}_scenario{ext}", "w",
                      encoding="utf-8") as f:
                f.write(text)
        else:
            sys.stdout.write("---\n" + text)
    return rc


def _agents(args):
    from pydcop_tpu.dcop import yaml_agents
    from pydcop_tpu.generators import generate_agents

    agents = generate_agents(
        args.count,
        capacity=args.capacity,
        hosting_default=args.hosting_default,
        routes_default=args.routes_default,
        seed=args.seed,
    )
    return _write(args, yaml_agents(agents))


def _scenario(args):
    from pydcop_tpu.dcop import yaml_scenario
    from pydcop_tpu.generators import generate_scenario

    if args.dcop_files:
        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(args.dcop_files)
        agent_names = list(dcop.agents)
    elif args.agents_count:
        agent_names = [f"a{i:04d}" for i in range(args.agents_count)]
    else:
        raise SystemExit("scenario: need --dcop_files or --agents_count")
    scenario = generate_scenario(
        agent_names,
        n_events=args.evts_count,
        removals_per_event=args.actions_count,
        delay=args.delay,
        seed=args.seed,
    )
    return _write(args, yaml_scenario(scenario))
