"""`pydcop_tpu analyze` — the program auditor + source lint front door.

No reference twin (docs/analysis.rst): ``program`` sweeps the budget
registry — every engine×mode cycle program lowered, its jaxpr walked,
and the measured collective/callback/dtype/constant footprint checked
against the budget DECLARED next to its cycle function — and ``lint``
runs the AST rules (tracer-hostile calls in cycle/chunk code, the
serve-tier lock-discipline race check).  Both print a JSON scorecard
and exit nonzero on any finding, so ``make analyze`` slots next to the
smokes as a fast guard tier.
"""
from __future__ import annotations

import json
import sys
from time import perf_counter


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "analyze",
        help="program auditor (declared budgets) + source lint",
    )
    sub = parser.add_subparsers(dest="analyze_cmd", required=True)

    p = sub.add_parser(
        "program",
        help="audit every registered engine cycle program against "
             "its declared budget",
    )
    p.set_defaults(func=_program)
    p.add_argument("--cell", default=None,
                   help="substring filter over registry cell names "
                        "(default: the full sweep)")
    p.add_argument("--list", action="store_true", dest="list_cells",
                   help="list registry cells and exit")

    p = sub.add_parser(
        "lint",
        help="AST lint: tracer hazards in cycle/chunk code + "
             "lock-discipline races in the serving tier",
    )
    p.set_defaults(func=_lint)
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint "
                        "(default: pydcop_tpu/)")
    p.add_argument("--rule", action="append", default=None,
                   help="restrict to one or more rule ids "
                        "(repeatable; see docs/analysis.rst)")


def _emit(payload) -> None:
    json.dump(payload, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")


def _program(args) -> int:
    from pydcop_tpu.analysis.registry import audit_all, cell_names

    if args.list_cells:
        _emit({"cells": cell_names()})
        return 0
    t0 = perf_counter()
    reports = audit_all(pattern=args.cell)
    wall = perf_counter() - t0
    findings = [
        f.to_dict() for rep in reports.values() for f in rep.findings
    ]
    _emit({
        "audited": len(reports),
        "ok": not findings,
        "findings": findings,
        "scorecard": {
            name: rep.scorecard for name, rep in reports.items()
        },
        "wall_s": round(wall, 3),
    })
    return 1 if findings else 0


def _lint(args) -> int:
    from pydcop_tpu.analysis.lint import DEFAULT_PATHS, lint_paths

    paths = args.paths or list(DEFAULT_PATHS)
    t0 = perf_counter()
    findings = lint_paths(paths, rules=args.rule)
    wall = perf_counter() - t0
    _emit({
        "paths": paths,
        "ok": not findings,
        "findings": [f.to_dict() for f in findings],
        "wall_s": round(wall, 3),
    })
    return 1 if findings else 0
