"""`pydcop_tpu graph` — computation-graph metrics.

Equivalent capability to the reference's pydcop/commands/graph.py: node and
edge counts, density, per-node degree stats for a DCOP under a given graph
model.
"""
from __future__ import annotations

from pydcop_tpu.commands._utils import output_metrics


def set_parser(subparsers):
    parser = subparsers.add_parser("graph", help="computation graph metrics")
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument(
        "-g", "--graph",
        choices=["factor_graph", "constraints_hypergraph", "pseudotree",
                 "ordered_graph"],
        required=True,
    )
    parser.add_argument("--display", action="store_true",
                        help="accepted for compatibility (no GUI backend)")
    return parser


def run_cmd(args):
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.graph import load_graph_module

    dcop = load_dcop_from_file(args.dcop_files)
    module = load_graph_module(args.graph)
    cg = module.build_computation_graph(dcop)
    degrees = [len(n.neighbors) for n in cg.nodes]
    metrics = {
        "graph": args.graph,
        "nodes_count": cg.node_count(),
        "edges_count": cg.link_count(),
        "density": cg.density(),
        "max_degree": max(degrees, default=0),
        "min_degree": min(degrees, default=0),
        "avg_degree": (sum(degrees) / len(degrees)) if degrees else 0,
        "status": "OK",
    }
    output_metrics(metrics, args.output)
    return 0
