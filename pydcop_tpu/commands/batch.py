"""`pydcop_tpu batch` — YAML-driven benchmark sweeps.

Equivalent capability to the reference's pydcop/commands/batch.py
(:117-357): problem *sets* (file lists + iterations) × *batches* (a command
template + cross-product of option values), each run as a subprocess of
this CLI.

Resume protocol (reference parity, batch.py:56-142): every job that ran
without error is registered as a ``JID:`` line in a
``progress_<batch_file>`` state file inside the output directory; on
startup, registered jobs are skipped, so an interrupted sweep (crash,
kill -9, shared-TPU preemption) resumes exactly where it stopped —
completion is recorded per JOB, not inferred from output files, so a
truncated output from a killed run is re-run rather than trusted.  When
the whole batch completes, the file is renamed
``done_<batch_file>_<date>`` (delete the progress file to re-run from
scratch).  The total job count (sets × files × iterations ×
combinations) is estimated up front (reference batch.py:159-169).

When NO progress file exists — outputs produced before the progress
protocol, or a sweep already completed and renamed to ``done_*`` —
existing output files are trusted as completed and skipped, so
re-invoking an old or finished sweep does not silently re-run and
overwrite everything; pass ``--force`` to re-run those jobs anyway.
While a progress file exists it is authoritative: an output file
without a ``JID:`` entry is an in-flight job that was killed, and is
re-run rather than trusted.

Batch definition format:

```yaml
sets:
  set1:
    path: ["instances/*.yaml"]     # glob(s)
    iterations: 2                   # repeat each file (seed varies)
batches:
  maxsum_sweep:
    command: solve                  # CLI command
    command_options:
      algo: [maxsum, dsa]           # cross-product of lists
      algo_params: ["damping:0.5"]
    global_options:
      timeout: 5
```

``--engine in-process`` routes ``solve`` jobs through the batched
vmap engine (pydcop_tpu.batch.BatchEngine) instead of forking one CLI
subprocess per job: instances are shape-bucketed and solved B at a
time with one compile per bucket, so a 1000-job sweep pays neither
1000 interpreter startups nor 1000 XLA compiles.  The JID resume
protocol is unchanged — every in-process job still registers its
``JID:`` line as its output file is written, so interrupted sweeps
resume identically in both engines.  Jobs the engine cannot express
(non-``solve`` commands, option combos beyond
algo/algo_params/cycles/seed) transparently fall back to the
subprocess path, per job.
"""
from __future__ import annotations

import datetime
import glob
import itertools
import os
import shutil
import subprocess
import sys
from typing import Any, Dict, List

import yaml


def set_parser(subparsers):
    parser = subparsers.add_parser("batch", help="run benchmark sweeps")
    parser.set_defaults(func=run_cmd)
    parser.add_argument("batch_file", help="batch definition YAML")
    parser.add_argument("--simulate", action="store_true",
                        help="print commands without running")
    parser.add_argument("--output_dir", default="batch_output")
    parser.add_argument(
        "--force", action="store_true",
        help="re-run jobs whose output file exists but has no progress "
        "entry (by default such outputs are trusted when no progress "
        "file exists)")
    parser.add_argument(
        "--engine", choices=["subprocess", "in-process"],
        default="subprocess",
        help="'in-process': route solve jobs through the batched vmap "
        "engine (one compile + one dispatch chain per shape bucket); "
        "'subprocess': one CLI subprocess per job (reference parity)")
    parser.add_argument(
        "--max-padding-waste", type=float, default=0.25,
        help="in-process bucketing: max fraction of padded array cells "
        "holding no real data before a new bucket is opened")
    parser.add_argument(
        "--compile-cache-dir", default=None,
        help="in-process: persistent XLA compile cache directory, so "
        "repeated sweeps skip recompiles across CLI invocations")
    return parser


def _option_combinations(options: Dict[str, Any]):
    keys = list(options)
    value_lists = [
        v if isinstance(v, list) else [v] for v in (options[k] for k in keys)
    ]
    for combo in itertools.product(*value_lists):
        yield dict(zip(keys, combo))


def _opt_to_cli(name: str, value) -> List[str]:
    if isinstance(value, bool):
        return [f"--{name}"] if value else []
    return [f"--{name}", str(value)]


def _iter_jobs(definition, output_dir):
    """Yield (jid, out_path, cmd, spec) for every job of the sweep, in
    a deterministic order (jid doubles as the output file stem).
    ``cmd`` is the subprocess argv; ``spec`` is the structured
    description (command / file / combo / global_options / iteration)
    the in-process engine interprets directly."""
    sets = definition.get("sets", {"default": {"path": []}})
    batches = definition.get("batches", {})
    for set_name, set_def in sets.items():
        paths = set_def.get("path", [])
        if isinstance(paths, str):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            files.extend(sorted(glob.glob(p)))
        iterations = int(set_def.get("iterations", 1))
        for batch_name, batch_def in batches.items():
            command = batch_def.get("command", "solve")
            for combo in _option_combinations(
                batch_def.get("command_options", {})
            ):
                for it in range(iterations):
                    for fn in files or [None]:
                        jid = "_".join(
                            str(x)
                            for x in [
                                set_name, batch_name,
                                os.path.basename(fn) if fn else "nofile",
                                *(f"{k}{v}" for k, v in combo.items()),
                                f"it{it}",
                            ]
                        ).replace("/", "-").replace(":", "")
                        out_path = os.path.join(output_dir, jid + ".json")
                        cmd = [sys.executable, "-m", "pydcop_tpu",
                               "--output", out_path]
                        for k, v in (
                            batch_def.get("global_options") or {}
                        ).items():
                            cmd.extend(_opt_to_cli(k, v))
                        cmd.append(command)
                        for k, v in combo.items():
                            cmd.extend(_opt_to_cli(k, v))
                        if command == "solve":
                            cmd.extend(_opt_to_cli("seed", it))
                        if fn:
                            cmd.append(fn)
                        spec = {
                            "command": command,
                            "file": fn,
                            "combo": dict(combo),
                            "global_options": dict(
                                batch_def.get("global_options") or {}
                            ),
                            "iteration": it,
                        }
                        yield jid, out_path, cmd, spec


def estimate_jobs(definition) -> int:
    """Upfront job count: sets × files × iterations × combinations
    (reference batch.py:159-169)."""
    return sum(1 for _ in _iter_jobs(definition, ""))


def _load_progress(progress_path: str) -> set:
    if not os.path.exists(progress_path):
        return set()
    with open(progress_path, encoding="utf-8") as f:
        return {
            line[5:].strip() for line in f if line.startswith("JID: ")
        }


def _register_jid(progress_path: str, jid: str) -> None:
    # append + flush per job: a kill -9 at any point loses at
    # most the in-flight work, never a completed job
    with open(progress_path, "a", encoding="utf-8") as f:
        f.write(f"JID: {jid}\n")
        f.flush()
        os.fsync(f.fileno())


#: combo keys the in-process engine can interpret; a job whose combo
#: uses anything else keeps full CLI semantics via the subprocess path
_IN_PROCESS_KEYS = {"algo", "algo_params", "cycles", "seed"}


def _run_in_process(pending, progress_path, args):
    """Route eligible solve jobs through the BatchEngine.

    Returns (remaining_jobs_for_subprocess, n_run, n_failed).  Each
    completed job writes its metrics JSON to its output path and
    registers its JID exactly like the subprocess path, so the resume
    protocol sees no difference.  Completion granularity is one engine
    call per (timeout, cycles) group: a kill mid-call re-runs that
    call's jobs on resume, never loses a registered one.
    """
    import json

    from pydcop_tpu.batch import BatchEngine, BatchItem
    from pydcop_tpu.commands._utils import NumpyEncoder, parse_algo_params
    from pydcop_tpu.dcop import load_dcop_from_file

    eligible, remaining = [], []
    for job in pending:
        _jid, _out, _cmd, spec = job
        combo = spec["combo"]
        if (
            spec["command"] == "solve"
            and spec["file"]
            and "algo" in combo
            and set(combo) <= _IN_PROCESS_KEYS
        ):
            eligible.append(job)
        else:
            remaining.append(job)

    n_run = n_failed = 0
    engine = BatchEngine(
        max_padding_waste=getattr(args, "max_padding_waste", 0.25),
        persistent_cache_dir=getattr(args, "compile_cache_dir", None),
    )
    # one engine call per (timeout, cycles) group — the engine itself
    # re-groups by algorithm+params and shape-buckets inside
    groups: dict = {}
    for job in eligible:
        _jid, _out, _cmd, spec = job
        combo = spec["combo"]
        timeout = spec["global_options"].get("timeout")
        cycles = combo.get("cycles")
        groups.setdefault(
            (timeout, cycles and int(cycles)), []
        ).append(job)

    for (timeout, cycles), jobs in sorted(
        groups.items(), key=lambda kv: str(kv[0])
    ):
        items, meta = [], []
        for jid, out_path, _cmd, spec in jobs:
            combo = spec["combo"]
            try:
                dcop = load_dcop_from_file([spec["file"]])
                ap = combo.get("algo_params")
                if ap is not None and not isinstance(ap, list):
                    ap = [str(ap)]
                params = parse_algo_params(ap) if ap else {}
                # subprocess parity: _iter_jobs appends `--seed <it>`
                # AFTER the combo options, and argparse keeps the last
                # occurrence — the iteration wins even over a combo seed
                seed = int(spec["iteration"])
                items.append(BatchItem(
                    dcop, str(combo["algo"]), algo_params=params,
                    seed=seed, label=jid,
                ))
                meta.append((jid, out_path))
            except Exception as e:
                n_failed += 1
                print(f"batch: job {jid} failed (in-process load): {e}",
                      file=sys.stderr)
        if not items:
            continue
        try:
            results = engine.solve(
                items, cycles=cycles,
                timeout=float(timeout) if timeout is not None else None,
            )
        except Exception as e:
            n_failed += len(items)
            print(f"batch: in-process engine failed ({e}); "
                  f"jobs count as failed", file=sys.stderr)
            continue
        for (jid, out_path), res in zip(meta, results):
            metrics = res.metrics()
            metrics["batch_engine"] = "in-process"
            with open(out_path, "w", encoding="utf-8") as f:
                f.write(json.dumps(metrics, sort_keys=True, indent="  ",
                                   cls=NumpyEncoder))
            n_run += 1
            _register_jid(progress_path, jid)
    print(
        f"batch: in-process engine solved {n_run} jobs "
        f"({engine.counters.counts['buckets_formed']} buckets, "
        f"{engine.cache.misses} compiles, {engine.cache.hits} cache "
        f"hits, padding waste "
        f"{engine.counters.padding_waste:.1%})"
    )
    return remaining, n_run, n_failed


def run_cmd(args):
    with open(args.batch_file, encoding="utf-8") as f:
        definition = yaml.safe_load(f)

    os.makedirs(args.output_dir, exist_ok=True)

    batch_stem = os.path.splitext(os.path.basename(args.batch_file))[0]
    progress_path = os.path.join(args.output_dir, f"progress_{batch_stem}")
    done_jobs = _load_progress(progress_path)
    # no progress file → pre-protocol outputs or a completed (renamed to
    # done_*) sweep: trust existing output files unless --force
    trust_outputs = (
        not os.path.exists(progress_path) and not getattr(
            args, "force", False)
    )

    total = estimate_jobs(definition)
    print(f"batch: {total} jobs total, {len(done_jobs)} already done "
          f"(progress file: {progress_path})")

    n_run = n_skipped = n_failed = 0
    if not args.simulate and not os.path.exists(progress_path):
        with open(progress_path, "a", encoding="utf-8") as f:
            f.write(f"{batch_stem}_{datetime.datetime.now():%Y%m%d_%H%M}\n")

    pending = []
    for jid, out_path, cmd, spec in _iter_jobs(definition, args.output_dir):
        if jid in done_jobs or (trust_outputs and os.path.exists(out_path)):
            n_skipped += 1
            continue
        if args.simulate:
            print(" ".join(cmd))
            continue
        pending.append((jid, out_path, cmd, spec))

    in_process = getattr(args, "engine", "subprocess") == "in-process"
    if in_process and pending:
        pending, ran, failed = _run_in_process(pending, progress_path, args)
        n_run += ran
        n_failed += failed

    for jid, out_path, cmd, _spec in pending:
        res = subprocess.run(cmd, check=False, capture_output=True)
        if res.returncode == 0:
            n_run += 1
            _register_jid(progress_path, jid)
        else:
            n_failed += 1
            tail = (res.stderr or b"")[-500:].decode(errors="replace")
            print(f"batch: job {jid} failed (rc={res.returncode}): {tail}",
                  file=sys.stderr)

    if not args.simulate and n_failed == 0:
        # everything ran: the progress file becomes a completion record
        done_path = os.path.join(
            args.output_dir,
            f"done_{batch_stem}_{datetime.datetime.now():%Y%m%d_%H%M}",
        )
        shutil.move(progress_path, done_path)
    print(f"batch: ran {n_run}, skipped {n_skipped}, failed {n_failed} "
          f"(outputs in {args.output_dir})")
    return 0 if n_failed == 0 else 1
