"""`pydcop_tpu batch` — YAML-driven benchmark sweeps.

Equivalent capability to the reference's pydcop/commands/batch.py
(:117-357): problem *sets* (file lists + iterations) × *batches* (a command
template + cross-product of option values), each run as a subprocess of
this CLI; simple resume (skip runs whose output file already exists).

Batch definition format:

```yaml
sets:
  set1:
    path: ["instances/*.yaml"]     # glob(s)
    iterations: 2                   # repeat each file (seed varies)
batches:
  maxsum_sweep:
    command: solve                  # CLI command
    command_options:
      algo: [maxsum, dsa]           # cross-product of lists
      algo_params: ["damping:0.5"]
    global_options:
      timeout: 5
```
"""
from __future__ import annotations

import glob
import itertools
import os
import subprocess
import sys
from typing import Any, Dict, List

import yaml


def set_parser(subparsers):
    parser = subparsers.add_parser("batch", help="run benchmark sweeps")
    parser.set_defaults(func=run_cmd)
    parser.add_argument("batch_file", help="batch definition YAML")
    parser.add_argument("--simulate", action="store_true",
                        help="print commands without running")
    parser.add_argument("--output_dir", default="batch_output")
    return parser


def _option_combinations(options: Dict[str, Any]):
    keys = list(options)
    value_lists = [
        v if isinstance(v, list) else [v] for v in (options[k] for k in keys)
    ]
    for combo in itertools.product(*value_lists):
        yield dict(zip(keys, combo))


def _opt_to_cli(name: str, value) -> List[str]:
    if isinstance(value, bool):
        return [f"--{name}"] if value else []
    return [f"--{name}", str(value)]


def run_cmd(args):
    with open(args.batch_file, encoding="utf-8") as f:
        definition = yaml.safe_load(f)

    sets = definition.get("sets", {"default": {"path": []}})
    batches = definition.get("batches", {})
    os.makedirs(args.output_dir, exist_ok=True)

    n_run, n_skipped = 0, 0
    for set_name, set_def in sets.items():
        paths = set_def.get("path", [])
        if isinstance(paths, str):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            files.extend(sorted(glob.glob(p)))
        iterations = int(set_def.get("iterations", 1))
        for batch_name, batch_def in batches.items():
            command = batch_def.get("command", "solve")
            for combo in _option_combinations(
                batch_def.get("command_options", {})
            ):
                for it in range(iterations):
                    for fn in files or [None]:
                        out_name = "_".join(
                            str(x)
                            for x in [
                                set_name, batch_name,
                                os.path.basename(fn) if fn else "nofile",
                                *(f"{k}{v}" for k, v in combo.items()),
                                f"it{it}",
                            ]
                        ).replace("/", "-").replace(":", "") + ".json"
                        out_path = os.path.join(args.output_dir, out_name)
                        if os.path.exists(out_path):
                            n_skipped += 1
                            continue
                        cmd = [sys.executable, "-m", "pydcop_tpu",
                               "--output", out_path]
                        for k, v in (
                            batch_def.get("global_options") or {}
                        ).items():
                            cmd.extend(_opt_to_cli(k, v))
                        cmd.append(command)
                        for k, v in combo.items():
                            cmd.extend(_opt_to_cli(k, v))
                        if command == "solve":
                            cmd.extend(_opt_to_cli("seed", it))
                        if fn:
                            cmd.append(fn)
                        if args.simulate:
                            print(" ".join(cmd))
                            continue
                        subprocess.run(cmd, check=False,
                                       capture_output=True)
                        n_run += 1
    print(f"batch: ran {n_run}, skipped {n_skipped} "
          f"(outputs in {args.output_dir})")
    return 0
