"""`pydcop_tpu batch` — YAML-driven benchmark sweeps.

Equivalent capability to the reference's pydcop/commands/batch.py
(:117-357): problem *sets* (file lists + iterations) × *batches* (a command
template + cross-product of option values), each run as a subprocess of
this CLI.

Resume protocol (reference parity, batch.py:56-142): every job that ran
without error is registered as a ``JID:`` line in a
``progress_<batch_file>`` state file inside the output directory; on
startup, registered jobs are skipped, so an interrupted sweep (crash,
kill -9, shared-TPU preemption) resumes exactly where it stopped —
completion is recorded per JOB, not inferred from output files, so a
truncated output from a killed run is re-run rather than trusted.  When
the whole batch completes, the file is renamed
``done_<batch_file>_<date>`` (delete the progress file to re-run from
scratch).  The total job count (sets × files × iterations ×
combinations) is estimated up front (reference batch.py:159-169).

When NO progress file exists — outputs produced before the progress
protocol, or a sweep already completed and renamed to ``done_*`` —
existing output files are trusted as completed and skipped, so
re-invoking an old or finished sweep does not silently re-run and
overwrite everything; pass ``--force`` to re-run those jobs anyway.
While a progress file exists it is authoritative: an output file
without a ``JID:`` entry is an in-flight job that was killed, and is
re-run rather than trusted.

Batch definition format:

```yaml
sets:
  set1:
    path: ["instances/*.yaml"]     # glob(s)
    iterations: 2                   # repeat each file (seed varies)
batches:
  maxsum_sweep:
    command: solve                  # CLI command
    command_options:
      algo: [maxsum, dsa]           # cross-product of lists
      algo_params: ["damping:0.5"]
    global_options:
      timeout: 5
```
"""
from __future__ import annotations

import datetime
import glob
import itertools
import os
import shutil
import subprocess
import sys
from typing import Any, Dict, List

import yaml


def set_parser(subparsers):
    parser = subparsers.add_parser("batch", help="run benchmark sweeps")
    parser.set_defaults(func=run_cmd)
    parser.add_argument("batch_file", help="batch definition YAML")
    parser.add_argument("--simulate", action="store_true",
                        help="print commands without running")
    parser.add_argument("--output_dir", default="batch_output")
    parser.add_argument(
        "--force", action="store_true",
        help="re-run jobs whose output file exists but has no progress "
        "entry (by default such outputs are trusted when no progress "
        "file exists)")
    return parser


def _option_combinations(options: Dict[str, Any]):
    keys = list(options)
    value_lists = [
        v if isinstance(v, list) else [v] for v in (options[k] for k in keys)
    ]
    for combo in itertools.product(*value_lists):
        yield dict(zip(keys, combo))


def _opt_to_cli(name: str, value) -> List[str]:
    if isinstance(value, bool):
        return [f"--{name}"] if value else []
    return [f"--{name}", str(value)]


def _iter_jobs(definition, output_dir):
    """Yield (jid, out_path, cmd) for every job of the sweep, in a
    deterministic order (jid doubles as the output file stem)."""
    sets = definition.get("sets", {"default": {"path": []}})
    batches = definition.get("batches", {})
    for set_name, set_def in sets.items():
        paths = set_def.get("path", [])
        if isinstance(paths, str):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            files.extend(sorted(glob.glob(p)))
        iterations = int(set_def.get("iterations", 1))
        for batch_name, batch_def in batches.items():
            command = batch_def.get("command", "solve")
            for combo in _option_combinations(
                batch_def.get("command_options", {})
            ):
                for it in range(iterations):
                    for fn in files or [None]:
                        jid = "_".join(
                            str(x)
                            for x in [
                                set_name, batch_name,
                                os.path.basename(fn) if fn else "nofile",
                                *(f"{k}{v}" for k, v in combo.items()),
                                f"it{it}",
                            ]
                        ).replace("/", "-").replace(":", "")
                        out_path = os.path.join(output_dir, jid + ".json")
                        cmd = [sys.executable, "-m", "pydcop_tpu",
                               "--output", out_path]
                        for k, v in (
                            batch_def.get("global_options") or {}
                        ).items():
                            cmd.extend(_opt_to_cli(k, v))
                        cmd.append(command)
                        for k, v in combo.items():
                            cmd.extend(_opt_to_cli(k, v))
                        if command == "solve":
                            cmd.extend(_opt_to_cli("seed", it))
                        if fn:
                            cmd.append(fn)
                        yield jid, out_path, cmd


def estimate_jobs(definition) -> int:
    """Upfront job count: sets × files × iterations × combinations
    (reference batch.py:159-169)."""
    return sum(1 for _ in _iter_jobs(definition, ""))


def _load_progress(progress_path: str) -> set:
    if not os.path.exists(progress_path):
        return set()
    with open(progress_path, encoding="utf-8") as f:
        return {
            line[5:].strip() for line in f if line.startswith("JID: ")
        }


def run_cmd(args):
    with open(args.batch_file, encoding="utf-8") as f:
        definition = yaml.safe_load(f)

    os.makedirs(args.output_dir, exist_ok=True)

    batch_stem = os.path.splitext(os.path.basename(args.batch_file))[0]
    progress_path = os.path.join(args.output_dir, f"progress_{batch_stem}")
    done_jobs = _load_progress(progress_path)
    # no progress file → pre-protocol outputs or a completed (renamed to
    # done_*) sweep: trust existing output files unless --force
    trust_outputs = (
        not os.path.exists(progress_path) and not getattr(
            args, "force", False)
    )

    total = estimate_jobs(definition)
    print(f"batch: {total} jobs total, {len(done_jobs)} already done "
          f"(progress file: {progress_path})")

    n_run = n_skipped = n_failed = 0
    if not args.simulate and not os.path.exists(progress_path):
        with open(progress_path, "a", encoding="utf-8") as f:
            f.write(f"{batch_stem}_{datetime.datetime.now():%Y%m%d_%H%M}\n")

    for jid, out_path, cmd in _iter_jobs(definition, args.output_dir):
        if jid in done_jobs or (trust_outputs and os.path.exists(out_path)):
            n_skipped += 1
            continue
        if args.simulate:
            print(" ".join(cmd))
            continue
        res = subprocess.run(cmd, check=False, capture_output=True)
        if res.returncode == 0:
            n_run += 1
            # append + flush per job: a kill -9 at any point loses at
            # most the in-flight job, never a completed one
            with open(progress_path, "a", encoding="utf-8") as f:
                f.write(f"JID: {jid}\n")
                f.flush()
                os.fsync(f.fileno())
        else:
            n_failed += 1
            tail = (res.stderr or b"")[-500:].decode(errors="replace")
            print(f"batch: job {jid} failed (rc={res.returncode}): {tail}",
                  file=sys.stderr)

    if not args.simulate and n_failed == 0:
        # everything ran: the progress file becomes a completion record
        done_path = os.path.join(
            args.output_dir,
            f"done_{batch_stem}_{datetime.datetime.now():%Y%m%d_%H%M}",
        )
        shutil.move(progress_path, done_path)
    print(f"batch: ran {n_run}, skipped {n_skipped}, failed {n_failed} "
          f"(outputs in {args.output_dir})")
    return 0 if n_failed == 0 else 1
