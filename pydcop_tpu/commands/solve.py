"""`pydcop_tpu solve` — single-machine solve of a static DCOP.

Equivalent capability to the reference's pydcop/commands/solve.py
(run_cmd :442-560, options doc :123-177): load YAML → build graph →
distribute → run → print the metrics JSON.  The reference's --mode
thread/process selects the actor runtime; here both modes run the tensor
path (one process IS the whole agent population), the flag is accepted for
CLI compatibility.
"""
from __future__ import annotations

import sys

from pydcop_tpu.commands._utils import (
    add_csvline,
    output_metrics,
    parse_algo_params,
    warn_process_mode,
)


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "solve", help="solve a static DCOP"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+", help="DCOP YAML file(s)")
    parser.add_argument(
        "--batch", action="store_true",
        help="treat each DCOP file as a SEPARATE instance and solve "
        "them all through the batched vmap engine (shape-bucketed, one "
        "compile per bucket) instead of merging the files into one "
        "problem; prints one metrics object per file")
    parser.add_argument(
        "--max-padding-waste", type=float, default=0.25,
        help="with --batch: bucketing waste bound (see docs/performance"
        ".rst 'Batched solving')")
    parser.add_argument(
        "--compile-cache-dir", default=None,
        help="with --batch: persistent XLA compile cache directory")
    parser.add_argument("-a", "--algo", default=None,
                        help="algorithm name (required unless --auto)")
    parser.add_argument(
        "--auto", action="store_true",
        help="let the learned portfolio pick the (algo, engine, "
        "chunk, ...) config for this instance: hard feasibility "
        "masks first, then the trained cost model's argmin "
        "(--portfolio-model), degrading to the pre-portfolio hand "
        "heuristics when no model is given; the chosen config and "
        "the predicted-vs-actual gap land in metrics['portfolio'] "
        "(docs/portfolio.rst)")
    parser.add_argument(
        "--portfolio-model", default=None,
        help="with --auto: trained cost model (.npz from "
        "'pydcop_tpu portfolio train'); omitted = heuristic fallback")
    parser.add_argument(
        "--portfolio-grid", default="default",
        choices=["default", "tiny"],
        help="with --auto: config grid to score")
    parser.add_argument(
        "-p", "--algo_params", action="append",
        help="algorithm parameter as name:value, repeatable",
    )
    parser.add_argument(
        "-d", "--distribution", default=None,
        help="distribution strategy name (computed and validated; the "
        "tensor runtime does not need a placement to solve), or a "
        "distribution YAML file — which DRIVES the solve: factors are "
        "sharded onto the device mesh by host agent (maxsum family only; "
        "other algorithms reject an explicit placement loudly)",
    )
    parser.add_argument("-m", "--mode", choices=["thread", "process"],
                        default="thread", help="accepted for compatibility")
    parser.add_argument("-c", "--collect_on",
                        choices=["value_change", "cycle_change", "period"],
                        default="value_change")
    parser.add_argument("--period", type=float, default=None)
    parser.add_argument("--run_metrics", default=None,
                        help="CSV file for run metrics")
    parser.add_argument("--end_metrics", default=None,
                        help="CSV file for end metrics")
    parser.add_argument("--delay", type=float, default=None,
                        help="accepted for compatibility")
    parser.add_argument("--uiport", type=int, default=None,
                        help="serve the GUI websocket protocol + HTTP "
                        "/state on this port (ws on port+1)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--cycles", type=int, default=None,
                        help="run exactly this many cycles")
    # boundary-compacted sharded collectives (docs/performance.rst,
    # "Boundary-compacted sharding") — meaningful on the multi-device
    # placement-driven path; the chosen path lands in metrics['shard']
    parser.add_argument("--shard-overlap",
                        choices=["off", "exact", "stale"], default=None,
                        help="sharded-engine collective path: off = "
                        "dense whole-space psum, exact = boundary-"
                        "compacted collective (bit-identical), stale = "
                        "double-buffered boundary exchange (staleness-1 "
                        "halo); default: auto by cut fraction")
    parser.add_argument("--shard-boundary-threshold", type=float,
                        default=0.5,
                        help="auto-policy cut-fraction threshold above "
                        "which the dense psum is kept (default 0.5)")
    # mixed-precision storage/wire tiers (docs/performance.rst,
    # "Mixed precision tiers") — shorthand for -p precision:<tier>
    parser.add_argument("--precision",
                        choices=["f32", "bf16", "int8"], default=None,
                        help="tensor storage/wire tier: f32 = exact "
                        "(bit-identical, default), bf16 = bfloat16 "
                        "tables + messages with f32 accumulation "
                        "(statistical), int8 = affine-quantized cost "
                        "tables (quantized; iterative engines only)")
    # sharded exact inference (docs/performance.rst "Sharded exact
    # inference") — DPOP only; shorthand for the matching -p algo params
    parser.add_argument("--dpop-budget-mb", type=float, default=None,
                        help="per-DEVICE byte budget for DPOP util "
                        "tables: instances whose tables exceed it are "
                        "tiled over the mesh along separator dimensions "
                        "(engine auto), and a typed UtilTableTooLarge "
                        "with a suggested --i-bound/shard count is "
                        "raised when even a tile is too big")
    parser.add_argument("--i-bound", type=int, default=None,
                        help="mini-bucket width bound for DPOP: when "
                        "exact inference is out of budget, buckets are "
                        "split at this many separator variables and "
                        "metrics['dpop'] reports the lower/upper bound "
                        "sandwich instead of refusing")
    parser.add_argument("--dpop-no-prune", action="store_true",
                        help="disable the cross-edge-consistency wire "
                        "pruning of the sharded DPOP sweep")
    # anytime exact search (docs/performance.rst "Frontier-batched
    # exact search"): the device-resident branch-and-bound engine for
    # the hard-instance regime (high induced width, small n) where
    # full DPOP issues a typed UtilTableTooLarge refusal
    parser.add_argument("--anytime-exact", action="store_true",
                        help="run the frontier-batched anytime "
                        "branch-and-bound engine (exact search on "
                        "device: a [B, depth] slab of partial "
                        "assignments expanded per jitted step with "
                        "mini-bucket lower bounds, incumbent + bound "
                        "read as 2 scalars per chunk).  Streams the "
                        "tightening lower <= optimum <= upper "
                        "sandwich as search.* events and terminates "
                        "with an optimality PROOF when the gap "
                        "closes; metrics land in metrics['search'].  "
                        "Default algorithm syncbb; also valid with "
                        "-a ncbb or -a dpop (shorthand for the "
                        "frontier engine); --i-bound/--dpop-budget-mb "
                        "size the bound tables")
    parser.add_argument("--frontier-width", type=int, default=0,
                        help="with --anytime-exact (or "
                        "engine:frontier): frontier slab rows B "
                        "(0 = auto); wider explores more nodes per "
                        "step, narrower spills sooner to the device "
                        "ring buffer")
    # warm repair (docs/resilience.rst "Warm repair and agent churn")
    parser.add_argument("--headroom", type=float, default=None,
                        help="build the WARM-repair engine with this "
                        "reserved headroom fraction (e.g. 0.25): live "
                        "mutations become fixed-shape buffer writes "
                        "with zero retraces; repair counters land in "
                        "metrics['repair'] (maxsum/mgm/dsa/adsa)")
    # crash resilience (docs/resilience.rst)
    # elastic device-fault tier (docs/resilience.rst, "Device loss and
    # data integrity"): a fault plan with device kinds routes the
    # solve through parallel/elastic — chunk-boundary snapshots,
    # integrity sentinels, shadow scrub and the recovery ladder
    parser.add_argument("--fault-plan", default=None,
                        help="seeded FaultPlan YAML; device kinds "
                        "(kill_device/shrink_mesh/corrupt_slab) run "
                        "the solve on the elastic sharded driver")
    parser.add_argument("--elastic", action="store_true",
                        help="force the elastic sharded driver even "
                        "without a fault plan (sentinel + scrub "
                        "coverage on a clean run)")
    parser.add_argument("--elastic-chunk", type=int, default=8,
                        help="cycles per elastic chunk boundary "
                        "(snapshot + sentinel cadence; default 8)")
    parser.add_argument("--scrub-every", type=int, default=0,
                        help="shadow-recompute scrub every K chunks "
                        "(0 = sentinel-only)")
    parser.add_argument("--elastic-min-devices", type=int, default=2,
                        help="shrink floor: below this many surviving "
                        "devices the ladder cold-repacks instead "
                        "(default 2)")
    parser.add_argument("--checkpoint", default=None,
                        help="rotating snapshot directory: solver state "
                        "is persisted every --checkpoint-every cycles "
                        "(atomic + checksummed)")
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument("--resume", action="store_true",
                        help="warm-start from the newest valid snapshot "
                        "in --checkpoint (corrupt files are skipped)")
    return parser


def run_cmd(args):
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.runtime import solve_result

    if args.anytime_exact:
        if args.auto or args.batch:
            output_metrics(
                {"status": "ERROR",
                 "error": "--anytime-exact is its own engine "
                 "selection; it does not combine with --auto or "
                 "--batch"},
                args.output,
            )
            return 1
        if args.algo is None:
            args.algo = "syncbb"
        if args.algo not in ("syncbb", "ncbb", "dpop"):
            output_metrics(
                {"status": "ERROR",
                 "error": f"--anytime-exact runs the exact-search "
                 f"family (syncbb/ncbb/dpop), not {args.algo!r}"},
                args.output,
            )
            return 1
    elif args.frontier_width and args.algo not in ("syncbb", "ncbb"):
        output_metrics(
            {"status": "ERROR",
             "error": "--frontier-width only applies with "
             "--anytime-exact or the syncbb/ncbb frontier engine"},
            args.output,
        )
        return 1
    if args.auto and args.algo:
        output_metrics(
            {"status": "ERROR",
             "error": "--auto and -a/--algo are mutually exclusive: "
             "--auto picks the algorithm itself"},
            args.output,
        )
        return 1
    if not args.auto and not args.algo:
        output_metrics(
            {"status": "ERROR",
             "error": "one of -a/--algo or --auto is required"},
            args.output,
        )
        return 1
    if args.auto:
        if (args.batch or args.distribution or args.checkpoint
                or args.resume or args.headroom is not None
                or args.dpop_budget_mb is not None
                or args.i_bound is not None or args.dpop_no_prune
                or args.fault_plan or args.elastic):
            output_metrics(
                {"status": "ERROR",
                 "error": "--auto does not combine with --batch, "
                 "--distribution, checkpointing, --headroom, "
                 "--fault-plan/--elastic or the --dpop-* shorthands; "
                 "it owns the engine configuration"},
                args.output,
            )
            return 1
        return _run_auto(args)

    if args.batch:
        if args.fault_plan or args.elastic:
            output_metrics(
                {"status": "ERROR",
                 "error": "--fault-plan/--elastic drive the elastic "
                 "sharded driver for ONE solve; they do not combine "
                 "with --batch"},
                args.output,
            )
            return 1
        return _run_batch(args)

    try:
        dcop = load_dcop_from_file(args.dcop_files)
    except Exception as e:
        output_metrics({"status": "ERROR", "error": str(e)}, args.output)
        return 1
    algo_params = parse_algo_params(args.algo_params)
    if args.precision is not None:
        algo_params.setdefault("precision", args.precision)
    if args.anytime_exact:
        # flag shorthands for the frontier engine params (the engine
        # itself is a first-class -p engine:frontier on syncbb/ncbb
        # and dpop; the flag just spells the common case)
        algo_params["engine"] = "frontier"
        if args.frontier_width and args.algo in ("syncbb", "ncbb"):
            algo_params.setdefault("frontier_width",
                                   args.frontier_width)
        if args.i_bound is not None:
            algo_params.setdefault("i_bound", args.i_bound)
        if args.dpop_budget_mb is not None:
            algo_params.setdefault("budget_mb", args.dpop_budget_mb)
    if args.algo in ("syncbb", "ncbb"):
        # the same shorthands work for the search family directly
        if args.frontier_width:
            algo_params.setdefault("frontier_width",
                                   args.frontier_width)
        if args.i_bound is not None:
            algo_params.setdefault("i_bound", args.i_bound)
        if args.dpop_budget_mb is not None:
            algo_params.setdefault("budget_mb", args.dpop_budget_mb)
    if args.algo == "dpop":
        # flag shorthands for the sharded/mini-bucket engine params
        if args.dpop_budget_mb is not None:
            algo_params.setdefault("budget_mb", args.dpop_budget_mb)
        if args.i_bound is not None:
            algo_params.setdefault("i_bound", args.i_bound)
        if args.dpop_no_prune:
            algo_params["prune"] = False
    elif (not args.anytime_exact
          and args.algo not in ("syncbb", "ncbb")
          and (args.dpop_budget_mb is not None
               or args.i_bound is not None or args.dpop_no_prune)):
        output_metrics(
            {"status": "ERROR",
             "error": "--dpop-budget-mb/--i-bound/--dpop-no-prune only "
             "apply to -a dpop (or the exact-search family)"},
            args.output,
        )
        return 1

    # no silent no-op: a reference user benchmarking thread vs process
    # would otherwise get identical numbers unexplained
    warn_process_mode(args.mode)

    distribution = args.distribution
    if distribution and (distribution.endswith(".yaml") or
                         distribution.endswith(".yml")):
        # a pre-computed distribution file DRIVES the solve: factors are
        # sharded onto devices by host agent (reference parity:
        # pydcop/commands/solve.py:483-507 runs under the placement)
        from pydcop_tpu.distribution.yamlformat import load_dist_from_file

        try:
            distribution = load_dist_from_file(distribution)
        except Exception as e:
            output_metrics(
                {"status": "ERROR",
                 "error": f"cannot load distribution: {e}"},
                args.output,
            )
            return 1

    fault_plan = None
    if args.fault_plan:
        from pydcop_tpu.runtime.faults import FaultPlan

        try:
            fault_plan = FaultPlan.from_yaml(args.fault_plan)
        except Exception as e:
            output_metrics(
                {"status": "ERROR",
                 "error": f"cannot load fault plan: {e}"},
                args.output,
            )
            return 1
    elastic_opts = None
    if args.elastic or (fault_plan is not None
                        and fault_plan.device_faults()):
        elastic_opts = {
            "chunk": args.elastic_chunk,
            "scrub_every": args.scrub_every,
            "min_devices": args.elastic_min_devices,
        }

    ui = None
    if args.uiport:
        from pydcop_tpu.runtime.events import event_bus
        from pydcop_tpu.runtime.ui import UiServer

        event_bus.enabled = True
        ui = UiServer(port=args.uiport)
        ui.start()
    try:
        res = solve_result(
            dcop,
            args.algo,
            distribution=distribution,
            timeout=args.timeout,
            cycles=args.cycles,
            algo_params=algo_params,
            seed=args.seed,
            collect_cycles=args.run_metrics is not None
            or args.collect_on == "cycle_change",
            checkpoint_dir=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            resume=args.resume,
            shard_overlap=args.shard_overlap,
            shard_boundary_threshold=args.shard_boundary_threshold,
            headroom=args.headroom,
            fault_plan=fault_plan,
            elastic=elastic_opts,
        )
    except Exception as e:
        output_metrics({"status": "ERROR", "error": str(e)}, args.output)
        return 1
    finally:
        if ui is not None:
            if "res" in locals():
                ui.update_state(**res.metrics())
            ui.stop()

    metrics = res.metrics()
    if args.run_metrics and res.history:
        for h in res.history:
            add_csvline(
                args.run_metrics, args.collect_on,
                {**metrics, **h, "status": "RUNNING"},
            )
    if args.end_metrics:
        add_csvline(args.end_metrics, args.collect_on, metrics)
    output_metrics(metrics, args.output)
    return 0 if res.status in ("FINISHED", "TIMEOUT") else 1


def _run_auto(args):
    """``solve --auto``: the learned portfolio picks the config
    (docs/portfolio.rst).  The chosen config, model provenance and
    predicted-vs-actual gap ride in metrics['portfolio']; with no
    --portfolio-model the selection is exactly the pre-portfolio hand
    heuristics (fallback=true)."""
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.portfolio.select import GRIDS, solve_auto

    try:
        dcop = load_dcop_from_file(args.dcop_files)
    except Exception as e:
        output_metrics({"status": "ERROR", "error": str(e)}, args.output)
        return 1
    warn_process_mode(args.mode)
    ui = None
    if args.uiport:
        from pydcop_tpu.runtime.events import event_bus
        from pydcop_tpu.runtime.ui import UiServer

        event_bus.enabled = True
        ui = UiServer(port=args.uiport)
        ui.start()
    try:
        res = solve_auto(
            dcop,
            model=args.portfolio_model,
            grid=GRIDS[args.portfolio_grid],
            seed=args.seed,
            timeout=args.timeout,
            cycles=args.cycles,
            collect_cycles=args.run_metrics is not None
            or args.collect_on == "cycle_change",
        )
    except Exception as e:
        output_metrics({"status": "ERROR", "error": str(e)}, args.output)
        return 1
    finally:
        if ui is not None:
            if "res" in locals():
                ui.update_state(**res.metrics())
            ui.stop()
    metrics = res.metrics()
    if args.run_metrics and res.history:
        for h in res.history:
            add_csvline(
                args.run_metrics, args.collect_on,
                {**metrics, **h, "status": "RUNNING"},
            )
    if args.end_metrics:
        add_csvline(args.end_metrics, args.collect_on, metrics)
    output_metrics(metrics, args.output)
    return 0 if res.status in ("FINISHED", "TIMEOUT") else 1


def _run_batch(args):
    """``solve --batch f1.yaml f2.yaml ...`` — the multi-instance front
    door: each file is one instance, solved through the batched vmap
    engine (pydcop_tpu.batch).  Prints a JSON object with per-file
    metrics plus the engine's bucket/cache summary."""
    from pydcop_tpu.batch import BatchEngine, BatchItem
    from pydcop_tpu.dcop import load_dcop_from_file

    if args.distribution or args.checkpoint or args.resume:
        output_metrics(
            {"status": "ERROR",
             "error": "--batch does not combine with --distribution or "
             "checkpointing; solve the instances separately"},
            args.output,
        )
        return 1
    algo_params = parse_algo_params(args.algo_params)
    warn_process_mode(args.mode)

    items, errors = [], {}
    for fn in args.dcop_files:
        try:
            items.append(BatchItem(
                load_dcop_from_file([fn]), args.algo,
                algo_params=algo_params, seed=args.seed, label=fn,
            ))
        except Exception as e:
            errors[fn] = {"status": "ERROR", "error": str(e)}

    engine = BatchEngine(
        max_padding_waste=args.max_padding_waste,
        persistent_cache_dir=args.compile_cache_dir,
    )
    try:
        results = engine.solve(
            items, cycles=args.cycles, timeout=args.timeout
        )
    except Exception as e:
        output_metrics({"status": "ERROR", "error": str(e)}, args.output)
        return 1

    per_file = dict(errors)
    for item, res in zip(items, results):
        per_file[item.label] = res.metrics()
    ok = not errors and all(
        r.status in ("FINISHED", "TIMEOUT") for r in results
    )
    output_metrics(
        {
            "status": "FINISHED" if ok else "ERROR",
            "results": per_file,
            "batch": engine.metrics(),
        },
        args.output,
    )
    return 0 if ok else 1
