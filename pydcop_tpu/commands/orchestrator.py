"""`pydcop_tpu orchestrator` — standalone orchestrator with an HTTP control
plane.

Equivalent capability to the reference's pydcop/commands/orchestrator.py
(:618 LoC HTTP orchestrator server): in the TPU framework all computations
execute on the orchestrator's device(s) — agents connect only as
*control-plane participants* (register, observe results).  This command
solves the DCOP and serves status/results over HTTP so `pydcop_tpu agent`
processes (or anything else) can poll them.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pydcop_tpu.commands._utils import output_metrics, parse_algo_params

_STATE = {"status": "INITIAL", "metrics": {}, "agents": []}
_LOCK = threading.Lock()


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _json(self, payload, code=200):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        with _LOCK:
            if self.path == "/status":
                self._json({"status": _STATE["status"],
                            "agents": _STATE["agents"]})
            elif self.path == "/metrics":
                self._json(_STATE["metrics"])
            else:
                self._json({"error": "unknown endpoint"}, 404)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        data = json.loads(self.rfile.read(length) or b"{}")
        with _LOCK:
            if self.path == "/register":
                name = data.get("agent")
                if name and name not in _STATE["agents"]:
                    _STATE["agents"].append(name)
                self._json({"registered": name})
            else:
                self._json({"error": "unknown endpoint"}, 404)


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "orchestrator", help="standalone orchestrator (HTTP control plane)"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-p", "--algo_params", action="append")
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--address", default="127.0.0.1")
    parser.add_argument("--expected_agents", type=int, default=0,
                        help="wait for this many registrations before "
                             "solving")
    parser.add_argument("--seed", type=int, default=0)
    return parser


def run_cmd(args):
    import time

    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

    dcop = load_dcop_from_file(args.dcop_files)
    server = ThreadingHTTPServer((args.address, args.port), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()

    with _LOCK:
        _STATE["status"] = "WAITING_AGENTS" if args.expected_agents \
            else "RUNNING"
    deadline = time.time() + (args.timeout or 30)
    while args.expected_agents and time.time() < deadline:
        with _LOCK:
            if len(_STATE["agents"]) >= args.expected_agents:
                break
        time.sleep(0.1)

    from pydcop_tpu.algorithms import AlgorithmDef

    algo_def = AlgorithmDef.build_with_default_params(
        args.algo, parse_algo_params(args.algo_params),
        mode=dcop.objective,
    )
    orch = VirtualOrchestrator(
        dcop, algo_def, distribution=args.distribution, seed=args.seed,
    )
    with _LOCK:
        _STATE["status"] = "RUNNING"
    res = orch.run(timeout=args.timeout)
    metrics = orch.end_metrics()
    with _LOCK:
        _STATE["status"] = res.status
        _STATE["metrics"] = metrics
    output_metrics(metrics, args.output)
    # keep serving briefly so agents can fetch the result
    time.sleep(1.0)
    server.shutdown()
    return 0
