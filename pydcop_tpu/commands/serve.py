"""`pydcop_tpu serve` — the continuous-batching solve service's CLI
front door.

Feeds a stream of jobs drawn from the given DCOP files through an
in-process :class:`~pydcop_tpu.serve.SolveService` and prints one JSON
object with per-job metrics, the serve counters, the compile-cache
scorecard and the (seeded, reproducible) arrival trace.

Arrival models:

* ``--arrival immediate`` (default): all jobs submitted up front —
  a burst, the serving twin of ``solve --batch``;
* ``--arrival poisson --rate R``: seeded Poisson arrivals at ``R``
  jobs/sec (``--arrival-seed``); the exact arrival offsets land in the
  output JSON as ``arrival_trace`` so a run can be replayed.

``--jobs N`` cycles through the files round-robin with seeds
0..N-1; the default is one job per file.  ``--journal-dir`` makes the
session crash-safe (submissions journaled, per-lane chunk-boundary
checkpoints, ``JID:`` completion lines); ``--resume`` re-queues the
journal's unfinished jobs first, re-seated at their last checkpointed
chunk boundary.  ``--uiport`` serves the GUI websocket protocol +
HTTP /state + SSE /events with the ``serve.*`` lifecycle topics
forwarded.

Overload + chaos (docs/serving.rst "Failure model and overload
behavior"): ``--max-pending`` / ``--tenant-quota`` turn on admission
control — rejected submits land in the output JSON's ``rejected`` list
with their retry-after hints, never dropped silently — and
``--fault-plan plan.yaml`` arms the seeded serve fault injector
(``make chaos-smoke`` drives the whole quarantine/supervision
machinery through it).

``--replicas N`` (N > 1) serves the same trace through the fleet tier
(docs/serving.rst "Fleet deployment and failover"): N replicated
services behind a compile-cache-signature router, per-replica journal
streaming into ``fleet.jsonl``, and failover re-seating — with a
``kill_replica`` fault in the plan, every in-flight job of the killed
replica completes on a peer bit-identically (``make fleet-smoke``),
and the output JSON's ``fleet`` section records the router state,
per-replica counters and the recovery-time objective.
"""
from __future__ import annotations

import sys
import time

from pydcop_tpu.commands._utils import output_metrics, parse_algo_params


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "serve", help="continuous-batching solve service"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="*",
                        help="DCOP YAML file(s) — the job pool")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm name")
    parser.add_argument(
        "-p", "--algo_params", action="append",
        help="algorithm parameter as name:value, repeatable",
    )
    parser.add_argument("--jobs", type=int, default=None,
                        help="total jobs to submit (default: one per "
                        "file); files are cycled round-robin, seeds "
                        "run 0..N-1")
    parser.add_argument("--arrival", choices=["immediate", "poisson"],
                        default="immediate",
                        help="arrival process for the submitted jobs")
    parser.add_argument("--rate", type=float, default=10.0,
                        help="poisson arrival rate, jobs/sec")
    parser.add_argument("--arrival-seed", type=int, default=0,
                        help="seed of the Poisson arrival process "
                        "(the trace is recorded in the output JSON)")
    parser.add_argument("--lanes", type=int, default=4,
                        help="lane (slot) count of each service bucket")
    parser.add_argument("--replicas", type=int, default=1,
                        help="solve-service replicas; > 1 serves "
                        "through the fleet tier (SolveFleet): jobs "
                        "route by compile-cache signature onto warm "
                        "replicas, a dead replica's in-flight jobs "
                        "re-seat on peers bit-identically, and the "
                        "output JSON gains a 'fleet' section "
                        "(docs/serving.rst 'Fleet deployment and "
                        "failover')")
    parser.add_argument("--processes", action="store_true",
                        help="with --replicas N > 1: each replica is "
                        "a real child PROCESS (ProcessFleet) — "
                        "socket-streamed journal, kill -9 failure "
                        "domain, shared serialized-runner artifacts "
                        "for zero-compile bring-up; requires "
                        "--journal-dir (docs/serving.rst 'Process "
                        "fleet')")
    parser.add_argument("--deadline", type=float, default=None,
                        help="per-job deadline in seconds (deadline-"
                        "pressured lanes shrink their chunks; expired "
                        "jobs complete as TIMEOUT and are counted "
                        "preempted)")
    parser.add_argument("--priority", type=int, default=0,
                        help="priority of every submitted job (higher "
                        "admits first)")
    parser.add_argument("--max-cycles", type=int, default=2000,
                        help="per-job cycle ceiling for "
                        "run-to-convergence")
    parser.add_argument("--prewarm", action="store_true",
                        help="compile bucket runners for the file "
                        "pool's shapes BEFORE starting arrivals, so "
                        "no admission pays a cold XLA compile")
    parser.add_argument("--max-pending", type=int, default=None,
                        help="bound on the not-yet-admitted queue: "
                        "submits beyond it are shed with a structured "
                        "overload error (a lower-priority queued job "
                        "is displaced instead when the arrival "
                        "outranks it)")
    parser.add_argument("--tenant-quota", type=int, default=None,
                        help="max open (unfinished) jobs per tenant; "
                        "submits over quota are rejected with a "
                        "retry-after hint")
    parser.add_argument("--fault-plan", default=None,
                        help="seeded serve fault plan YAML (chaos "
                        "injection: raise_in_step / nan_lane / "
                        "torn_journal_write / stall_tick — "
                        "docs/serving.rst 'Failure model')")
    parser.add_argument("--journal-dir", default=None,
                        help="crash-safe session journal + per-lane "
                        "chunk-boundary checkpoints")
    parser.add_argument("--resume", action="store_true",
                        help="re-queue the journal's unfinished jobs "
                        "(resumed from their last chunk boundary) "
                        "before submitting new ones")
    parser.add_argument("--uiport", type=int, default=None,
                        help="serve the GUI websocket protocol + HTTP "
                        "/state + SSE /events on this port (ws on "
                        "port+1), with serve.* events forwarded")
    parser.add_argument("--memo", action="store_true",
                        help="enable the cross-request solution cache "
                        "(docs/serving.rst 'Solution cache and "
                        "warm-start serving'): exact duplicates are "
                        "served bit-identically from the cache, "
                        "near-duplicates warm-start from the nearest "
                        "cached solution — never worse than a cold "
                        "solve.  Persisted beside the journal when "
                        "--journal-dir is given; --resume rehydrates")
    parser.add_argument("--memo-ttl", type=float, default=3600.0,
                        help="solution-cache entry time-to-live in "
                        "seconds")
    parser.add_argument("--memo-max-edits", type=int, default=8,
                        help="max factor-diff edits for a warm-start "
                        "variant hit (beyond it: cold solve)")
    parser.add_argument("--seed-period", type=int, default=None,
                        help="cycle job seeds with this period "
                        "instead of 0..N-1 — with one file, jobs i "
                        "and i+PERIOD are exact duplicates (the memo "
                        "smoke's duplicate trace)")
    return parser


def run_cmd(args):
    import numpy as np

    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.serve import SolveFleet, SolveService

    if args.resume and not args.journal_dir:
        output_metrics(
            {"status": "ERROR",
             "error": "--resume requires --journal-dir"},
            args.output,
        )
        return 1
    if args.resume and args.replicas > 1:
        output_metrics(
            {"status": "ERROR",
             "error": "--resume is a single-service flag; a fleet "
                      "re-seats a dead replica's jobs on live peers "
                      "instead of restarting"},
            args.output,
        )
        return 1
    algo_params = parse_algo_params(args.algo_params)

    pool, errors = [], {}
    for fn in args.dcop_files:
        try:
            pool.append((fn, load_dcop_from_file([fn])))
        except Exception as e:
            errors[fn] = {"status": "ERROR", "error": str(e)}
    if errors and not pool:
        output_metrics(
            {"status": "ERROR", "results": errors}, args.output
        )
        return 1

    ui = None
    if args.uiport:
        from pydcop_tpu.runtime.events import event_bus
        from pydcop_tpu.runtime.ui import UiServer

        event_bus.enabled = True
        ui = UiServer(port=args.uiport)
        ui.start()

    fault_plan = None
    if args.fault_plan:
        from pydcop_tpu.runtime.faults import FaultPlan

        try:
            fault_plan = FaultPlan.from_yaml(args.fault_plan)
        except (OSError, ValueError) as e:
            output_metrics(
                {"status": "ERROR",
                 "error": f"bad fault plan: {e}"},
                args.output,
            )
            return 1

    memo_cfg = None
    if args.memo:
        from pydcop_tpu.serve import MemoConfig

        memo_cfg = MemoConfig(
            ttl_s=args.memo_ttl, max_edits=args.memo_max_edits,
        )

    fleet = None
    if args.replicas > 1 and args.processes:
        from pydcop_tpu.serve import ProcessFleet

        if not args.journal_dir:
            output_metrics(
                {"status": "ERROR",
                 "error": "--processes requires --journal-dir (the "
                          "socket journal, heartbeat files and shared "
                          "artifact store live there)"},
                args.output,
            )
            return 1
        fleet = ProcessFleet(
            replicas=args.replicas,
            lanes=args.lanes,
            max_cycles=args.max_cycles,
            journal_dir=args.journal_dir,
            max_pending=args.max_pending,
            tenant_quota=args.tenant_quota,
            fault_plan=fault_plan,
            memo=memo_cfg,
        )
        fleet.wait_ready()
        service = fleet
    elif args.replicas > 1:
        fleet = SolveFleet(
            replicas=args.replicas,
            lanes=args.lanes,
            max_cycles=args.max_cycles,
            journal_dir=args.journal_dir,
            max_pending=args.max_pending,
            tenant_quota=args.tenant_quota,
            fault_plan=fault_plan,
            # the production front door shares the persistent XLA
            # cache dir across replicas and restarts
            shared_xla_cache=bool(args.journal_dir),
            memo=memo_cfg,
        )
        service = fleet  # same submit/result/stop surface below
    else:
        service = SolveService(
            lanes=args.lanes,
            max_cycles=args.max_cycles,
            journal_dir=args.journal_dir,
            max_pending=args.max_pending,
            tenant_quota=args.tenant_quota,
            fault_plan=fault_plan,
            memo=memo_cfg,
        )
    n_resumed = 0
    if args.resume:
        n_resumed = service.resume()
    if args.prewarm and pool:
        # a process fleet ships prewarms by source path (the DCOP
        # objects live in the children); everything else takes objects
        heads = ([fn for fn, _dcop in pool]
                 if args.replicas > 1 and args.processes
                 else [dcop for _fn, dcop in pool])
        service.prewarm(
            [(h, args.algo, algo_params) for h in heads], block=True,
        )
    service.start()

    # arrival schedule (recorded for reproducibility)
    n_jobs = args.jobs if args.jobs is not None else len(pool)
    offsets = [0.0] * n_jobs
    if args.arrival == "poisson" and n_jobs:
        rng = np.random.default_rng(args.arrival_seed)
        inter = rng.exponential(1.0 / max(args.rate, 1e-9), n_jobs)
        inter[0] = 0.0
        offsets = [float(x) for x in np.cumsum(inter)]
    trace = [round(o, 6) for o in offsets]

    from pydcop_tpu.serve import ServeError, ServiceOverloaded

    jids, rejected = [], []
    t0 = time.monotonic()
    for i in range(n_jobs):
        fn, dcop = pool[i % len(pool)] if pool else (None, None)
        if dcop is None:
            break
        wait = offsets[i] - (time.monotonic() - t0)
        if wait > 0:
            time.sleep(wait)
        seed = i if args.seed_period is None else i % args.seed_period
        try:
            jids.append(service.submit(
                dcop, args.algo, algo_params=algo_params, seed=seed,
                priority=args.priority, deadline_s=args.deadline,
                label=f"{fn}:{i}", source_file=fn,
            ))
        except ServeError as e:
            # admission control said no: a structured, recorded
            # rejection — never a silent drop
            rej = {"label": f"{fn}:{i}", "error": str(e)}
            if isinstance(e, ServiceOverloaded):
                rej.update(e.to_dict())
            rejected.append(rej)

    # resumed jobs are part of the session too
    all_jids = sorted(
        set(jids) | {j for j in service._jobs if args.resume}
    )
    per_job = dict(errors)
    ok = True
    try:
        for jid in all_jids:
            try:
                res = service.result(jid, timeout=args.timeout)
            except TimeoutError:
                per_job[jid] = {"status": "TIMEOUT",
                                "error": "service timeout"}
                ok = False
                continue
            except ServeError as e:
                per_job[jid] = {"status": "ERROR", "error": str(e)}
                ok = False
                continue
            job = service._jobs[jid]
            m = res.metrics()
            m["tenant"] = job.tenant
            m["label"] = job.label
            # fleet jobs carry re-seat provenance instead of a resumed
            # flag; surface both through the same key
            m["resumed"] = bool(
                getattr(job, "resumed", False)
                or (m.get("serve") or {}).get("resumed")
            )
            per_job[jid] = m
            if res.status not in ("FINISHED", "TIMEOUT"):
                ok = False
    finally:
        service.stop(drain=False)
        if ui is not None:
            ui.stop()

    payload = {
        "status": "FINISHED" if ok and not errors else "ERROR",
        "results": per_job,
        "arrival": {
            "model": args.arrival,
            "rate": args.rate,
            "seed": args.arrival_seed,
            "trace": trace,
        },
        "rejected": rejected,
        "resumed_jobs": n_resumed,
    }
    if fleet is not None:
        payload["fleet"] = fleet.metrics()
    else:
        payload["serve"] = service.metrics()
    output_metrics(payload, args.output)
    return 0 if ok and not errors else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(run_cmd(None))
