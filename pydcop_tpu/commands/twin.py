"""`pydcop_tpu twin` — the city-scale digital-twin scenario
(docs/scenarios.rst).

Runs the combined sustained scenario — seeded Poisson multi-tenant
traffic with gold/silver/bronze deadline tiers through a replicated
solve fleet, concurrent warm-repair churn against a live problem, a
combined chaos plan (fleet + serve + churn fault kinds), optional
``--auto`` portfolio selection — and prints the SLO scorecard as ONE
JSON object: per-tier deadline attainment and p99, shed rate,
time-to-recover-cost per mutation, RTO per injected kill, and the
degradation ladder's rung audit.

The run is tick-driven and fully seeded: the same flags replay the
same scenario.  ``--no-ladder`` keeps the identical scenario but never
escalates the guardrail ladder — the honest A/B arm
(``make bench-twin`` runs both and pins that the ladder is what holds
the gold floor).  ``--no-chaos`` / ``--no-churn`` switch pressures off
individually.

Exit status is 0 when every submitted job reached a terminal state and
(when chaos injected a kill) every recovery completed with a finite
RTO.
"""
from __future__ import annotations

import sys

from pydcop_tpu.commands._utils import output_metrics


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "twin", help="city-scale digital-twin SLO scenario"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("--jobs", type=int, default=12,
                        help="tenant jobs in the traffic stream")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--lanes", type=int, default=4,
                        help="lane (slot) count per service bucket")
    parser.add_argument("--seed", type=int, default=0,
                        help="seeds traffic, tiers, chaos and churn")
    parser.add_argument("-a", "--algo", default="mgm",
                        help="traffic algorithm (mgm keeps results "
                        "chunk-independent, the bit-identity anchor)")
    parser.add_argument("--auto", action="store_true",
                        help="pick each instance's config through the "
                        "portfolio selector (heuristic fallback when "
                        "no model is trained); chosen configs land in "
                        "the scorecard")
    parser.add_argument("--max-cycles", type=int, default=200)
    parser.add_argument("--gold-deadline", type=float, default=30.0)
    parser.add_argument("--silver-deadline", type=float, default=10.0)
    parser.add_argument("--bronze-deadline", type=float, default=20.0)
    parser.add_argument("--mutations", type=int, default=10,
                        help="live-problem churn events (tracking "
                        "target-walk steps + jitter edits)")
    parser.add_argument("--live-vars", type=int, default=100,
                        help="live problem size (a square sensor-grid "
                        "count for the tracking twin)")
    parser.add_argument("--kill-tick", type=int, default=8,
                        help="supervisor tick of the injected "
                        "kill_replica (chaos plan)")
    parser.add_argument("--fault-plan", default=None,
                        help="explicit chaos plan YAML (default: the "
                        "built-in combined plan; validated against "
                        "the fault-kind catalog)")
    parser.add_argument("--no-chaos", action="store_true")
    parser.add_argument("--no-churn", action="store_true")
    parser.add_argument("--no-ladder", action="store_true",
                        help="score the identical scenario with the "
                        "guardrail ladder disabled (the A/B arm)")
    parser.add_argument("--max-ticks", type=int, default=5000)
    parser.add_argument("--journal-dir", default=None)
    parser.add_argument("--uiport", type=int, default=None,
                        help="serve the GUI websocket + SSE with "
                        "slo.*/fleet.*/serve.* events forwarded")
    return parser


def run_cmd(args):
    from pydcop_tpu.generators import (
        generate_tracking,
        tracking_scenario,
    )
    from pydcop_tpu.scenario import (
        TwinRunner,
        build_twin_traffic,
        default_chaos_plan,
        default_tiers,
    )

    ui = None
    if args.uiport:
        from pydcop_tpu.runtime.events import event_bus
        from pydcop_tpu.runtime.ui import UiServer

        event_bus.enabled = True
        ui = UiServer(port=args.uiport)
        ui.start()

    tiers = default_tiers(
        gold_deadline=args.gold_deadline,
        silver_deadline=args.silver_deadline,
        bronze_deadline=args.bronze_deadline,
    )
    jobs = build_twin_traffic(
        args.jobs, tiers, seed=args.seed, algo=args.algo,
        auto=args.auto,
    )

    fault_plan = None
    if not args.no_chaos:
        if args.fault_plan:
            from pydcop_tpu.runtime.faults import FaultPlan

            try:
                fault_plan = FaultPlan.from_yaml(args.fault_plan)
            except (OSError, ValueError) as e:
                output_metrics(
                    {"status": "ERROR",
                     "error": f"bad fault plan: {e}"},
                    args.output,
                )
                return 1
        else:
            fault_plan = default_chaos_plan(
                seed=args.seed, kill_tick=args.kill_tick,
            )

    live = scenario = None
    if not args.no_churn and args.mutations > 0:
        side = max(2, int(round(args.live_vars ** 0.5)))
        live = generate_tracking(side * side, n_targets=2,
                                 seed=args.seed + 1)
        scenario = tracking_scenario(live, args.mutations)

    twin = TwinRunner(
        jobs, tiers,
        replicas=args.replicas, lanes=args.lanes,
        max_cycles=args.max_cycles, fault_plan=fault_plan,
        journal_dir=args.journal_dir, live_dcop=live,
        live_scenario=scenario, ladder=not args.no_ladder,
    )
    try:
        card = twin.run(max_ticks=args.max_ticks)
    finally:
        if ui is not None:
            ui.stop()

    all_scored = all(j.scored for j in twin.jobs)
    kills = card["fleet"]["replicas_down"]
    recovered = kills == 0 or (
        card["rto_max_s"] is not None or card["fleet"]["jobs_reseated"] == 0
    )
    ok = all_scored and recovered
    card["status"] = "FINISHED" if ok else "ERROR"
    output_metrics(card, args.output)
    return 0 if ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(run_cmd(None))
