"""`pydcop_tpu run` — solve a dynamic DCOP with a scenario.

Equivalent capability to the reference's pydcop/commands/run.py
(run_cmd :312-446): like solve, plus a scenario event stream,
k-replication and repair on agent departures.
"""
from __future__ import annotations

from pydcop_tpu.commands._utils import (
    add_csvline,
    output_metrics,
    parse_algo_params,
    warn_process_mode,
)


def set_parser(subparsers):
    parser = subparsers.add_parser("run", help="run a dynamic DCOP")
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-p", "--algo_params", action="append")
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("-s", "--scenario", required=True,
                        help="scenario YAML file")
    parser.add_argument("-m", "--mode", choices=["thread", "process"],
                        default="thread")
    parser.add_argument("-c", "--collect_on",
                        choices=["value_change", "cycle_change", "period"],
                        default="value_change")
    parser.add_argument("--period", type=float, default=None)
    parser.add_argument("--run_metrics", default=None)
    parser.add_argument("--end_metrics", default=None)
    parser.add_argument("--replication_method", default="dist_ucs_hostingcosts",
                        help="accepted for compatibility (one method)")
    parser.add_argument("--uiport", type=int, default=None,
                        help="serve the GUI websocket protocol + HTTP "
                        "/state on this port (ws on port+1)")
    parser.add_argument("--ktarget", type=int, default=3,
                        help="replication level k")
    parser.add_argument("--replica_dist", default=None,
                        help="pre-computed replica-distribution YAML "
                        "(from `replica_dist`); skips online replication")
    parser.add_argument("--seed", type=int, default=0)
    # crash resilience (docs/resilience.rst)
    parser.add_argument("--fault-plan", default=None,
                        help="fault-plan YAML (runtime/faults.py): "
                        "kill_agent faults fire at phase boundaries and "
                        "route through the replica-repair handshake")
    parser.add_argument("--checkpoint", default=None,
                        help="rotating snapshot directory: solver state "
                        "is persisted every --checkpoint-every cycles "
                        "(atomic + checksummed)")
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument("--resume", action="store_true",
                        help="warm-start from the newest valid snapshot "
                        "in --checkpoint (corrupt files are skipped)")
    # warm repair (docs/resilience.rst "Warm repair and agent churn")
    parser.add_argument("--warm-repair", action="store_true",
                        help="route scenario mutations and agent churn "
                        "through the warm-repair layer: in-place "
                        "fixed-shape buffer writes at reserved headroom "
                        "(zero retraces; one counted repack when "
                        "exhausted) instead of cold restarts "
                        "(maxsum/maxsum_dynamic/mgm/dsa/adsa)")
    parser.add_argument("--headroom", type=float, default=0.25,
                        help="with --warm-repair: reserved inert slot "
                        "fraction of the compiled capacity (default "
                        "0.25)")
    return parser


def run_cmd(args):
    from pydcop_tpu.dcop import load_dcop_from_file, load_scenario_from_file
    from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

    dcop = load_dcop_from_file(args.dcop_files)
    scenario = load_scenario_from_file(args.scenario)
    algo_params = parse_algo_params(args.algo_params)
    warn_process_mode(args.mode)

    from pydcop_tpu.algorithms import AlgorithmDef

    algo_def = AlgorithmDef.build_with_default_params(
        args.algo, algo_params, mode=dcop.objective
    )
    fault_plan = None
    if args.fault_plan:
        from pydcop_tpu.runtime.faults import FaultPlan

        try:
            fault_plan = FaultPlan.from_yaml(args.fault_plan)
        except (OSError, ValueError) as e:
            output_metrics(
                {"status": "ERROR",
                 "error": f"cannot load fault plan: {e}"}, args.output)
            return 1
    collected = []
    orch = VirtualOrchestrator(
        dcop, algo_def, distribution=args.distribution,
        collect_on=args.collect_on, period=args.period,
        collector=(lambda t, m: collected.append((t, m)))
        if args.run_metrics else None,
        seed=args.seed,
        fault_plan=fault_plan,
        checkpoint_dir=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        auto_resume=args.resume,
        warm_repair=args.warm_repair,
        headroom=args.headroom,
    )
    orch.deploy_computations()
    if args.replica_dist:
        from pydcop_tpu.replication.yamlformat import (
            load_replica_dist_from_file,
        )

        orch.replicas = load_replica_dist_from_file(args.replica_dist)
    elif args.ktarget:
        orch.start_replication(args.ktarget)
    ui = None
    if args.uiport:
        from pydcop_tpu.runtime.events import event_bus
        from pydcop_tpu.runtime.ui import UiServer

        event_bus.enabled = True
        ui = UiServer(port=args.uiport, orchestrator=orch)
        ui.start()
    try:
        orch.run(scenario, timeout=args.timeout)
    except Exception as e:
        output_metrics({"status": "ERROR", "error": str(e)}, args.output)
        return 1
    finally:
        if ui is not None:
            ui.update_state(**orch.end_metrics())
            ui.stop()
    metrics = orch.end_metrics()
    if args.run_metrics:
        for t, m in collected:
            add_csvline(args.run_metrics, args.collect_on, m)
    if args.end_metrics:
        add_csvline(args.end_metrics, args.collect_on, metrics)
    output_metrics(metrics, args.output)
    return 0
