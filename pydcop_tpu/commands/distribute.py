"""`pydcop_tpu distribute` — compute and save a distribution.

Equivalent capability to the reference's pydcop/commands/distribute.py:
build the computation graph, run a placement strategy, output the
distribution + its cost as JSON/YAML.
"""
from __future__ import annotations

from pydcop_tpu.commands._utils import output_metrics


def set_parser(subparsers):
    parser = subparsers.add_parser("distribute",
                                   help="compute a distribution")
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument("-d", "--distribution", required=True,
                        help="distribution strategy name")
    parser.add_argument(
        "-g", "--graph", default=None,
        help="graph model (default: from --algo)",
    )
    parser.add_argument("-a", "--algo", default=None,
                        help="algorithm (for cost callbacks + graph model)")
    return parser


def run_cmd(args):
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.distribution import load_distribution_module
    from pydcop_tpu.graph import load_graph_module

    dcop = load_dcop_from_file(args.dcop_files)

    algo_module = None
    if args.algo:
        from pydcop_tpu.algorithms import load_algorithm_module

        algo_module = load_algorithm_module(args.algo)
    graph_type = args.graph or (
        algo_module.GRAPH_TYPE if algo_module else "constraints_hypergraph"
    )
    cg = load_graph_module(graph_type).build_computation_graph(dcop)

    dist_module = load_distribution_module(args.distribution)
    mem = algo_module.computation_memory if algo_module else None
    load = algo_module.communication_load if algo_module else None
    dist = dist_module.distribute(
        cg, dcop.agents.values(), hints=dcop.dist_hints,
        computation_memory=mem, communication_load=load,
    )
    result = {"distribution": dist.mapping(), "status": "OK"}
    if hasattr(dist_module, "distribution_cost"):
        try:
            result["cost"] = dist_module.distribution_cost(
                dist, cg, dcop.agents.values(),
                computation_memory=mem, communication_load=load,
            )
        except Exception:
            result["cost"] = None
    output_metrics(result, args.output)
    return 0
