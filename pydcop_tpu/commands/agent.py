"""`pydcop_tpu agent` — agent process client.

Equivalent capability to the reference's pydcop/commands/agent.py (:32-46):
in the reference, agent processes host computations and exchange algorithm
messages over HTTP.  Two modes here:

* default (control plane): computations execute as batched device kernels
  on the orchestrator; the agent registers, waits for the solve, prints
  the final metrics.  (--restart accepted for CLI compatibility.)
* ``--multihost``: the agent process IS a compute participant — one rank
  of the global device mesh (parallel/multihost.py).  All ranks load the
  same DCOP (SPMD), shard the factor graph over the global mesh, and
  exchange messages through the mesh collectives instead of HTTP — the
  true TPU-native equivalent of reference agents hosting computations.
"""
from __future__ import annotations

import json
import time
import urllib.request

from pydcop_tpu.commands._utils import output_metrics


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "agent", help="agent client for a standalone orchestrator"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("-n", "--names", nargs="+", default=None,
                        help="agent names (control-plane mode)")
    parser.add_argument("--address", default="127.0.0.1",
                        help="accepted for compatibility")
    parser.add_argument("-p", "--port", type=int, default=9001,
                        help="accepted for compatibility")
    parser.add_argument("--orchestrator", default="127.0.0.1:9000",
                        help="orchestrator address host:port")
    parser.add_argument("--restart", action="store_true")
    # --multihost: this agent is one rank of a global device mesh
    parser.add_argument("--multihost", action="store_true",
                        help="be a compute rank of a multi-process mesh "
                        "instead of a control-plane client")
    parser.add_argument("--coordinator", default="127.0.0.1:29517")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--local-devices", type=int, default=None,
                        help="force N virtual CPU devices (testing)")
    parser.add_argument("--platform", default=None,
                        help="cpu for testing; default autodetect")
    parser.add_argument("--dcop", default=None,
                        help="DCOP YAML (must be identical on all ranks)")
    parser.add_argument("--algo", default="maxsum")
    parser.add_argument("--algo_params", action="append", default=None,
                        help="repeated name:value algorithm parameters "
                        "(e.g. gdba's modifier/violation/increase_mode)")
    parser.add_argument("--cycles", type=int, default=30)
    parser.add_argument("--seed", type=int, default=0,
                        help="PRNG seed for the local-search rules "
                        "(must be identical on all ranks)")
    parser.add_argument("--shard-overlap",
                        choices=["off", "exact", "stale"], default=None,
                        help="boundary-compacted collective path for "
                        "the sharded engines (identical on all ranks); "
                        "default: auto by cut fraction — see "
                        "docs/performance.rst")
    parser.add_argument("--shard-boundary-threshold", type=float,
                        default=0.5,
                        help="auto-policy cut-fraction threshold above "
                        "which the dense psum is kept (default 0.5)")
    # crash-resilience plumbing (runtime/process.py watchdog contract)
    parser.add_argument("--heartbeat-file", default=None,
                        help="touch this file every --heartbeat-interval "
                        "seconds (coordinator stall detection)")
    parser.add_argument("--heartbeat-interval", type=float, default=0.5)
    parser.add_argument("--checkpoint-dir", default=None,
                        help="rotating snapshot directory; rank 0 saves "
                        "mesh state every --checkpoint-every cycles and "
                        "every rank auto-resumes from the latest valid "
                        "snapshot (maxsum family)")
    parser.add_argument("--checkpoint-every", type=int, default=5)
    return parser


def _resilience_hooks(args):
    """Heartbeat writer (started BEFORE the heavy jax import so the
    coordinator sees a live rank immediately), fault injector (from the
    coordinator's env channel) and checkpoint manager for this rank."""
    from pydcop_tpu.runtime.faults import (
        FaultPlan,
        HeartbeatWriter,
        RankFaultInjector,
    )

    hb = None
    if args.heartbeat_file:
        hb = HeartbeatWriter(args.heartbeat_file,
                             args.heartbeat_interval).start()
    injector = None
    plan = FaultPlan.from_env()
    if plan is not None and args.process_id is not None:
        injector = RankFaultInjector(plan, args.process_id)
    mgr = None
    if args.checkpoint_dir:
        from pydcop_tpu.runtime.checkpoint import CheckpointManager

        mgr = CheckpointManager(args.checkpoint_dir)
    return hb, injector, mgr


def run_multihost(args):
    if args.num_processes is None or args.process_id is None:
        output_metrics(
            {"status": "ERROR",
             "error": "--multihost needs --num-processes and "
             "--process-id"}, args.output)
        return 1
    if not args.dcop:
        output_metrics(
            {"status": "ERROR", "error": "--multihost needs --dcop"},
            args.output)
        return 1
    LS_RULES = ("mgm", "dsa", "dba", "gdba")
    if args.algo not in ("maxsum", "amaxsum") + LS_RULES:
        output_metrics(
            {"status": "ERROR",
             "error": f"multihost mesh execution supports the factor-"
             f"graph BP family (maxsum/amaxsum) and the local-search "
             f"family ({', '.join(LS_RULES)}), not {args.algo!r}"},
            args.output)
        return 1
    # heartbeat/injector/checkpoints must exist before the jax import +
    # rendezvous (the longest silent stretch of a rank's life)
    hb, injector, ckpt_mgr = _resilience_hooks(args)

    from pydcop_tpu.parallel.multihost import (
        init_multihost,
        run_multihost_local_search,
        run_multihost_maxsum,
        run_multihost_maxsum_resumable,
    )

    init_multihost(
        args.coordinator, args.num_processes, args.process_id,
        local_devices=args.local_devices, platform=args.platform,
    )
    from pydcop_tpu.dcop import load_dcop_from_file

    dcop = load_dcop_from_file(args.dcop)
    t0 = time.time()
    from pydcop_tpu.algorithms import DEFAULT_INFINITY

    from pydcop_tpu.commands._utils import parse_algo_params

    algo_params = parse_algo_params(getattr(args, "algo_params", None))
    resumed_from = 0
    shard_info: dict = {}
    if args.algo in LS_RULES:
        if ckpt_mgr is not None or (
                injector is not None and injector.cycle_faults_pending):
            import logging

            logging.getLogger("pydcop_tpu.agent").warning(
                "checkpoint/resume and cycle faults need message-state "
                "continuation — a maxsum-family feature; the %s rule "
                "runs unchunked (a relaunch restarts it from cycle 0, "
                "which is deterministic for the same seed)", args.algo,
            )
        values, n_devices, tensors = run_multihost_local_search(
            dcop, rule=args.algo, cycles=args.cycles,
            seed=args.seed, algo_params=algo_params,
            overlap=args.shard_overlap,
            boundary_threshold=args.shard_boundary_threshold,
            info=shard_info)
    else:
        # amaxsum: per-edge activation masks in the sharded engine (same
        # emulation as AMaxSumSolver, decorrelated per shard)
        activation = None
        if args.algo == "amaxsum":
            from pydcop_tpu.algorithms.amaxsum import DEFAULT_ACTIVATION

            activation = float(
                algo_params.get("activation", DEFAULT_ACTIVATION)
            )
        if ckpt_mgr is None and injector is None:
            values, n_devices, tensors = run_multihost_maxsum(
                dcop, cycles=args.cycles, activation=activation,
                seed=args.seed, overlap=args.shard_overlap,
                boundary_threshold=args.shard_boundary_threshold,
                info=shard_info)
        else:
            state = None
            epoch = 0
            if ckpt_mgr is not None:
                latest = ckpt_mgr.latest_valid_state()
                if latest is not None:
                    cycle, meta, arrays = latest
                    if (meta.get("algo") == args.algo
                            and meta.get("seed") == args.seed):
                        state, resumed_from = arrays, cycle
                        epoch = int(meta.get("epoch", 0))

            def on_chunk(done, sharded, q, r):
                # injection FIRST: a rank killed at this boundary leaves
                # the previous boundary's snapshot as the resume point
                if injector is not None:
                    injector.at_cycle(done)
                if (ckpt_mgr is not None and done < args.cycles
                        and done % max(1, args.checkpoint_every) == 0):
                    # the allgather below is a collective — every rank
                    # participates; only rank 0 touches the filesystem
                    arrays = sharded.state_to_host(q, r)
                    if args.process_id == 0:
                        ckpt_mgr.save_state(done, arrays, {
                            "kind": "mesh_state",
                            "algo": args.algo,
                            "seed": args.seed,
                            "epoch": getattr(sharded, "_epoch", 0),
                        })

            values, n_devices, tensors = run_multihost_maxsum_resumable(
                dcop, cycles=args.cycles, activation=activation,
                seed=args.seed, overlap=args.shard_overlap,
                boundary_threshold=args.shard_boundary_threshold,
                chunk=max(1, args.checkpoint_every),
                start_cycle=resumed_from, state=state, epoch=epoch,
                on_chunk=on_chunk, info=shard_info)
    assignment = tensors.assignment_from_indices(values)
    violation, cost = dcop.solution_cost(assignment, DEFAULT_INFINITY)
    if hb is not None:
        hb.stop()
    metrics = {
        "status": "FINISHED",
        "assignment": assignment,
        "cost": cost,
        "violation": violation,
        "cycle": args.cycles,
        "time": time.time() - t0,
        "process_id": args.process_id,
        "n_global_devices": int(n_devices),
        "resumed_from": resumed_from,
    }
    if shard_info.get("shard"):
        metrics["shard"] = shard_info["shard"]
    output_metrics(metrics, args.output)
    return 0


def _request(url: str, payload=None):
    if payload is not None:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
    else:
        req = url
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


def run_cmd(args):
    if args.multihost:
        return run_multihost(args)
    if not args.names:
        output_metrics(
            {"status": "ERROR",
             "error": "control-plane mode needs --names"}, args.output)
        return 1
    base = f"http://{args.orchestrator}"
    deadline = time.time() + (args.timeout or 60)
    # register every agent name
    registered = False
    while time.time() < deadline and not registered:
        try:
            for name in args.names:
                _request(f"{base}/register", {"agent": name})
            registered = True
        except OSError:
            time.sleep(0.5)
    if not registered:
        output_metrics({"status": "ERROR",
                        "error": "orchestrator unreachable"}, args.output)
        return 1
    # wait for the solve to finish, then print the metrics
    while time.time() < deadline:
        try:
            status = _request(f"{base}/status")["status"]
            if status in ("FINISHED", "TIMEOUT", "STOPPED", "ERROR"):
                metrics = _request(f"{base}/metrics")
                output_metrics(metrics, args.output)
                return 0
        except OSError:
            pass
        time.sleep(0.5)
    output_metrics({"status": "TIMEOUT"}, args.output)
    return 1
