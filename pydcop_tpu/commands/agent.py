"""`pydcop_tpu agent` — control-plane agent client.

Equivalent capability to the reference's pydcop/commands/agent.py (:32-46):
in the reference, agent processes host computations and exchange algorithm
messages over HTTP.  In the TPU framework computations execute as batched
device kernels on the orchestrator; agent processes participate in the
control plane only: they register with the orchestrator, wait for the
solve, and print the final metrics.  (--restart is accepted for CLI
compatibility.)
"""
from __future__ import annotations

import json
import time
import urllib.request

from pydcop_tpu.commands._utils import output_metrics


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "agent", help="agent client for a standalone orchestrator"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("-n", "--names", nargs="+", required=True)
    parser.add_argument("--address", default="127.0.0.1",
                        help="accepted for compatibility")
    parser.add_argument("-p", "--port", type=int, default=9001,
                        help="accepted for compatibility")
    parser.add_argument("--orchestrator", default="127.0.0.1:9000",
                        help="orchestrator address host:port")
    parser.add_argument("--restart", action="store_true")
    return parser


def _request(url: str, payload=None):
    if payload is not None:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
    else:
        req = url
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


def run_cmd(args):
    base = f"http://{args.orchestrator}"
    deadline = time.time() + (args.timeout or 60)
    # register every agent name
    registered = False
    while time.time() < deadline and not registered:
        try:
            for name in args.names:
                _request(f"{base}/register", {"agent": name})
            registered = True
        except OSError:
            time.sleep(0.5)
    if not registered:
        output_metrics({"status": "ERROR",
                        "error": "orchestrator unreachable"}, args.output)
        return 1
    # wait for the solve to finish, then print the metrics
    while time.time() < deadline:
        try:
            status = _request(f"{base}/status")["status"]
            if status in ("FINISHED", "TIMEOUT", "STOPPED", "ERROR"):
                metrics = _request(f"{base}/metrics")
                output_metrics(metrics, args.output)
                return 0
        except OSError:
            pass
        time.sleep(0.5)
    output_metrics({"status": "TIMEOUT"}, args.output)
    return 1
