"""`pydcop_tpu replica_dist` — compute a replica placement offline.

Equivalent capability to the reference's pydcop/commands/replica_dist.py:
given a DCOP, an algorithm and a distribution, place k replicas of every
computation and emit the mapping as a replica-distribution YAML document
(reference :219-233) that `pydcop_tpu run --replica_dist` can consume.
"""
from __future__ import annotations

import sys


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "replica_dist", help="compute replica placement"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument("-a", "--algo", required=True)
    parser.add_argument("-d", "--distribution", default="oneagent")
    parser.add_argument("-k", "--ktarget", type=int, required=True)
    return parser


def run_cmd(args):
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.distribution import load_distribution_module
    from pydcop_tpu.graph import load_graph_module
    from pydcop_tpu.replication import place_replicas

    dcop = load_dcop_from_file(args.dcop_files)
    algo_module = load_algorithm_module(args.algo)
    cg = load_graph_module(algo_module.GRAPH_TYPE).build_computation_graph(
        dcop
    )
    try:
        dist = load_distribution_module(args.distribution).distribute(
            cg, dcop.agents.values(), hints=dcop.dist_hints,
            computation_memory=algo_module.computation_memory,
            communication_load=algo_module.communication_load,
        )
    except Exception as e:
        print(f"replica_dist: cannot distribute with "
              f"'{args.distribution}': {e}", file=sys.stderr)
        return 1
    replicas = place_replicas(
        [n.name for n in cg.nodes], dist, dcop.agents.values(),
        args.ktarget,
        computation_memory=lambda c: algo_module.computation_memory(
            cg.computation(c)
        ),
    )
    from pydcop_tpu.replication.yamlformat import yaml_replica_dist

    text = yaml_replica_dist(replicas, inputs={
        "dcop": list(args.dcop_files),
        "algo": args.algo,
        "distribution": args.distribution,
        "replication": "dist_ucs_hostingcosts",
        "k": args.ktarget,
    })
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0
