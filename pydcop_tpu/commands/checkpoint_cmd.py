"""``pydcop_tpu checkpoint`` — offline checkpoint/journal hygiene.

``checkpoint scrub <dir>`` walks a journal/checkpoint tree and
verifies every artifact OFFLINE — the hygiene pass a long-lived
snapshot directory needs between runs (ISSUE 14 satellite):

* ``*.npz`` checkpoints go through the full hardened read
  (runtime/checkpoint.read_state_npz): zip integrity, schema version,
  and every per-array CRC32;
* ``*.jsonl`` journals are line-checked with the serve tier's
  torn-line discipline: an unterminated TAIL line is tolerated (a
  crash loses at most the in-flight append — counted, not corrupt),
  but an unparseable line with records after it is corruption.

Exit status 1 when any corruption was found (and left in place);
``--fix`` quarantines each bad file by renaming it to
``<name>.quarantined`` — exactly the set resume()/latest_valid_state()
would have skipped at runtime, now moved out of the snapshot rotation
so the next run never reads them at all — and exits 0.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "checkpoint",
        help="offline checkpoint/journal verification (scrub)",
    )
    actions = parser.add_subparsers(dest="action", required=True)
    scrub = actions.add_parser(
        "scrub",
        help="verify every checkpoint CRC + schema and every journal "
        "line under a directory tree; exit 1 on corruption",
    )
    scrub.add_argument("directory", help="checkpoint/journal tree root")
    scrub.add_argument(
        "--fix", action="store_true",
        help="quarantine corrupt files (rename to *.quarantined — the "
        "same files resume() would skip) and exit 0",
    )
    scrub.set_defaults(func=run_cmd)


def _scrub_npz(path: str) -> List[str]:
    from pydcop_tpu.runtime.checkpoint import read_state_npz

    try:
        read_state_npz(path)
    except ValueError as e:
        return [str(e)]
    return []


def _scrub_jsonl(path: str) -> List[str]:
    problems: List[str] = []
    with open(path, "rb") as f:
        raw = f.read()
    if not raw:
        return problems
    lines = raw.split(b"\n")
    torn_tail = lines[-1] != b""  # no trailing newline: in-flight append
    body = lines[:-1]
    for i, line in enumerate(body):
        if not line.strip():
            continue
        try:
            rec = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            rest = any(ln.strip() for ln in body[i + 1:])
            if rest or not torn_tail:
                problems.append(
                    f"line {i + 1} is not a JSON record"
                )
    return problems


def run_cmd(args) -> int:
    root = args.directory
    if not os.path.isdir(root):
        print(json.dumps({
            "status": "ERROR",
            "error": f"{root!r} is not a directory",
        }))
        return 1
    checked = 0
    torn_tails = 0
    corrupt: List[Dict[str, Any]] = []
    quarantined: List[str] = []
    for dirpath, _dirs, names in os.walk(root):
        for name in sorted(names):
            path = os.path.join(dirpath, name)
            if name.endswith(".quarantined"):
                continue
            if name.endswith(".npz"):
                problems = _scrub_npz(path)
            elif name.endswith(".jsonl"):
                problems = _scrub_jsonl(path)
                with open(path, "rb") as f:
                    data = f.read()
                if data and not data.endswith(b"\n"):
                    torn_tails += 1
            else:
                continue
            checked += 1
            if problems:
                rel = os.path.relpath(path, root)
                corrupt.append({"file": rel, "problems": problems})
                if args.fix:
                    os.replace(path, path + ".quarantined")
                    quarantined.append(rel)
    out = {
        "status": "CORRUPT" if corrupt and not args.fix else "OK",
        "checked": checked,
        "corrupt": corrupt,
        "torn_tails_tolerated": torn_tails,
        "quarantined": quarantined,
    }
    print(json.dumps(out, indent=2, sort_keys=True))
    return 1 if corrupt and not args.fix else 0
