"""Shared CLI helpers: algo-param parsing, JSON/CSV output.

Equivalent capability to the reference's pydcop/commands/_utils.py +
the NumpyEncoder/_results plumbing in pydcop/commands/solve.py:580-627.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np


class NumpyEncoder(json.JSONEncoder):
    def default(self, obj):
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        return json.JSONEncoder.default(self, obj)


def parse_algo_params(param_strs: Optional[List[str]]) -> Dict[str, Any]:
    """Parse repeated --algo_params name:value options."""
    params: Dict[str, Any] = {}
    for p in param_strs or []:
        if ":" not in p:
            raise ValueError(
                f"Invalid algo param {p!r}, expected name:value"
            )
        name, value = p.split(":", 1)
        params[name.strip()] = value.strip()
    return params


def output_metrics(metrics: Dict, output_file: Optional[str] = None) -> None:
    """Print (and optionally write) the metrics JSON, reference format:
    sorted keys, 2-space indent."""
    txt = json.dumps(metrics, sort_keys=True, indent="  ", cls=NumpyEncoder)
    if output_file:
        with open(output_file, "w", encoding="utf-8") as f:
            f.write(txt)
    print(txt)


CSV_COLUMNS = ["time", "cycle", "cost", "violation", "msg_count", "msg_size",
               "status"]


def add_csvline(csv_file: str, collect_on: str, metrics: Dict) -> None:
    """Append one metrics line to a CSV (creating the header on first
    write) — reference: pydcop/commands/_utils.py add_csvline."""
    new = not os.path.exists(csv_file)
    with open(csv_file, "a", encoding="utf-8") as f:
        if new:
            f.write(",".join(CSV_COLUMNS) + "\n")
        f.write(
            ",".join(str(metrics.get(c, "")) for c in CSV_COLUMNS) + "\n"
        )


def warn_process_mode(mode: str) -> None:
    """One-line stderr notice when --mode process is requested: both
    modes run the single-process tensor engine, and a silent no-op would
    read as identical thread-vs-process benchmark numbers with no
    explanation."""
    import sys

    if mode == "process":
        print(
            "note: --mode process runs the same single-process tensor "
            "engine as thread mode (one process IS the whole agent "
            "population); for true multi-process execution use "
            "'pydcop_tpu agent --multihost' or the library API "
            "run_local_process_dcop (spawns N localhost mesh ranks)",
            file=sys.stderr,
        )
