"""`pydcop_tpu serve-replica` — the process-fleet replica child body.

Not a user-facing front door: :class:`~pydcop_tpu.serve.ProcessFleet`
spawns this command once per replica process (docs/serving.rst
"Process fleet").  It hosts a real :class:`~pydcop_tpu.serve
.SolveService` — own scheduler thread, journal, heartbeat file,
compile cache backed by the shared ``--artifact-dir`` store — and
drives it from length-prefixed, CRC-framed command records streamed
over the ``--connect`` socket by the fleet head's
:class:`~pydcop_tpu.serve.wire.JournalHub`.

The child's fault plan arrives through the watchdog environment
protocol (``PYDCOP_TPU_FAULT_PLAN``), not a flag, so a relaunched
incarnation automatically sees the same plan with its bumped attempt
counter.  Exit codes follow the runtime/process.py taxonomy: 0 clean,
negative/KILL_EXIT_CODE retryable (the head relaunches with backoff),
anything else permanent.
"""
from __future__ import annotations

import sys


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "serve-replica",
        help="process-fleet replica child (spawned by ProcessFleet)",
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("--connect", required=True,
                        help="host:port of the fleet head's journal "
                        "hub socket")
    parser.add_argument("--name", required=True,
                        help="replica name (journal + heartbeat + "
                        "router identity)")
    parser.add_argument("--journal-dir", default=None,
                        help="this replica's crash-safe journal + "
                        "per-lane checkpoint directory")
    parser.add_argument("--heartbeat-file", default=None,
                        help="heartbeat file the head's supervisor "
                        "watches for staleness")
    parser.add_argument("--artifact-dir", default=None,
                        help="shared jax.export-style serialized "
                        "runner store: hits here serve the first job "
                        "with zero XLA compiles")
    parser.add_argument("--lanes", type=int, default=4,
                        help="lane (slot) count of each service bucket")
    parser.add_argument("--max-cycles", type=int, default=0,
                        help="per-job cycle ceiling (0: engine default)")
    parser.add_argument("--checkpoint-every", type=int, default=4,
                        help="lane checkpoint cadence in chunks")
    parser.add_argument("--max-buckets", type=int, default=None,
                        help="resident bucket-worker ceiling")
    parser.add_argument("--stats-interval", type=float, default=0.25,
                        help="seconds between counter/cache-key "
                        "snapshots streamed to the head")
    parser.add_argument("--memo", action="store_true",
                        help="enable the cross-request solution cache "
                        "(entries persisted under the journal dir and "
                        "shared fleet-wide via memo_adopt frames)")
    return parser


def run_cmd(args):
    from pydcop_tpu.runtime.faults import FaultPlan
    from pydcop_tpu.serve.procfleet import ReplicaWorker

    host, _, port = args.connect.rpartition(":")
    worker = ReplicaWorker(
        (host or "127.0.0.1", int(port)),
        args.name,
        journal_dir=args.journal_dir,
        heartbeat_path=args.heartbeat_file,
        artifact_dir=args.artifact_dir,
        lanes=args.lanes,
        max_cycles=args.max_cycles,
        checkpoint_every=args.checkpoint_every,
        max_buckets=args.max_buckets,
        fault_plan=FaultPlan.from_env(),
        stats_interval=args.stats_interval,
        memo=bool(getattr(args, "memo", False)),
    )
    return worker.run()


if __name__ == "__main__":  # pragma: no cover
    sys.exit(run_cmd(None))
