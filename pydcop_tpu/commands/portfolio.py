"""`pydcop_tpu portfolio` — the learned cost model's lifecycle.

No reference twin (docs/portfolio.rst): ``dataset`` runs the
self-labeling sweep (generators x config grid, resumable by cell
key), ``train`` fits the pure-JAX cost model with a held-out-family
evaluation report, ``eval`` re-scores an existing model, and
``select`` dry-runs the ``solve --auto`` policy on concrete YAML
instances without solving them.
"""
from __future__ import annotations


def _csv(s):
    return [p.strip() for p in str(s).split(",") if p.strip()]


def _int_csv(s):
    return [int(p) for p in _csv(s)]


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "portfolio",
        help="learned portfolio: dataset / train / eval / select",
    )
    sub = parser.add_subparsers(dest="portfolio_cmd", required=True)

    p = sub.add_parser("dataset", help="run the self-labeling sweep")
    p.set_defaults(func=_dataset)
    p.add_argument("--out", required=True,
                   help="dataset directory (rows.jsonl + dataset.npz; "
                   "append-only, resumable by cell key)")
    p.add_argument("--families", default="graphcoloring,ising,iot",
                   help="comma list of generator families (see "
                   "portfolio.dataset.FAMILIES)")
    p.add_argument("--sizes", default="6,9,12",
                   help="comma list of family size knobs")
    p.add_argument("--seeds", default="0,1",
                   help="comma list of instance seeds")
    p.add_argument("--grid", default="default",
                   choices=["default", "tiny"],
                   help="declared config grid to sweep")
    p.add_argument("--cycles", type=int, default=200,
                   help="cycle budget per iterative solve")
    p.add_argument("--cell-timeout", type=float, default=30.0,
                   help="wall cap per (instance, config) cell")
    p.add_argument("--no-resume", action="store_true",
                   help="re-run cells already present in the dataset")

    p = sub.add_parser("train", help="fit the cost model")
    p.set_defaults(func=_train)
    p.add_argument("--data", required=True, help="dataset directory")
    p.add_argument("--model", required=True,
                   help="output model file (.npz)")
    p.add_argument("--holdout", default="",
                   help="comma list of families excluded from "
                   "training and used for the ranking report")
    p.add_argument("--epochs", type=int, default=300)
    p.add_argument("--hidden", default="48,48",
                   help="comma list of hidden layer widths")
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)

    p = sub.add_parser("eval", help="re-evaluate a trained model")
    p.set_defaults(func=_eval)
    p.add_argument("--data", required=True)
    p.add_argument("--model", required=True)
    p.add_argument("--holdout", required=True,
                   help="comma list of families to report on")

    p = sub.add_parser(
        "select", help="dry-run the --auto policy (no solve)"
    )
    p.set_defaults(func=_select)
    p.add_argument("dcop_files", nargs="+")
    p.add_argument("--model", default=None,
                   help="trained model (.npz); omitted = the "
                   "heuristic fallback policy")
    p.add_argument("--grid", default="default",
                   choices=["default", "tiny"])
    return parser


def _grid(name):
    from pydcop_tpu.portfolio.select import GRIDS

    return GRIDS[name]


def _out(args, payload) -> int:
    from pydcop_tpu.commands._utils import output_metrics

    output_metrics(payload, args.output)
    return 0 if payload.get("status") != "ERROR" else 1


def _dataset(args):
    from pydcop_tpu.portfolio.dataset import run_sweep, sweep_spec

    spec = sweep_spec(
        _csv(args.families), _int_csv(args.sizes),
        _int_csv(args.seeds), _grid(args.grid),
        cycles=args.cycles, timeout_s=args.cell_timeout,
    )
    try:
        summary = run_sweep(spec, args.out,
                            resume=not args.no_resume)
    except Exception as e:
        return _out(args, {"status": "ERROR", "error": str(e)})
    return _out(args, {"status": "FINISHED", **summary})


def _train(args):
    import numpy as np

    from pydcop_tpu.portfolio.dataset import (
        PortfolioDataset,
        split_holdout,
        training_matrix,
    )
    from pydcop_tpu.portfolio.model import evaluate, train_model

    ds = PortfolioDataset(args.data)
    rows = ds.rows()
    X, y, gids, _keys = training_matrix(rows)
    if X.shape[0] == 0:
        return _out(args, {"status": "ERROR",
                           "error": f"no usable rows in {args.data}"})
    holdout = _csv(args.holdout)
    (trX, trY, tr_gids), held = split_holdout(X, y, gids, holdout)
    if trX.shape[0] == 0:
        return _out(args, {"status": "ERROR",
                           "error": "holdout excludes every row"})
    probe_rates = [
        float(r.get("probe_rate") or 0.0) for r in rows
        if r.get("probe_rate")
    ]
    meta = {
        "probe_rate": float(np.median(probe_rates)) if probe_rates
        else 0.0,
        "trained_rows": int(trX.shape[0]),
        "holdout": holdout,
    }
    model, hist = train_model(
        trX, trY, hidden=tuple(_int_csv(args.hidden)),
        epochs=args.epochs, lr=args.lr, seed=args.seed, meta=meta,
        group_ids=tr_gids,
    )
    model.save(args.model)
    report = {
        "status": "FINISHED",
        "model": args.model,
        "rows_total": int(X.shape[0]),
        "rows_trained": int(trX.shape[0]),
        "final_loss": round(hist["final_loss"], 6),
        "holdout": holdout,
    }
    if held:
        report["holdout_eval"] = evaluate(model, held)
    return _out(args, report)


def _eval(args):
    from pydcop_tpu.portfolio.dataset import (
        PortfolioDataset,
        split_holdout,
        training_matrix,
    )
    from pydcop_tpu.portfolio.model import CostModel, evaluate

    ds = PortfolioDataset(args.data)
    X, y, gids, _keys = training_matrix(ds.rows())
    _train, held = split_holdout(X, y, gids, _csv(args.holdout))
    if not held:
        return _out(args, {"status": "ERROR",
                           "error": "no held-out groups matched"})
    try:
        model = CostModel.load(args.model)
    except Exception as e:
        return _out(args, {"status": "ERROR", "error": str(e)})
    return _out(args, {"status": "FINISHED",
                       "holdout_eval": evaluate(model, held)})


def _select(args):
    from pydcop_tpu.dcop import load_dcop_from_file
    from pydcop_tpu.portfolio.select import load_model, select_config

    model = load_model(args.model)
    out = {}
    status = "FINISHED"
    for fn in args.dcop_files:
        try:
            dcop = load_dcop_from_file([fn])
            sel = select_config(dcop, grid=_grid(args.grid),
                                model=model)
            out[fn] = {
                "config": sel.config.as_dict(),
                "fallback": sel.fallback,
                "predicted_norm_time": sel.predicted_norm_time,
                "scores": sel.scores,
                "masked": sel.masked,
            }
        except Exception as e:
            out[fn] = {"status": "ERROR", "error": str(e)}
            status = "ERROR"
    return _out(args, {"status": status, "selections": out})
