"""`pydcop_tpu consolidate` — fold result JSON files into one CSV.

Equivalent capability to the reference's pydcop/commands/consolidate.py:
collect per-run JSON outputs (e.g. from `batch`) and emit a CSV with one
row per run.
"""
from __future__ import annotations

import csv
import glob
import json
import os
import sys


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "consolidate", help="fold result JSONs into a CSV"
    )
    parser.set_defaults(func=run_cmd)
    parser.add_argument("files", nargs="+",
                        help="JSON result files or globs")
    parser.add_argument("--csv_file", default=None,
                        help="output CSV (default: stdout)")
    return parser


def run_cmd(args):
    files = []
    for pattern in args.files:
        files.extend(sorted(glob.glob(pattern)))
    rows = []
    for fn in files:
        try:
            with open(fn, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        row = {"file": os.path.basename(fn)}
        for k, v in data.items():
            if isinstance(v, (str, int, float, bool)) or v is None:
                row[k] = v
            elif k == "assignment" and isinstance(v, dict):
                row[k] = ";".join(f"{a}={b}" for a, b in sorted(v.items()))
        rows.append(row)
    if not rows:
        print("consolidate: no readable results", file=sys.stderr)
        return 1
    columns = ["file"] + sorted({k for r in rows for k in r} - {"file"})
    out = open(args.csv_file, "w", newline="", encoding="utf-8") \
        if args.csv_file else sys.stdout
    try:
        w = csv.DictWriter(out, fieldnames=columns)
        w.writeheader()
        for r in rows:
            w.writerow(r)
    finally:
        if args.csv_file:
            out.close()
    return 0
