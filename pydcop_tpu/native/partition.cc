// Native graph partitioner for mesh sharding.
//
// The TPU framework's "distribution layer reborn": factors/constraints are
// assigned to device-mesh shards so that variables are shared by as few
// shards as possible (each shared variable adds a row to the psum'd
// partial-belief traffic).  This is the hot host-side step when compiling
// 10^5+-edge graphs, hence native code (the reference runs its placement
// in python — pydcop/distribution/*; at tensor-graph scale that is too
// slow).
//
// Algorithm: BFS region growing (the seed/grow scheme of multilevel
// partitioners' initial phase): repeatedly seed an unassigned max-degree
// vertex and grow the region breadth-first to the target size.  O(V + E),
// deterministic.
//
// Build: g++ -O3 -shared -fPIC partition.cc -o libdcop_partition.so
// (pydcop_tpu.native builds this lazily; python fallback exists.)

#include <algorithm>
#include <cstdint>
#include <queue>
#include <vector>

extern "C" {

// Partition an undirected graph given as an edge list.
//   edge_u, edge_v : [n_edges] vertex ids
//   out_part       : [n_vertices] receives the part id of each vertex
// Returns 0 on success.
int partition_bfs_growing(const int32_t* edge_u, const int32_t* edge_v,
                          int64_t n_edges, int32_t n_vertices,
                          int32_t n_parts, int32_t* out_part) {
  if (n_parts <= 0 || n_vertices <= 0) return 1;
  // CSR adjacency
  std::vector<int64_t> deg(n_vertices, 0);
  for (int64_t e = 0; e < n_edges; ++e) {
    if (edge_u[e] >= n_vertices || edge_v[e] >= n_vertices) return 2;
    deg[edge_u[e]]++;
    deg[edge_v[e]]++;
  }
  std::vector<int64_t> offset(n_vertices + 1, 0);
  for (int32_t v = 0; v < n_vertices; ++v) offset[v + 1] = offset[v] + deg[v];
  std::vector<int32_t> adj(offset[n_vertices]);
  std::vector<int64_t> fill(offset.begin(), offset.end() - 1);
  for (int64_t e = 0; e < n_edges; ++e) {
    adj[fill[edge_u[e]]++] = edge_v[e];
    adj[fill[edge_v[e]]++] = edge_u[e];
  }

  // vertices by decreasing degree for seed selection (stable / determ.)
  std::vector<int32_t> by_deg(n_vertices);
  for (int32_t v = 0; v < n_vertices; ++v) by_deg[v] = v;
  std::stable_sort(by_deg.begin(), by_deg.end(),
                   [&](int32_t a, int32_t b) { return deg[a] > deg[b]; });

  const int64_t target =
      (static_cast<int64_t>(n_vertices) + n_parts - 1) / n_parts;
  for (int32_t v = 0; v < n_vertices; ++v) out_part[v] = -1;
  int64_t seed_cursor = 0;
  for (int32_t p = 0; p < n_parts; ++p) {
    // find next unassigned seed (highest degree first)
    while (seed_cursor < n_vertices && out_part[by_deg[seed_cursor]] != -1)
      ++seed_cursor;
    if (seed_cursor >= n_vertices) break;
    int32_t seed = by_deg[seed_cursor];
    std::queue<int32_t> q;
    q.push(seed);
    out_part[seed] = p;
    int64_t grown = 1;
    while (!q.empty() && grown < target) {
      int32_t v = q.front();
      q.pop();
      for (int64_t i = offset[v]; i < offset[v + 1]; ++i) {
        int32_t w = adj[i];
        if (out_part[w] == -1) {
          out_part[w] = p;
          q.push(w);
          if (++grown >= target) break;
        }
      }
    }
  }
  // leftovers (disconnected remainder): round-robin to the lightest parts
  std::vector<int64_t> sizes(n_parts, 0);
  for (int32_t v = 0; v < n_vertices; ++v)
    if (out_part[v] >= 0) sizes[out_part[v]]++;
  for (int32_t v = 0; v < n_vertices; ++v) {
    if (out_part[v] == -1) {
      int32_t best = 0;
      for (int32_t p = 1; p < n_parts; ++p)
        if (sizes[p] < sizes[best]) best = p;
      out_part[v] = best;
      sizes[best]++;
    }
  }
  return 0;
}

}  // extern "C"
