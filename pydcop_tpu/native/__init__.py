"""Native (C++) host-side components with lazy compilation + ctypes
bindings.

The accelerator path is JAX/XLA; the host-side compilation steps that
dominate at 10^5+-edge scale are native C++ here (the reference's
equivalents are pure python).  Each component ships as source, is compiled
with g++ on first use into ``_build/``, and has a pure-python fallback so
the framework works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(__file__)
_BUILD_DIR = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def _compile_lib() -> Optional[str]:
    src = os.path.join(_DIR, "partition.cc")
    out = os.path.join(_BUILD_DIR, "libdcop_partition.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", src, "-o", out],
            check=True, capture_output=True, timeout=120,
        )
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LOAD_FAILED
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        path = _compile_lib()
        if path is None:
            _LOAD_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.partition_bfs_growing.restype = ctypes.c_int
            lib.partition_bfs_growing.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
            ]
            _LIB = lib
        except OSError:
            _LOAD_FAILED = True
        return _LIB


def native_available() -> bool:
    return _get_lib() is not None


def partition_vertices(
    edge_u: np.ndarray, edge_v: np.ndarray, n_vertices: int, n_parts: int
) -> Optional[np.ndarray]:
    """BFS-region-growing vertex partition (C++). Returns the per-vertex
    part array, or None when the native library is unavailable."""
    lib = _get_lib()
    if lib is None:
        return None
    eu = np.ascontiguousarray(edge_u, dtype=np.int32)
    ev = np.ascontiguousarray(edge_v, dtype=np.int32)
    out = np.empty(n_vertices, dtype=np.int32)
    rc = lib.partition_bfs_growing(
        eu.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ev.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(eu.shape[0]),
        ctypes.c_int32(n_vertices),
        ctypes.c_int32(n_parts),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return out
