"""Constraints-hypergraph model: one node per variable, constraints as
hyper-edges (the classic "one agent = one variable" DCOP view).

Equivalent capability to the reference's
pydcop/computations_graph/constraints_hypergraph.py
(VariableComputationNode :49, ConstraintLink :113, build_computation_graph
:176).  Used by dsa / adsa / dsatuto / mgm / mgm2 / dba / gdba / mixeddsa.
"""
from __future__ import annotations

from typing import List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint
from pydcop_tpu.graph.objects import ComputationGraph, ComputationNode, Link

GRAPH_TYPE = "constraints_hypergraph"


class ConstraintLink(Link):
    """Hyper-edge over all variables of one constraint."""

    def __init__(self, constraint_name: str, variable_names: List[str]):
        super().__init__(variable_names, "constraint_link")
        self._constraint_name = constraint_name

    @property
    def constraint_name(self) -> str:
        return self._constraint_name


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable, constraints: List[Constraint]):
        links = [
            ConstraintLink(c.name, [v.name for v in c.dimensions])
            for c in constraints
        ]
        super().__init__(variable.name, "VariableComputation", links)
        self._variable = variable
        self._constraints = list(constraints)

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        return list(self._constraints)


class ConstraintHyperGraph(ComputationGraph):
    pass


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[List[Variable]] = None,
    constraints: Optional[List[Constraint]] = None,
) -> ConstraintHyperGraph:
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    variables = variables or []
    constraints = constraints or []
    nodes = [
        VariableComputationNode(
            v, [c for c in constraints if v.name in c.scope_names]
        )
        for v in variables
    ]
    return ConstraintHyperGraph(GRAPH_TYPE, nodes)
