"""Computation-graph models: how a DCOP maps to communicating computations.

Equivalent capability to the reference's pydcop/computations_graph/ package:
four graph models (factor graph, constraints hypergraph, pseudo-tree, ordered
chain), each with a ``build_computation_graph(dcop)`` entry point.

In the TPU design the graph model is *also* the tensorization recipe: each
model knows how to emit padded index arrays for the kernels
(see pydcop_tpu.ops.compile).
"""
from pydcop_tpu.graph.objects import ComputationGraph, ComputationNode, Link

GRAPH_MODULES = [
    "factor_graph",
    "constraints_hypergraph",
    "pseudotree",
    "ordered_graph",
]


def load_graph_module(graph_type: str):
    import importlib

    if graph_type not in GRAPH_MODULES:
        raise ValueError(
            f"Unknown graph model {graph_type!r}; available: {GRAPH_MODULES}"
        )
    return importlib.import_module(f"pydcop_tpu.graph.{graph_type}")


__all__ = ["ComputationGraph", "ComputationNode", "Link", "GRAPH_MODULES",
           "load_graph_module"]
