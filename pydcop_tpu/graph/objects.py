"""Base objects for computation graphs.

Equivalent capability to the reference's pydcop/computations_graph/objects.py
(ComputationNode :37, Link :136, ComputationGraph :197).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

from pydcop_tpu.utils.serialization import SimpleRepr


class Link(SimpleRepr):
    """A (hyper-)edge between computation nodes, identified by name."""

    def __init__(self, nodes: Iterable[str], link_type: str = "link"):
        self._nodes = tuple(sorted(nodes))
        self._link_type = link_type

    @property
    def nodes(self) -> tuple:
        return self._nodes

    @property
    def type(self) -> str:
        return self._link_type

    def has_node(self, name: str) -> bool:
        return name in self._nodes

    def __eq__(self, other):
        return (
            isinstance(other, Link)
            and self._nodes == other._nodes
            and self._link_type == other._link_type
        )

    def __hash__(self):
        return hash((self._nodes, self._link_type))

    def __repr__(self):
        return f"Link({self._link_type!r}, {self._nodes})"


class ComputationNode(SimpleRepr):
    """A node of a computation graph: one message-passing computation.

    Subclasses attach model data (the variable, the constraint, tree links…).
    """

    def __init__(
        self,
        name: str,
        node_type: str = "node",
        links: Optional[Iterable[Link]] = None,
    ):
        self._name = name
        self._node_type = node_type
        self._links = list(links) if links else []

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._node_type

    @property
    def links(self) -> List[Link]:
        return list(self._links)

    @property
    def neighbors(self) -> List[str]:
        ns: List[str] = []
        for l in self._links:
            for n in l.nodes:
                if n != self._name and n not in ns:
                    ns.append(n)
        return ns

    def add_link(self, link: Link):
        self._links.append(link)

    def __eq__(self, other):
        return (
            isinstance(other, ComputationNode)
            and self._name == other._name
            and self._node_type == other._node_type
        )

    def __hash__(self):
        return hash((self._name, self._node_type))

    def __repr__(self):
        return f"{type(self).__name__}({self._name!r})"


class ComputationGraph:
    """A set of computation nodes + links; the unit handed to algorithms and
    to the distribution layer."""

    def __init__(
        self,
        graph_type: str,
        nodes: Optional[Iterable[ComputationNode]] = None,
    ):
        self._graph_type = graph_type
        self._nodes: Dict[str, ComputationNode] = {}
        for n in nodes or []:
            self.add_node(n)

    @property
    def graph_type(self) -> str:
        return self._graph_type

    @property
    def nodes(self) -> List[ComputationNode]:
        return list(self._nodes.values())

    def add_node(self, node: ComputationNode):
        self._nodes[node.name] = node

    def computation(self, name: str) -> ComputationNode:
        return self._nodes[name]

    def __contains__(self, name: str) -> bool:
        return name in self._nodes

    @property
    def links(self) -> List[Link]:
        seen: Set[Link] = set()
        out: List[Link] = []
        for n in self._nodes.values():
            for l in n.links:
                if l not in seen:
                    seen.add(l)
                    out.append(l)
        return out

    def neighbors(self, name: str) -> List[str]:
        return self._nodes[name].neighbors

    def node_count(self) -> int:
        return len(self._nodes)

    def link_count(self) -> int:
        return len(self.links)

    def density(self) -> float:
        n = self.node_count()
        if n < 2:
            return 0.0
        return 2 * self.link_count() / (n * (n - 1))

    def __repr__(self):
        return (
            f"ComputationGraph({self._graph_type!r}, {self.node_count()} nodes,"
            f" {self.link_count()} links)"
        )
