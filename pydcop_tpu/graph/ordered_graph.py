"""Ordered constraint graph: the constraint graph plus a total order (chain)
over variables, used by SyncBB.

Equivalent capability to the reference's
pydcop/computations_graph/ordered_graph.py (OrderLink :119,
OrderedConstraintGraph :168, build_computation_graph :182).
"""
from __future__ import annotations

from typing import List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint
from pydcop_tpu.graph.objects import ComputationGraph, ComputationNode, Link

GRAPH_TYPE = "ordered_graph"


class OrderLink(Link):
    def __init__(self, link_type: str, source: str, target: str):
        if link_type not in ("next", "previous"):
            raise ValueError(f"Invalid order link type {link_type!r}")
        self._source = source
        self._target = target
        super().__init__([source, target], link_type)

    @property
    def source(self) -> str:
        return self._source

    @property
    def target(self) -> str:
        return self._target


class OrderedVarNode(ComputationNode):
    def __init__(self, variable: Variable, constraints: List[Constraint],
                 links: List[OrderLink], position: int):
        super().__init__(variable.name, "OrderedComputation", links)
        self._variable = variable
        self._constraints = list(constraints)
        self._position = position

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        return list(self._constraints)

    @property
    def position(self) -> int:
        return self._position

    @property
    def next_node(self) -> Optional[str]:
        for l in self._links:
            if l.type == "next" and l.source == self.name:
                return l.target
        return None

    @property
    def previous_node(self) -> Optional[str]:
        for l in self._links:
            if l.type == "previous" and l.source == self.name:
                return l.target
        return None


class OrderedConstraintGraph(ComputationGraph):
    def __init__(self, nodes: List[OrderedVarNode]):
        super().__init__(GRAPH_TYPE, nodes)
        self._order = [n.name for n in
                       sorted(nodes, key=lambda n: n.position)]

    @property
    def order(self) -> List[str]:
        return list(self._order)


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[List[Variable]] = None,
    constraints: Optional[List[Constraint]] = None,
) -> OrderedConstraintGraph:
    """Chain the variables in lexical order (deterministic, like the
    reference's default ordering)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    variables = sorted(variables or [], key=lambda v: v.name)
    constraints = constraints or []
    nodes = []
    for i, v in enumerate(variables):
        links: List[OrderLink] = []
        if i > 0:
            links.append(OrderLink("previous", v.name, variables[i - 1].name))
        if i < len(variables) - 1:
            links.append(OrderLink("next", v.name, variables[i + 1].name))
        v_constraints = [c for c in constraints if v.name in c.scope_names]
        nodes.append(OrderedVarNode(v, v_constraints, links, i))
    return OrderedConstraintGraph(nodes)
