"""Pseudo-tree computation model (for DPOP / NCBB).

Equivalent capability to the reference's
pydcop/computations_graph/pseudotree.py (PseudoTreeLink :51, PseudoTreeNode
:122, _generate_dfs_tree :325, build_computation_graph :468,
_filter_relation_to_lowest_node :448).

A DFS traversal of the variables' constraint graph yields a spanning tree
where every non-tree constraint edge connects a node to one of its ancestors
(a *pseudo* parent).  Each constraint is attached to the **lowest** (deepest)
of its variables, so it is evaluated exactly once during the UTIL sweep.

TPU note: unlike the reference's token-passing distributed DFS, the tree is
built centrally on host (the reference's DFS is deterministic given the same
heuristic, so results match); the device-side work is the level-batched
UTIL/VALUE sweeps in pydcop_tpu.algorithms.dpop.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint
from pydcop_tpu.graph.objects import ComputationGraph, ComputationNode, Link

GRAPH_TYPE = "pseudotree"


class PseudoTreeLink(Link):
    """Directed, typed tree link: parent / children / pseudo_parent /
    pseudo_children."""

    def __init__(self, link_type: str, source: str, target: str):
        if link_type not in (
            "parent", "children", "pseudo_parent", "pseudo_children"
        ):
            raise ValueError(f"Invalid pseudo-tree link type {link_type!r}")
        self._source = source
        self._target = target
        # note: Link sorts nodes; source/target keep direction
        super().__init__([source, target], link_type)

    @property
    def source(self) -> str:
        return self._source

    @property
    def target(self) -> str:
        return self._target

    def __repr__(self):
        return f"PseudoTreeLink({self.type}, {self._source} -> {self._target})"


class PseudoTreeNode(ComputationNode):
    def __init__(
        self,
        variable: Variable,
        constraints: List[Constraint],
        links: List[PseudoTreeLink],
    ):
        super().__init__(variable.name, "PseudoTreeComputation", links)
        self._variable = variable
        self._constraints = list(constraints)

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        """Constraints attached to this node (lowest-node rule)."""
        return list(self._constraints)

    def _links_of(self, link_type: str) -> List[str]:
        return [
            l.target for l in self._links
            if l.type == link_type and l.source == self.name
        ]

    @property
    def parent(self) -> Optional[str]:
        ps = self._links_of("parent")
        return ps[0] if ps else None

    @property
    def children(self) -> List[str]:
        return self._links_of("children")

    @property
    def pseudo_parents(self) -> List[str]:
        return self._links_of("pseudo_parent")

    @property
    def pseudo_children(self) -> List[str]:
        return self._links_of("pseudo_children")


class ComputationPseudoTree(ComputationGraph):
    def __init__(self, nodes: List[PseudoTreeNode], roots: List[str],
                 depths: Dict[str, int]):
        super().__init__(GRAPH_TYPE, nodes)
        self._roots = list(roots)
        self._depths = dict(depths)

    @property
    def roots(self) -> List[str]:
        return list(self._roots)

    @property
    def root(self) -> str:
        return self._roots[0]

    def depth(self, name: str) -> int:
        return self._depths[name]

    @property
    def height(self) -> int:
        return max(self._depths.values(), default=0)

    def nodes_by_depth(self) -> List[List[PseudoTreeNode]]:
        """Nodes grouped by tree depth — the level schedule for batched
        UTIL/VALUE sweeps."""
        levels: List[List[PseudoTreeNode]] = [[] for _ in range(self.height + 1)]
        for n in self.nodes:
            levels[self._depths[n.name]].append(n)
        return levels

    def separators(self) -> Dict[str, Set[str]]:
        """Bottom-up separator sets: ``sep(n) = (scope of n's own
        constraints ∪ children's separators) - {n}``; every member is an
        ancestor of ``n``.  This is the shape oracle of the whole DPOP
        engine family — ``|sep(n)|`` is the UTIL-table width at ``n``,
        and the sweep compilers (ops/dpop_sweep), the separator-tiling
        planner (ops/dpop_shard) and the byte estimators all derive
        their layouts from it."""
        sep: Dict[str, Set[str]] = {}
        for lv in reversed(self.nodes_by_depth()):
            for node in lv:
                s: Set[str] = set()
                for c in node.constraints:
                    s.update(
                        v.name for v in c.dimensions
                        if v.name in self._depths
                    )
                for ch in node.children:
                    s.update(sep[ch])
                s.discard(node.name)
                sep[node.name] = s
        return sep

    @property
    def induced_width(self) -> int:
        """Max separator size over the tree — DPOP's table exponent
        (tables hold ``D^(induced_width+1)`` entries at the widest
        node)."""
        return max(
            (len(s) for s in self.separators().values()), default=0
        )


def _adjacency(
    variables: List[Variable], constraints: List[Constraint]
) -> Dict[str, Set[str]]:
    adj: Dict[str, Set[str]] = {v.name: set() for v in variables}
    for c in constraints:
        names = [v.name for v in c.dimensions if v.name in adj]
        for a in names:
            for b in names:
                if a != b:
                    adj[a].add(b)
    return adj


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[List[Variable]] = None,
    constraints: Optional[List[Constraint]] = None,
) -> ComputationPseudoTree:
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    variables = variables or []
    constraints = constraints or []
    var_map = {v.name: v for v in variables}
    adj = _adjacency(variables, constraints)

    # deterministic heuristics, as in the reference: root = most-connected
    # node (ties: lexical); DFS visits most-connected neighbors first.
    def heur(name: str) -> Tuple[int, str]:
        return (-len(adj[name]), name)

    visited: Set[str] = set()
    parent: Dict[str, Optional[str]] = {}
    children: Dict[str, List[str]] = {v: [] for v in adj}
    pseudo_parents: Dict[str, List[str]] = {v: [] for v in adj}
    pseudo_children: Dict[str, List[str]] = {v: [] for v in adj}
    depth: Dict[str, int] = {}
    roots: List[str] = []

    for start in sorted(adj, key=heur):
        if start in visited:
            continue
        roots.append(start)
        parent[start] = None
        depth[start] = 0
        # iterative DFS with ancestor tracking
        stack: List[Tuple[str, iter]] = []
        visited.add(start)
        on_path: Set[str] = {start}
        stack.append((start, iter(sorted(adj[start], key=heur))))
        while stack:
            node, it = stack[-1]
            advanced = False
            for nb in it:
                if nb not in visited:
                    visited.add(nb)
                    parent[nb] = node
                    children[node].append(nb)
                    depth[nb] = depth[node] + 1
                    on_path.add(nb)
                    stack.append((nb, iter(sorted(adj[nb], key=heur))))
                    advanced = True
                    break
                elif nb in on_path and nb != parent[node]:
                    # back edge to an ancestor → pseudo relationship
                    if nb not in pseudo_parents[node]:
                        pseudo_parents[node].append(nb)
                        pseudo_children[nb].append(node)
                # forward/cross edges within the DFS cannot occur in an
                # undirected DFS traversal
            if not advanced:
                stack.pop()
                on_path.discard(node)

    # attach each constraint to its lowest variable
    # (reference: _filter_relation_to_lowest_node, pseudotree.py:448)
    constraints_for: Dict[str, List[Constraint]] = {v: [] for v in adj}
    for c in constraints:
        names = [v.name for v in c.dimensions if v.name in adj]
        if not names:
            continue
        lowest = max(names, key=lambda n: (depth[n], n))
        constraints_for[lowest].append(c)

    nodes = []
    for name, v in var_map.items():
        links: List[PseudoTreeLink] = []
        if parent.get(name):
            links.append(PseudoTreeLink("parent", name, parent[name]))
            links.append(PseudoTreeLink("children", parent[name], name))
        for ch in children[name]:
            links.append(PseudoTreeLink("children", name, ch))
        for pp in pseudo_parents[name]:
            links.append(PseudoTreeLink("pseudo_parent", name, pp))
        for pc in pseudo_children[name]:
            links.append(PseudoTreeLink("pseudo_children", name, pc))
        nodes.append(PseudoTreeNode(v, constraints_for[name], links))

    return ComputationPseudoTree(nodes, roots, depth)


def get_dfs_relations(node: PseudoTreeNode):
    """Split a node's view of the tree for DPOP: (parent, pseudo_parents,
    children, pseudo_children, constraints) — reference pseudotree.py:178."""
    return (
        node.parent,
        node.pseudo_parents,
        node.children,
        node.pseudo_children,
        node.constraints,
    )
