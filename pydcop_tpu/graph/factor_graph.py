"""Factor-graph computation model: one node per variable AND per constraint.

Equivalent capability to the reference's
pydcop/computations_graph/factor_graph.py (FactorComputationNode :45,
VariableComputationNode :104, ComputationsFactorGraph :210,
build_computation_graph :245).  Used by maxsum / amaxsum / maxsum_dynamic.
"""
from __future__ import annotations

from typing import List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint
from pydcop_tpu.graph.objects import ComputationGraph, ComputationNode, Link

GRAPH_TYPE = "factor_graph"


class FactorGraphLink(Link):
    """A var↔factor edge."""

    def __init__(self, variable_node: str, factor_node: str):
        super().__init__([variable_node, factor_node], "var_factor")


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable, factor_names: List[str]):
        links = [FactorGraphLink(variable.name, f) for f in factor_names]
        super().__init__(variable.name, "VariableComputation", links)
        self._variable = variable

    @property
    def variable(self) -> Variable:
        return self._variable


class FactorComputationNode(ComputationNode):
    def __init__(self, factor: Constraint):
        links = [FactorGraphLink(v.name, factor.name)
                 for v in factor.dimensions]
        super().__init__(factor.name, "FactorComputation", links)
        self._factor = factor

    @property
    def factor(self) -> Constraint:
        return self._factor

    @property
    def variables(self) -> List[Variable]:
        return self._factor.dimensions


class ComputationsFactorGraph(ComputationGraph):
    def __init__(self, var_nodes, factor_nodes):
        super().__init__(GRAPH_TYPE, list(var_nodes) + list(factor_nodes))
        self.var_nodes: List[VariableComputationNode] = list(var_nodes)
        self.factor_nodes: List[FactorComputationNode] = list(factor_nodes)


def build_computation_graph(
    dcop: Optional[DCOP] = None,
    variables: Optional[List[Variable]] = None,
    constraints: Optional[List[Constraint]] = None,
) -> ComputationsFactorGraph:
    """Build the bipartite factor graph for a DCOP (or explicit lists)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    variables = variables or []
    constraints = constraints or []
    factors_for_var = {v.name: [] for v in variables}
    for c in constraints:
        for v in c.dimensions:
            if v.name in factors_for_var:
                factors_for_var[v.name].append(c.name)
    var_nodes = [
        VariableComputationNode(v, factors_for_var[v.name]) for v in variables
    ]
    factor_nodes = [FactorComputationNode(c) for c in constraints]
    return ComputationsFactorGraph(var_nodes, factor_nodes)
