"""Anytime driver of the frontier-batched exact search.

:class:`FrontierSearchSolver` is the solve-path face of the engine:
it owns the chunk loop (ONE ``[2]`` incumbent+bound read per chunk —
the PR 4 discipline), decodes the spill flag and drains/reinjects the
annex at chunk boundaries (the counted host fallback), streams the
anytime ``lower <= optimum <= upper`` sandwich as ``search.*`` events
exactly like PR 9's mini-bucket bounds, and terminates with an
optimality PROOF when the bound meets the incumbent.  It speaks the
same surface as every other solver — ``run(cycles=, timeout=,
collect_cycles=, resume=)`` returning a :class:`SolveResult` — so the
checkpoint layer (``solve --checkpoint/--resume``), the portfolio and
the CLI drive it unchanged; a *cycle* is one device chunk.

Checkpoint note: the state pytree (slab + ring + annex + incumbent)
rides the existing CRC'd container unchanged (schema v3 — a search
snapshot is just more leaves).  Rows stashed host-side by the spill
fallback are flushed back into the device inject buffer before the
run returns, so a snapshot taken between runs captures them; any
remainder is counted in ``metrics()["search"]["stash_rows"]``.
"""
from __future__ import annotations

from time import perf_counter
from typing import Any, Dict, List, Optional

import numpy as np

from pydcop_tpu.algorithms import DEFAULT_INFINITY, AlgorithmDef
from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.search.frontier import FrontierEngine
from pydcop_tpu.search.plan import (
    BIG,
    SearchPlan,
    compile_search_plan,
)

#: safety cap on open-ended runs (a *proof* loop, not a convergence
#: heuristic — hitting it means the instance needs a wider i-bound)
DEFAULT_MAX_CHUNKS = 100_000


class FrontierSearchSolver:
    """Device-resident anytime branch-and-bound over one DCOP."""

    def __init__(
        self,
        dcop,
        tree=None,
        algo_def: Optional[AlgorithmDef] = None,
        seed: int = 0,
        algo: str = "syncbb",
        frontier_width: int = 0,
        ring: int = 0,
        steps: int = 0,
        i_bound: int = 0,
        bound_budget_bytes: Optional[int] = None,
        max_chunks: int = DEFAULT_MAX_CHUNKS,
        seed_incumbent: bool = True,
    ):
        self.dcop = dcop
        self.mode = dcop.objective
        self.seed = seed
        self.infinity = DEFAULT_INFINITY
        params = dict(algo_def.params) if (
            algo_def is not None and algo_def.params
        ) else {}
        self.algo_name = algo_def.algo if algo_def is not None else algo
        self.algo_def = algo_def or AlgorithmDef(
            self.algo_name, {}, dcop.objective
        )
        B = int(frontier_width or params.get("frontier_width") or 0)
        R = int(ring or params.get("ring") or 0)
        S = int(steps or params.get("search_chunk") or 0)
        ib = int(i_bound or params.get("i_bound") or 0)
        budget_mb = float(params.get("budget_mb") or 0.0)
        self.seed_incumbent = bool(
            params.get("seed_incumbent", seed_incumbent))
        if bound_budget_bytes is None and budget_mb > 0:
            bound_budget_bytes = int(budget_mb * 2**20)
        self.max_chunks = int(max_chunks)

        self.n = len(dcop.variables)
        self.plan: Optional[SearchPlan] = None
        self.engine: Optional[FrontierEngine] = None
        if self.n:
            self.plan = compile_search_plan(
                dcop, tree=tree, i_bound=ib,
                bound_budget_bytes=bound_budget_bytes,
            )
            self.engine = FrontierEngine(
                self.plan,
                frontier_width=B or min(256, max(32, 2 * self.n)),
                ring=R,
                steps=S or 8,
            )
        self._last_state: Optional[Dict[str, Any]] = None
        self._stash: List[np.ndarray] = []   # [rows, n+3] packed f64
        self._lb_best = -np.inf              # sign-space, monotone

    # -- checkpoint surface -------------------------------------------------

    def initial_state(self) -> Dict[str, Any]:
        assert self.engine is not None
        return self.engine.initial_state()

    def trace_count(self) -> int:
        return self.engine.trace_count() if self.engine else 0

    def program_budget(self):
        assert self.engine is not None
        return self.engine.program_budget()

    # -- spill fallback -----------------------------------------------------

    def _drain_annex(self, state, counters) -> Dict[str, Any]:
        """Pull the annex rows to the host stash and clear the count —
        the counted fallback behind the bound scalar's spill flag."""
        import jax.numpy as jnp

        xc = int(np.asarray(state["x_count"]))
        counters["spill_drains"] += 1
        if xc > 0:
            rows = np.concatenate([
                np.asarray(state["x_assign"])[:xc].astype(np.float64),
                np.asarray(state["x_g"])[:xc, None].astype(np.float64),
                np.asarray(state["x_f"])[:xc, None].astype(np.float64),
                np.asarray(state["x_depth"])[:xc, None].astype(
                    np.float64),
            ], axis=1)
            self._stash.append(rows)
            counters["spill_rows"] += xc
        return {**state, "x_count": jnp.int32(0)}

    def _reinject(self, state, counters) -> Dict[str, Any]:
        """Move up to one annex-quantum of stashed rows back into the
        device inject buffer (consumed by the next chunk's first
        step)."""
        import jax.numpy as jnp

        if not self._stash or int(np.asarray(state["j_count"])) > 0:
            return state
        rows = np.concatenate(self._stash, axis=0)
        A = self.engine.shape.A
        take, rest = rows[:A], rows[A:]
        self._stash = [rest] if rest.size else []
        m, n = take.shape[0], max(self.n, 1)
        ja = np.zeros((A, n), np.int32)
        jg = np.zeros((A,), np.float32)
        jf = np.full((A,), BIG, np.float32)
        jd = np.zeros((A,), np.int32)
        ja[:m] = take[:, :n].astype(np.int32)
        jg[:m] = take[:, n].astype(np.float32)
        jf[:m] = take[:, n + 1].astype(np.float32)
        jd[:m] = take[:, n + 2].astype(np.int32)
        counters["reinjected_rows"] += m
        return {
            **state,
            "j_assign": jnp.asarray(ja), "j_g": jnp.asarray(jg),
            "j_f": jnp.asarray(jf), "j_depth": jnp.asarray(jd),
            "j_count": jnp.int32(m),
        }

    def _stash_min_f(self) -> float:
        n = max(self.n, 1)
        if not self._stash:
            return np.inf
        return float(min(r[:, n + 1].min() for r in self._stash
                         if r.size))

    def _stash_rows(self) -> int:
        return int(sum(r.shape[0] for r in self._stash))

    # -- run ----------------------------------------------------------------

    def run(self, cycles: Optional[int] = None,
            timeout: Optional[float] = None,
            collect_cycles: bool = False, resume: bool = False,
            **_kwargs) -> SolveResult:
        from pydcop_tpu.runtime.events import send_search
        from pydcop_tpu.runtime.stats import SearchCounters, \
            resolved_config

        t0 = perf_counter()
        if self.engine is None:  # no variables: trivially optimal
            violation, cost = self.dcop.solution_cost({}, self.infinity)
            return SolveResult("FINISHED", {}, cost, violation, 0, 0,
                               0.0, perf_counter() - t0)
        plan = self.plan
        runner = self.engine.chunk_runner()
        warm = resume and self._last_state is not None
        state = self._last_state if warm else self.initial_state()
        if not warm:
            self._stash = []
            self._lb_best = -np.inf
        if not warm and self.seed_incumbent:
            # seed the incumbent with one beam rollout: pruning
            # starts on the first chunk, and the anytime answer is a
            # real leaf even if best-first never reaches one
            # width grows with n: tight feasibility structure (exact
            # capacities, forbidden values) needs more surviving
            # alternatives the deeper the rollout goes
            dive_assign, dive_g = self.engine.beam_dive(
                width=max(64, 4 * self.n))
            if dive_g < BIG / 2:
                import jax.numpy as jnp

                state = {
                    **state,
                    "incumbent": jnp.float32(dive_g),
                    "best_assign": jnp.asarray(
                        dive_assign, jnp.int32),
                }
        counters = SearchCounters()
        history: List[Dict[str, Any]] = []
        status = "FINISHED"
        proved = False
        limit = cycles if cycles is not None else self.max_chunks
        chunks = 0
        U = BIG
        lb_true = upper_true = None
        while chunks < limit:
            state, stats = runner(state)
            su = np.asarray(stats)  # the per-chunk 2-scalar read
            counters["chunks"] += 1
            counters["scalar_reads"] += int(su.size)
            chunks += 1
            U = float(su[0])
            enc = float(su[1])
            # NaN bound = annex pending: the chunk publishes no bound
            # (the previous one remains valid); anything else is the
            # exact device bound, tightened by the host stash
            spilled = bool(np.isnan(enc))
            if spilled:
                state = self._drain_annex(state, counters)
                send_search("spill.drain", {
                    "chunk": chunks,
                    "stash_rows": self._stash_rows(),
                })
            else:
                lb = min(enc, self._stash_min_f(), U)
                self._lb_best = max(self._lb_best, lb)
                state = self._reinject(state, counters)
            # report in TRUE cost space: for max problems the engine's
            # sign-space sandwich flips orientation.  Until the first
            # clean (non-spill) chunk no bound has been published
            s = plan.sign
            incumbent_true = s * U if U < BIG / 2 else None
            if np.isfinite(self._lb_best):
                lo, hi = sorted((s * U, s * self._lb_best))
                lb_true, upper_true = lo, hi
                gap = max(0.0, float(U - self._lb_best))
            else:
                lo = hi = None
                gap = None
            if collect_cycles:
                history.append({
                    "cycle": chunks,
                    "cost": incumbent_true,
                    "lower_bound": lo,
                    "upper_bound": hi,
                    "gap": gap,
                    "time": perf_counter() - t0,
                })
            send_search("bounds", {
                "chunk": chunks,
                "incumbent": incumbent_true,
                "lower_bound": lo,
                "upper_bound": hi,
                "gap": gap,
                "proved": bool(self._lb_best >= U),
            })
            if self._lb_best >= U:
                proved = True
                break
            if timeout is not None and perf_counter() - t0 > timeout:
                status = "TIMEOUT"
                break
        # park any host-stashed rows back on device so checkpoints
        # taken between runs capture them
        state = self._reinject(state, counters)
        self._last_state = state

        # single end-of-run state read: incumbent assignment + counts
        best = np.asarray(state["best_assign"])
        assignment: Dict[str, Any] = {}
        for i, name in enumerate(plan.order):
            dom = plan.domain_values[i]
            idx = int(best[i]) if U < BIG / 2 else 0
            assignment[name] = dom[min(idx, len(dom) - 1)]
        violation, cost = self.dcop.solution_cost(
            assignment, self.infinity
        )
        nodes = int(np.asarray(state["nodes"]))
        wall = perf_counter() - t0
        search = dict(plan.info())
        search.update(
            frontier_width=self.engine.shape.B,
            ring=self.engine.shape.R,
            steps_per_chunk=self.engine.shape.steps,
            nodes=nodes,
            leaves=int(np.asarray(state["leaves"])),
            pruned=int(np.asarray(state["pruned"])),
            lost_rows=int(np.asarray(state["lost"])),
            nodes_per_s=round(nodes / wall, 1) if wall > 0 else 0.0,
            lower_bound=lb_true,
            upper_bound=upper_true,
            gap=(
                max(0.0, float(U - self._lb_best))
                if lb_true is not None else None
            ),
            optimal=proved,
            stash_rows=self._stash_rows(),
            **counters.as_dict(),
        )
        send_search("done", {
            "status": status, "optimal": proved, "chunks": chunks,
            "nodes": nodes, "cost": cost,
        })
        return SolveResult(
            status=status,
            assignment=assignment,
            cost=cost,
            violation=violation,
            cycle=chunks,
            msg_count=nodes,
            msg_size=float(nodes * plan.n),
            time=wall,
            history=history if collect_cycles else None,
            search=search,
            config=resolved_config(
                self.algo_name, "frontier", i_bound=plan.i_bound
            ),
        )


def build_frontier_solver(dcop, computation_graph=None, algo_def=None,
                          seed: int = 0, algo: str = "syncbb",
                          **overrides) -> FrontierSearchSolver:
    """Shared constructor for the syncbb/ncbb ``engine=frontier``
    route and the dpop auto-ladder tier; ``computation_graph`` is
    reused when it already is a pseudo-tree."""
    tree = (
        computation_graph
        if computation_graph is not None
        and hasattr(computation_graph, "roots")
        else None
    )
    return FrontierSearchSolver(
        dcop, tree=tree, algo_def=algo_def, seed=seed, algo=algo,
        **overrides,
    )
