"""Host-side compile pass of the frontier-batched exact search.

Everything the device engine needs is flattened here into fixed-shape
gather tables, once per problem:

* a **search order** — the pseudo-tree DFS preorder (deterministic,
  the same heuristic the DPOP family roots on), with every constraint
  attached at its DEEPEST variable in the order, so a constraint is
  scored exactly once: at the step that assigns its last open variable;
* **increment tables** — per depth ``k``, the constraints attached
  there as one flat f32 buffer plus (offset, stride, scope-position)
  index arrays, so the cost added by every candidate value of
  ``order[k]`` under a batch of prefixes is a masked gather-sum
  (the vectorized pass SyncBB did per node, now for the whole slab);
* **bound tables** — a static mini-bucket elimination (Kask & Dechter)
  along the REVERSE search order: each bucket's items are partitioned
  into mini-buckets of separator scope <= ``i_bound``, joined and
  projected separately, and the resulting messages are laid out per
  depth so the admissible heuristic ``h_d(prefix)`` — the sum of all
  messages crossing the assigned/unassigned boundary — is one more
  gather-sum.  With ``i_bound >= induced width`` nothing splits and
  ``h`` is the exact DPOP conditional optimum (best-first search then
  proves optimality almost immediately); smaller bounds trade
  tightness for the same typed table-memory budget the PR 9 engines
  route on.

Pure numpy; consumed at plan time by ``search.frontier``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

#: +inf stand-in shared with the DPOP sweeps (survives f32 sums)
BIG = 1e9
#: padding cost of values beyond a variable's true domain: dominates
#: every reachable f so padded children are pruned on arrival
PAD_COST = 4 * BIG
#: the bound scalar of the per-chunk stats vector is NaN when the
#: spill annex holds rows the host must drain — an EXACT sentinel (an
#: additive flag offset would round the bound away in f32: at 2e9 the
#: ulp is 256, enough to fake an optimality proof), so the
#: steady-state chunk read stays two scalars and spill chunks simply
#: publish no bound (the previous one remains valid)
SPILL_SENTINEL = float("nan")

#: default byte budget for the mini-bucket bound tables (matches the
#: portfolio's AUTO_DPOP_BUDGET_MB scale)
DEFAULT_BOUND_BUDGET_BYTES = 64 * 2**20
#: hard cap on the auto-chosen i-bound (tables stay seconds-cheap)
MAX_AUTO_I_BOUND = 12


def suggest_search_i_bound(Dmax: int,
                           budget_bytes: Optional[int] = None) -> int:
    """Largest ``i`` whose widest mini-bucket table
    (``Dmax^(i+1)`` f32 entries) fits the bound-table budget, capped
    at :data:`MAX_AUTO_I_BOUND`; at least 1."""
    cap = (budget_bytes or DEFAULT_BOUND_BUDGET_BYTES) // 4
    d = max(2, int(Dmax))
    i = 1
    while i < MAX_AUTO_I_BOUND and d ** (i + 2) <= max(cap, d * d):
        i += 1
    return i


# ---------------------------------------------------------------------------
# positioned tables (numpy, scope = sorted order positions)
# ---------------------------------------------------------------------------


def _join_pos(t1: np.ndarray, s1: Tuple[int, ...],
              t2: np.ndarray, s2: Tuple[int, ...]):
    """Join two tables whose axes follow their sorted position scopes."""
    scope = tuple(sorted(set(s1) | set(s2)))

    def expand(t, s):
        shape = [1] * len(scope)
        for ax, p in enumerate(s):
            shape[scope.index(p)] = t.shape[ax]
        return t.reshape(shape)

    return expand(t1, s1) + expand(t2, s2), scope


def _project_pos(t: np.ndarray, scope: Tuple[int, ...], p: int):
    """Min-project position ``p`` out of a positioned table."""
    ax = scope.index(p)
    return np.min(t, axis=ax), tuple(q for q in scope if q != p)


@dataclasses.dataclass
class _Msg:
    """One mini-bucket message: created eliminating ``src``, scoped on
    positions all < ``src`` whose deepest is ``dest`` (-1 = constant)."""

    src: int
    dest: int
    scope: Tuple[int, ...]
    table: np.ndarray  # scalar () when dest == -1


@dataclasses.dataclass
class SearchPlan:
    """Flattened gather tables of one problem's frontier search."""

    order: List[str]
    dom_sizes: np.ndarray          # [n] int32, true domain sizes
    domain_values: List[list]      # decode index -> value per position
    sign: float                    # +1 min / -1 max (engine minimizes)
    n: int
    Dmax: int
    unary: np.ndarray              # [n, Dmax] f32, PAD_COST beyond dom
    # constraints attached per depth (deepest scope position = depth):
    c_flat: np.ndarray             # [sum entries] f32
    c_base: np.ndarray             # [n, Cmax] i32 offsets into c_flat
    c_valid: np.ndarray            # [n, Cmax] f32 0/1
    c_pos: np.ndarray              # [n, Cmax, Amax] i32 scope positions
    c_stride: np.ndarray           # [n, Cmax, Amax] i32 (0 = padding)
    c_own_stride: np.ndarray       # [n, Cmax] i32
    # table-free cardinality increments (structured constraints): at the
    # depth of each scope position, g grows by the telescoping delta
    # s_flat[base + cnt + 1] - s_flat[base + cnt] when the candidate
    # value is the counted one (cnt = counted positions already
    # assigned).  Sum over depths = count_cost[final count], exactly.
    s_flat: np.ndarray             # [sum curves] f32 (normalized, [0]=0)
    s_base: np.ndarray             # [n, Smax] i32 offsets into s_flat
    s_valid: np.ndarray            # [n, Smax] f32 0/1
    s_cnt: np.ndarray              # [n, Smax] i32 counted value idx here
    s_pri_pos: np.ndarray          # [n, Smax, Kmax] i32 earlier positions
    s_pri_cnt: np.ndarray          # [n, Smax, Kmax] i32 their counted idx
    s_pri_valid: np.ndarray        # [n, Smax, Kmax] f32 0/1
    # mini-bucket bound messages, laid out per child depth d in [0, n]:
    i_bound: int
    exact_heuristic: bool          # no mini-bucket ever split
    h_flat: np.ndarray             # [sum entries] f32
    m_base: np.ndarray             # [n+1, Mmax] i32
    m_valid: np.ndarray            # [n+1, Mmax] f32 0/1
    m_pos: np.ndarray              # [n+1, Mmax, Hmax] i32
    m_stride: np.ndarray           # [n+1, Mmax, Hmax] i32
    h_const: np.ndarray            # [n+1] f32 (constant messages)
    root_bound: float              # h at depth 0 — the global MBE bound
    bucket_splits: int
    table_bytes: int               # c_flat + h_flat + index arrays

    def info(self) -> Dict[str, object]:
        """The static half of ``metrics()["search"]``."""
        return {
            "engine": "frontier",
            "n_vars": self.n,
            "max_domain": int(self.Dmax),
            "i_bound": self.i_bound,
            "bound_source": (
                "dpop-exact" if self.exact_heuristic else "minibucket"
            ),
            "bucket_splits": self.bucket_splits,
            "root_bound": float(self.sign * self.root_bound),
            "table_bytes": self.table_bytes,
        }


def estimate_search_bytes(n: int, Dmax: int, i_bound: int,
                          frontier_width: int, ring: int) -> int:
    """Cheap shape-pass byte estimate of the engine's resident state:
    the slab, ring and annex rows plus a worst-case bound-table bucket
    per variable — the number the portfolio feasibility mask and the
    dpop auto ladder route on before anything is built."""
    rows = frontier_width * (Dmax + 2) + ring
    state = rows * (n + 4) * 4
    tables = n * (max(2, Dmax) ** min(i_bound + 1, MAX_AUTO_I_BOUND)) * 4
    return int(state + tables)


def _dfs_preorder(tree) -> List[str]:
    """Deterministic DFS preorder of the pseudo-tree forest (children
    in tree order, roots in tree order)."""
    order: List[str] = []
    for root in tree.roots:
        stack = [root]
        while stack:
            name = stack.pop()
            order.append(name)
            node = tree.computation(name)
            stack.extend(reversed(node.children))
    return order


def compile_search_plan(
    dcop,
    tree=None,
    i_bound: int = 0,
    bound_budget_bytes: Optional[int] = None,
) -> SearchPlan:
    """Compile a DCOP (+ optional prebuilt pseudo-tree) into a
    :class:`SearchPlan`.  ``i_bound=0`` auto-sizes the bound tables to
    ``bound_budget_bytes`` (default 64 MiB) via
    :func:`suggest_search_i_bound`, additionally capped by the induced
    width + 1 (beyond which the heuristic is already exact)."""
    from pydcop_tpu.graph import pseudotree as pt_module

    if tree is None or not hasattr(tree, "roots"):
        tree = pt_module.build_computation_graph(dcop)
    order = _dfs_preorder(tree)
    n = len(order)
    pos = {name: i for i, name in enumerate(order)}
    sign = 1.0 if dcop.objective == "min" else -1.0
    ext = {ev.name: ev.value for ev in dcop.external_variables.values()}

    variables = [dcop.variables[name] for name in order]
    dom_sizes = np.asarray([len(v.domain) for v in variables], np.int32)
    domain_values = [list(v.domain) for v in variables]
    Dmax = int(dom_sizes.max()) if n else 1

    unary = np.full((max(n, 1), Dmax), PAD_COST, np.float32)
    for k, v in enumerate(variables):
        unary[k, : dom_sizes[k]] = (
            sign * np.asarray(v.cost_vector(), np.float64)
        ).astype(np.float32)

    # ---- constraints, positioned and attached at their deepest var.
    # Structured constraints never densify: linear primitives fold into
    # the unary slabs (entering the mini-bucket bound exactly), and
    # cardinality primitives become per-depth telescoping increments.
    from pydcop_tpu.dcop.structured import (
        CardinalityConstraint,
        LinearConstraint,
        StructuredConstraint,
    )

    per_depth: List[List[Tuple[np.ndarray, Tuple[int, ...]]]] = [
        [] for _ in range(max(n, 1))
    ]
    # card entries per depth: (base_offset, cnt_idx_here, prior list)
    card_depth: List[List[Tuple[int, int, List[Tuple[int, int]]]]] = [
        [] for _ in range(max(n, 1))
    ]
    s_chunks: List[np.ndarray] = [np.zeros(2, np.float32)]  # safe slot 0/1
    s_off = 2
    card_lb_by_last: List[Tuple[int, float]] = []  # (last scope pos, lb)
    has_card = False
    for c in dcop.constraints.values():
        if any(nm in ext for nm in c.scope_names):
            c = c.slice(ext)
        scope_pos = [pos[v.name] for v in c.dimensions if v.name in pos]
        if not scope_pos:
            continue
        if isinstance(c, StructuredConstraint):
            for prim in c.lower():
                p_scope = [pos[v.name] for v in prim.dimensions]
                if isinstance(prim, LinearConstraint):
                    for p, row in zip(p_scope, prim.tables):
                        dom = dom_sizes[p]
                        unary[p, :dom] += (
                            sign * row.astype(np.float64)
                        ).astype(np.float32)
                    if prim.bias:
                        p0 = p_scope[0]
                        unary[p0, : dom_sizes[p0]] += np.float32(
                            sign * prim.bias
                        )
                    continue
                assert isinstance(prim, CardinalityConstraint)
                cc = sign * prim.count_cost.astype(np.float64)
                if np.all(cc == cc[0]):
                    # constant curve: fold into the first position's unary
                    if cc[0]:
                        p0 = min(p_scope)
                        unary[p0, : dom_sizes[p0]] += np.float32(cc[0])
                    continue
                has_card = True
                cc_n = (cc - cc[0]).astype(np.float32)
                if cc[0]:
                    p0 = min(p_scope)
                    unary[p0, : dom_sizes[p0]] += np.float32(cc[0])
                base = s_off
                s_chunks.append(cc_n)
                s_off += cc_n.size
                suffix_min = np.minimum.accumulate(
                    cc_n[::-1].astype(np.float64))[::-1]
                lb = float(np.min(suffix_min - cc_n))
                cnt_idx = prim.counted_indices()
                order_ix = np.argsort(np.asarray(p_scope, np.int64),
                                      kind="stable")
                sorted_scope = [
                    (p_scope[i], int(cnt_idx[i])) for i in order_ix
                ]
                card_lb_by_last.append((sorted_scope[-1][0], lb))
                priors: List[Tuple[int, int]] = []
                for p, ci in sorted_scope:
                    if ci >= 0:
                        card_depth[p].append((base, ci, list(priors)))
                        priors.append((p, ci))
                # positions whose domain lacks the counted value can
                # never change the count: no entry, not a prior
            continue
        t = (sign * np.asarray(c.to_tensor(), np.float64)).astype(
            np.float32
        )
        perm = np.argsort(np.asarray(scope_pos, np.int64), kind="stable")
        t = np.ascontiguousarray(np.transpose(t, tuple(perm)))
        scope = tuple(sorted(scope_pos))
        per_depth[scope[-1]].append((t, scope))

    s_flat = np.concatenate(s_chunks)
    Smax = max((len(es) for es in card_depth), default=0) or 1
    Kmax = max(
        (len(pr) for es in card_depth for _b, _c, pr in es), default=0
    ) or 1
    s_base = np.zeros((max(n, 1), Smax), np.int32)
    s_valid = np.zeros((max(n, 1), Smax), np.float32)
    s_cnt = np.zeros((max(n, 1), Smax), np.int32)
    s_pri_pos = np.zeros((max(n, 1), Smax, Kmax), np.int32)
    s_pri_cnt = np.zeros((max(n, 1), Smax, Kmax), np.int32)
    s_pri_valid = np.zeros((max(n, 1), Smax, Kmax), np.float32)
    for k, es in enumerate(card_depth):
        for ei, (base, ci, priors) in enumerate(es):
            s_base[k, ei] = base
            s_valid[k, ei] = 1.0
            s_cnt[k, ei] = ci
            for j, (p, pc) in enumerate(priors):
                s_pri_pos[k, ei, j] = p
                s_pri_cnt[k, ei, j] = pc
                s_pri_valid[k, ei, j] = 1.0

    Cmax = max((len(cs) for cs in per_depth), default=0) or 1
    Amax = max(
        (len(s) - 1 for cs in per_depth for _t, s in cs), default=0
    ) or 1
    c_chunks: List[np.ndarray] = [np.zeros(1, np.float32)]  # safe slot 0
    c_off = 1
    c_base = np.zeros((max(n, 1), Cmax), np.int32)
    c_valid = np.zeros((max(n, 1), Cmax), np.float32)
    c_pos = np.zeros((max(n, 1), Cmax, Amax), np.int32)
    c_stride = np.zeros((max(n, 1), Cmax, Amax), np.int32)
    c_own = np.zeros((max(n, 1), Cmax), np.int32)
    for k, cs in enumerate(per_depth):
        for ci, (t, scope) in enumerate(cs):
            strides = np.asarray(t.strides, np.int64) // t.itemsize
            c_base[k, ci] = c_off
            c_valid[k, ci] = 1.0
            c_own[k, ci] = int(strides[-1])
            for j, p in enumerate(scope[:-1]):
                c_pos[k, ci, j] = p
                c_stride[k, ci, j] = int(strides[j])
            c_chunks.append(t.reshape(-1))
            c_off += t.size
    c_flat = np.concatenate(c_chunks) if c_chunks else np.zeros(
        1, np.float32
    )

    # ---- static mini-bucket elimination along the reverse order
    if i_bound <= 0:
        i_bound = suggest_search_i_bound(Dmax, bound_budget_bytes)
    induced = int(getattr(tree, "induced_width", n))
    i_bound = max(1, min(i_bound, induced + 1))
    dom_of = [int(d) for d in dom_sizes]

    buckets: List[List[Tuple[np.ndarray, Tuple[int, ...]]]] = [
        list(per_depth[k]) for k in range(n)
    ]
    for k in range(n):
        buckets[k].append((unary[k, : dom_of[k]].copy(), (k,)))
    msgs: List[_Msg] = []
    const_by_src = np.zeros(max(n, 1), np.float64)
    n_splits = 0
    for j in range(n - 1, -1, -1):
        items = buckets[j]
        # greedy first-fit-decreasing on separator scope, like
        # ops.dpop_shard.minibucket_solve
        items.sort(key=lambda it: -len([p for p in it[1] if p != j]))
        mini: List[Tuple[set, List[Tuple[np.ndarray, Tuple[int, ...]]]]]
        mini = []
        for t, scope in items:
            sep = {p for p in scope if p != j}
            placed = False
            for sc, members in mini:
                if len(sc | sep) <= i_bound:
                    sc |= sep
                    members.append((t, scope))
                    placed = True
                    break
            if not placed:
                mini.append((set(sep), [(t, scope)]))
        n_splits += max(0, len(mini) - 1)
        for _sc, members in mini:
            t, scope = members[0]
            for t2, s2 in members[1:]:
                t, scope = _join_pos(t, scope, t2, s2)
            t, scope = _project_pos(t, scope, j)
            if not scope:
                const_by_src[j] += float(t)
            else:
                dest = scope[-1]
                msgs.append(_Msg(j, dest, scope,
                                 np.ascontiguousarray(t)))
                buckets[dest].append((t, scope))

    # ---- per-depth layout: message m is live at child depth d iff
    # dest < d <= src (scope fully assigned, source still open)
    h_chunks: List[np.ndarray] = [np.zeros(1, np.float32)]
    h_off = 1
    m_offset = {}
    for m in msgs:
        m_offset[id(m)] = h_off
        h_chunks.append(m.table.astype(np.float32).reshape(-1))
        h_off += m.table.size
    h_flat = np.concatenate(h_chunks)
    by_depth: List[List[_Msg]] = [
        [m for m in msgs if m.dest < d <= m.src] for d in range(n + 1)
    ]
    Mmax = max((len(ms) for ms in by_depth), default=0) or 1
    Hmax = max(
        (len(m.scope) for ms in by_depth for m in ms), default=0
    ) or 1
    m_base = np.zeros((n + 1, Mmax), np.int32)
    m_valid = np.zeros((n + 1, Mmax), np.float32)
    m_pos = np.zeros((n + 1, Mmax, Hmax), np.int32)
    m_stride = np.zeros((n + 1, Mmax, Hmax), np.int32)
    h_const = np.zeros(n + 1, np.float32)
    for d in range(n + 1):
        h_const[d] = float(const_by_src[d:].sum()) if n else 0.0
        # admissible slack for still-open cardinality curves: the worst
        # remaining count-cost delta (0 for monotone min-mode curves —
        # capacity penalties only grow with count)
        h_const[d] += sum(
            lb for last, lb in card_lb_by_last if d <= last
        )
        for mi, m in enumerate(by_depth[d]):
            strides = (
                np.asarray(m.table.strides, np.int64) // m.table.itemsize
            )
            m_base[d, mi] = m_offset[id(m)]
            m_valid[d, mi] = 1.0
            for j, p in enumerate(m.scope):
                m_pos[d, mi, j] = p
                m_stride[d, mi, j] = int(strides[j])

    table_bytes = int(
        c_flat.nbytes + h_flat.nbytes + c_base.nbytes + c_pos.nbytes
        + c_stride.nbytes + m_base.nbytes + m_pos.nbytes
        + m_stride.nbytes + unary.nbytes
        + s_flat.nbytes + s_base.nbytes + s_cnt.nbytes
        + s_pri_pos.nbytes + s_pri_cnt.nbytes + s_pri_valid.nbytes
    )
    return SearchPlan(
        order=order, dom_sizes=dom_sizes, domain_values=domain_values,
        sign=sign, n=n, Dmax=Dmax, unary=unary,
        c_flat=c_flat, c_base=c_base, c_valid=c_valid, c_pos=c_pos,
        c_stride=c_stride, c_own_stride=c_own,
        s_flat=s_flat, s_base=s_base, s_valid=s_valid, s_cnt=s_cnt,
        s_pri_pos=s_pri_pos, s_pri_cnt=s_pri_cnt,
        s_pri_valid=s_pri_valid,
        i_bound=i_bound, exact_heuristic=(n_splits == 0 and not has_card),
        h_flat=h_flat, m_base=m_base, m_valid=m_valid, m_pos=m_pos,
        m_stride=m_stride, h_const=h_const,
        root_bound=float(h_const[0]), bucket_splits=n_splits,
        table_bytes=table_bytes,
    )
