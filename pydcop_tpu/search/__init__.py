"""Device-resident anytime branch-and-bound (ISSUE 15).

The last pre-seed algorithms still running host-side sequential loops —
SyncBB's token walk and NCBB's recursive subtree search — become one
frontier-batched exact engine: a fixed-shape ``[B, n]`` slab of partial
assignments along a pseudo-tree DFS order, expanded one level per step
inside jit, with static mini-bucket lower bounds (the Kask–Dechter
heuristic, exact when the i-bound covers the induced width — the
DPOP-sourced tier) evaluated as batched gather kernels, best-first
selection and incumbent updates on device, and the host reading ONE
``[2]`` stats vector — incumbent + global bound — per chunk (the PR 4
discipline).  Overflowing frontier rows spill to a device-side ring
buffer, then to a small annex the host drains at chunk boundaries (the
counted spill fallback); the anytime ``lower <= optimum <= upper``
sandwich streams over ws/SSE as ``search.*`` events.

* :mod:`pydcop_tpu.search.plan` — host-side compile: DFS order,
  per-depth constraint gather specs, mini-bucket bound tables;
* :mod:`pydcop_tpu.search.frontier` — the jitted expand/bound/select
  step, chunk runner and its declared ProgramBudget;
* :mod:`pydcop_tpu.search.solver` — the anytime driver behind
  ``solve --anytime-exact`` and ``engine=frontier`` on syncbb/ncbb
  (checkpoint/resume-compatible, ``search.*`` event stream).
"""
from pydcop_tpu.search.plan import (  # noqa: F401
    SearchPlan,
    compile_search_plan,
    estimate_search_bytes,
    suggest_search_i_bound,
)
from pydcop_tpu.search.solver import (  # noqa: F401
    FrontierSearchSolver,
    build_frontier_solver,
)
