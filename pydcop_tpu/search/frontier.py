"""The jitted frontier step and chunk runner of the anytime B&B.

One *step* expands the best-first prefix of the live slab one level
along the search order: every expanded row produces ``Dmax`` children
whose cost increments and mini-bucket lower bounds are gathered from
the plan's flat tables as two batched kernels, leaf children update the
device-resident incumbent (value + argmin assignment), children at or
above the incumbent are pruned on arrival, and the survivor pool —
unexpanded rows + children + a ring pop + host-reinjected rows — is
sorted once by ``f = g + h`` so the best ``B`` stay in the slab and the
overflow is pushed back (ring first, then the spill annex).  Expansion
is capacity-throttled so no node is ever dropped: when slab + ring +
annex are full the step stalls (expands nothing) until the host drains
the annex at the next chunk boundary — the counted spill fallback.

A *chunk* is ``lax.scan`` over steps; its host-visible output is the
state pytree (donated, device-resident) plus ONE ``[2]`` f32 vector:
``[incumbent, bound]`` — the PR 4 two-scalars-per-chunk discipline.
The bound scalar doubles as the spill signal: it is NaN when annex
rows await draining (an exact sentinel — see plan.SPILL_SENTINEL;
such chunks publish no bound and the previous one remains valid).
The search is finished when ``bound >= incumbent`` — no open node can
beat the incumbent — which doubles as the optimality proof.

The chunk program's declared :class:`ProgramBudget` lives here, next
to the cycle fn it governs (:func:`frontier_chunk_budget`), and is
swept by the ``analysis`` registry (``search/frontier/*`` cells).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import numpy as np

from pydcop_tpu.search.plan import BIG, SearchPlan

#: dtype tier of the frontier programs: f32 costs/bounds, i32
#: assignments/indices/counters, bool masks — no PRNG (the search is
#: deterministic), no f64 anywhere
FRONTIER_DTYPES = frozenset({"float32", "int32", "bool"})


def frontier_chunk_budget(plan_table_bytes: int,
                          donate: bool = True):
    """Declared budget of the frontier chunk runner: a single-device
    program — zero collectives, ZERO host callbacks (the incumbent and
    bound ride the ``[2]`` stats vector out), the f32/i32/bool tier,
    constants bounded by the plan's flat gather tables (a cold engine:
    the problem is baked, the SLAB travels as a donated argument)."""
    from pydcop_tpu.algorithms.base import CONST_SLACK_BYTES
    from pydcop_tpu.analysis.budget import (
        COLLECTIVE_KINDS,
        ProgramBudget,
    )

    return ProgramBudget(
        collectives={k: 0 for k in COLLECTIVE_KINDS},
        max_collective_bytes=0,
        max_host_callbacks=0,
        dtypes=FRONTIER_DTYPES,
        max_const_bytes=int(plan_table_bytes) + CONST_SLACK_BYTES,
        donate=donate,
    )


@dataclasses.dataclass
class FrontierShape:
    """Fixed shapes of one engine instance."""

    B: int        # slab rows (frontier width)
    R: int        # ring rows (device spill)
    A: int        # annex/inject rows (host spill quantum)
    steps: int    # expand steps per chunk


class FrontierEngine:
    """Compiled device half of the frontier search: builds the jitted
    step + chunk runner over a :class:`SearchPlan` and exposes the
    initial/injected state pytrees.  Driving (anytime loop, events,
    spill drain) lives in ``search.solver``."""

    def __init__(self, plan: SearchPlan, frontier_width: int = 256,
                 ring: int = 0, annex: int = 0, steps: int = 16):
        B = max(2, int(frontier_width))
        D = max(1, plan.Dmax)
        self.plan = plan
        # annex scales with the slab: a chunk whose spills outrun the
        # annex stalls expansion until the next host drain, so a
        # too-small quantum turns sustained pressure into idle steps
        self.shape = FrontierShape(
            B=B,
            R=int(ring) if ring else 8 * B,
            A=max(int(annex) if annex else B // 4, D, 8),
            steps=max(1, int(steps)),
        )
        self._runner = None
        self._dive_fns: Dict[int, Any] = {}
        self._trace_counts: Dict[Any, int] = {}

    # -- state --------------------------------------------------------------

    def initial_state(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        p, s = self.plan, self.shape
        n = max(p.n, 1)

        def rows(m):
            return {
                "assign": jnp.zeros((m, n), jnp.int32),
                "g": jnp.zeros((m,), jnp.float32),
                "f": jnp.full((m,), BIG, jnp.float32),
                "depth": jnp.zeros((m,), jnp.int32),
            }

        front = rows(s.B)
        # the root row: empty prefix, f = the global MBE bound
        front["f"] = front["f"].at[0].set(jnp.float32(p.root_bound))
        state = {
            "f_" + k: v for k, v in front.items()
        }
        state["f_live"] = (
            jnp.zeros((s.B,), bool).at[0].set(p.n > 0)
        )
        # ring and annex carry one extra "dump" row absorbing the
        # masked scatter lanes, so a genuine push never collides with
        # a no-op write (scatter duplicates are unordered)
        for pre, m in (("r_", s.R + 1), ("x_", s.A + 1), ("j_", s.A)):
            for k, v in rows(m).items():
                state[pre + k] = v
        state["r_count"] = jnp.int32(0)
        state["x_count"] = jnp.int32(0)
        state["j_count"] = jnp.int32(0)
        state["incumbent"] = jnp.float32(BIG)
        state["best_assign"] = jnp.zeros((n,), jnp.int32)
        state["nodes"] = jnp.int32(0)
        state["leaves"] = jnp.int32(0)
        state["pruned"] = jnp.int32(0)
        state["lost"] = jnp.int32(0)
        return state

    # -- gather kernels -----------------------------------------------------

    def _build_kernels(self):
        import jax
        import jax.numpy as jnp

        p = self.plan
        unary = jnp.asarray(p.unary)
        c_flat = jnp.asarray(p.c_flat)
        c_base = jnp.asarray(p.c_base)
        c_valid = jnp.asarray(p.c_valid)
        c_pos = jnp.asarray(p.c_pos)
        c_stride = jnp.asarray(p.c_stride)
        c_own = jnp.asarray(p.c_own_stride)
        h_flat = jnp.asarray(p.h_flat)
        m_base = jnp.asarray(p.m_base)
        m_valid = jnp.asarray(p.m_valid)
        m_pos = jnp.asarray(p.m_pos)
        m_stride = jnp.asarray(p.m_stride)
        h_const = jnp.asarray(p.h_const)
        s_flat = jnp.asarray(p.s_flat)
        s_base = jnp.asarray(p.s_base)
        s_valid = jnp.asarray(p.s_valid)
        s_cnt = jnp.asarray(p.s_cnt)
        s_pri_pos = jnp.asarray(p.s_pri_pos)
        s_pri_cnt = jnp.asarray(p.s_pri_cnt)
        s_pri_valid = jnp.asarray(p.s_pri_valid)
        D = p.Dmax

        def inc_row(assign, k):
            """[Dmax] cost increments of assigning order[k] under the
            row's prefix — one gather-sum over the flat tables, plus the
            table-free cardinality deltas (structured constraints)."""
            base = c_base[k] + jnp.sum(
                c_stride[k] * assign[c_pos[k]], axis=-1
            )  # [Cmax]
            offs = base[:, None] + (
                jnp.arange(D, dtype=jnp.int32)[None, :] * c_own[k][:, None]
            )
            vals = c_flat[offs]  # [Cmax, D]
            out = unary[k] + jnp.sum(
                c_valid[k][:, None] * vals, axis=0
            )
            # cardinality deltas: count prior counted positions in the
            # prefix, then charge count_cost[c+1]-count_cost[c] on the
            # counted candidate value only (telescoping → exact g)
            cnt = jnp.sum(
                s_pri_valid[k]
                * (assign[s_pri_pos[k]] == s_pri_cnt[k]),
                axis=-1,
            ).astype(jnp.int32)  # [Smax]
            off = s_base[k] + cnt
            delta = (s_flat[off + 1] - s_flat[off]) * s_valid[k]  # [Smax]
            hit = (
                jnp.arange(D, dtype=jnp.int32)[None, :]
                == s_cnt[k][:, None]
            )  # [Smax, D]
            return out + jnp.sum(
                jnp.where(hit, delta[:, None], 0.0), axis=0
            )

        def h_row(assign, d):
            """Mini-bucket lower bound of the suffix below depth d."""
            base = m_base[d] + jnp.sum(
                m_stride[d] * assign[m_pos[d]], axis=-1
            )
            return h_const[d] + jnp.sum(m_valid[d] * h_flat[base])

        return jax.vmap(inc_row), jax.vmap(
            jax.vmap(h_row, in_axes=(0, None)), in_axes=(0, 0)
        )

    # -- step ---------------------------------------------------------------

    def _make_step(self):
        import jax.numpy as jnp

        p, s = self.plan, self.shape
        n = max(p.n, 1)
        D = p.Dmax
        B, R, A = s.B, s.R, s.A
        dom = jnp.asarray(p.dom_sizes) if p.n else jnp.ones(
            (1,), jnp.int32
        )
        inc_rows, h_rows = self._build_kernels()
        INF = jnp.float32(np.inf)

        def step(st):
            U = st["incumbent"]
            # rows at/above the incumbent can never improve it: dead
            live = st["f_live"] & (st["f_f"] < U)
            live_count = jnp.sum(live)
            stored = (
                live_count + st["r_count"] + st["x_count"]
                + st["j_count"]
            )
            slack = jnp.int32(B + R + A) - stored
            E = jnp.clip(slack // jnp.int32(max(D - 1, 1)), 0, B)

            # best-first choice of the E rows to expand; equal-f ties
            # break toward DEEPER rows — when the heuristic is (near)
            # exact, e.g. the separable part of structured constraints,
            # the whole optimal prefix ties on f and a shallow-first
            # order degenerates to breadth-first churn that never
            # reaches a leaf at high arity
            deep = jnp.argsort(-st["f_depth"], stable=True)
            keys = jnp.where(live, st["f_f"], INF)
            rank = jnp.argsort(deep[jnp.argsort(keys[deep], stable=True)])
            expand = live & (rank < E)

            k = st["f_depth"]                       # [B]
            inc = inc_rows(st["f_assign"], k)       # [B, D]
            g_c = st["f_g"][:, None] + inc
            vals = jnp.arange(D, dtype=jnp.int32)
            child_assign = jnp.where(
                jnp.arange(n, dtype=jnp.int32)[None, None, :]
                == k[:, None, None],
                vals[None, :, None],
                st["f_assign"][:, None, :],
            )                                       # [B, D, n]
            d_child = jnp.minimum(k + 1, p.n)
            h_c = h_rows(child_assign, d_child)     # [B, D]
            f_c = g_c + h_c

            is_leaf = (k + 1 == p.n)                # [B]
            val_ok = vals[None, :] < dom[jnp.clip(k, 0, n - 1)][:, None]
            leaf_g = jnp.where(
                expand[:, None] & is_leaf[:, None] & val_ok, g_c, INF
            )
            best_flat = jnp.argmin(leaf_g)
            leaf_min = leaf_g.reshape(-1)[best_flat]
            improved = leaf_min < U
            U2 = jnp.where(improved, leaf_min, U)
            best_assign = jnp.where(
                improved,
                child_assign.reshape(-1, n)[best_flat],
                st["best_assign"],
            )

            child_open = (
                expand[:, None] & (~is_leaf)[:, None] & (f_c < U2)
            )
            n_pruned = jnp.sum(
                expand[:, None] & (~is_leaf)[:, None] & val_ok
                & (f_c >= U2)
            )

            # ---- pool: survivors + children + ring pop + inject
            def cat(field, children_val, ring_val, inj_val):
                return jnp.concatenate(
                    [field, children_val, ring_val, inj_val], axis=0
                )

            pop_idx = st["r_count"] - 1 - jnp.arange(B, dtype=jnp.int32)
            pop_ok = pop_idx >= 0
            pop_at = jnp.clip(pop_idx, 0, R - 1)
            inj_ok = jnp.arange(A, dtype=jnp.int32) < st["j_count"]

            pool_assign = cat(
                st["f_assign"], child_assign.reshape(-1, n),
                st["r_assign"][pop_at], st["j_assign"],
            )
            pool_g = cat(st["f_g"], g_c.reshape(-1),
                         st["r_g"][pop_at], st["j_g"])
            pool_f = cat(st["f_f"], f_c.reshape(-1),
                         st["r_f"][pop_at], st["j_f"])
            pool_depth = cat(
                st["f_depth"],
                jnp.broadcast_to(k[:, None] + 1, (B, D)).reshape(-1),
                st["r_depth"][pop_at], st["j_depth"],
            )
            pool_ok = jnp.concatenate([
                live & ~expand,
                child_open.reshape(-1),
                pop_ok,
                inj_ok,
            ]) & (pool_f < U2)

            # same deeper-first tie-break as the expansion choice, so
            # equal-f children outrank their parents in the slab
            pdeep = jnp.argsort(-pool_depth, stable=True)
            order = pdeep[jnp.argsort(
                jnp.where(pool_ok, pool_f, INF)[pdeep], stable=True)]
            pool_assign = pool_assign[order]
            pool_g = pool_g[order]
            pool_f = pool_f[order]
            pool_depth = pool_depth[order]
            pool_ok = pool_ok[order]

            n_valid = jnp.sum(pool_ok)
            r_count = jnp.maximum(
                st["r_count"] - jnp.sum(pop_ok), 0
            )
            n_push = jnp.maximum(n_valid - B, 0)
            to_ring = jnp.minimum(n_push, R - r_count)
            to_annex = jnp.minimum(
                n_push - to_ring, A - st["x_count"]
            )
            lost = n_push - to_ring - to_annex

            P = pool_f.shape[0]
            ov = jnp.arange(P, dtype=jnp.int32) - B  # overflow rank
            pushing = pool_ok & (ov >= 0)
            # ring pushes go in REVERSE priority order so the stack top
            # (popped first next step) holds the best overflow row
            ring_slot = r_count + (to_ring - 1 - ov)
            ring_idx = jnp.where(
                pushing & (ov < to_ring), ring_slot, R
            )
            annex_slot = st["x_count"] + (ov - to_ring)
            annex_idx = jnp.where(
                pushing & (ov >= to_ring) & (ov < to_ring + to_annex),
                annex_slot, A,
            )

            # note: ring/annex buffers carry one extra dump row (index
            # R / A) that absorbs the non-pushed scatter lanes
            r_assign = st["r_assign"].at[jnp.clip(ring_idx, 0, R)].set(
                jnp.where((ring_idx < R)[:, None], pool_assign,
                          st["r_assign"][jnp.clip(ring_idx, 0, R)]))
            r_g = st["r_g"].at[jnp.clip(ring_idx, 0, R)].set(
                jnp.where(ring_idx < R, pool_g,
                          st["r_g"][jnp.clip(ring_idx, 0, R)]))
            r_f = st["r_f"].at[jnp.clip(ring_idx, 0, R)].set(
                jnp.where(ring_idx < R, pool_f,
                          st["r_f"][jnp.clip(ring_idx, 0, R)]))
            r_depth = st["r_depth"].at[jnp.clip(ring_idx, 0, R)].set(
                jnp.where(ring_idx < R, pool_depth,
                          st["r_depth"][jnp.clip(ring_idx, 0, R)]))
            xcl = jnp.clip(annex_idx, 0, A)
            x_ok = annex_idx < A
            x_assign = st["x_assign"].at[xcl].set(
                jnp.where(x_ok[:, None], pool_assign,
                          st["x_assign"][xcl]))
            x_g = st["x_g"].at[xcl].set(
                jnp.where(x_ok, pool_g, st["x_g"][xcl]))
            x_f = st["x_f"].at[xcl].set(
                jnp.where(x_ok, pool_f, st["x_f"][xcl]))
            x_depth = st["x_depth"].at[xcl].set(
                jnp.where(x_ok, pool_depth, st["x_depth"][xcl]))

            return {
                "f_assign": pool_assign[:B],
                "f_g": pool_g[:B],
                "f_f": pool_f[:B],
                "f_depth": pool_depth[:B],
                "f_live": pool_ok[:B],
                "r_assign": r_assign, "r_g": r_g, "r_f": r_f,
                "r_depth": r_depth,
                "r_count": r_count + to_ring,
                "x_assign": x_assign, "x_g": x_g, "x_f": x_f,
                "x_depth": x_depth,
                "x_count": st["x_count"] + to_annex,
                "j_assign": st["j_assign"], "j_g": st["j_g"],
                "j_f": st["j_f"], "j_depth": st["j_depth"],
                "j_count": jnp.int32(0),
                "incumbent": U2,
                "best_assign": best_assign,
                "nodes": st["nodes"] + jnp.sum(expand),
                "leaves": st["leaves"] + jnp.sum(
                    jnp.where(expand & is_leaf, 1, 0)
                ),
                "pruned": st["pruned"] + n_pruned,
                "lost": st["lost"] + lost,
            }

        return step

    def beam_dive(self, width: int = 64):
        """Depth-synchronous beam rollout: carry ``width`` partial
        rows from the empty prefix to the leaves, keeping the best
        ``width`` children by f = g + h at every depth.  Returns
        ``(assign, cost)`` of the best leaf — a true upper bound
        usable as an initial incumbent.

        Best-first alone can touch no leaf for arbitrarily long when
        the bound is inexact and the space is deep (a 100-ary
        structured constraint has 4^100 leaves); seeding the incumbent
        with this rollout turns the very first chunk into pruning
        work.  A beam (rather than a single greedy path) survives
        tight feasibility structure — with exact capacities and
        forbidden values a lone rollout can paint itself into a
        corner no single-step lookahead warns about."""
        import jax
        import jax.numpy as jnp

        p = self.plan
        if not p.n:
            return np.zeros((0,), np.int32), 0.0
        inc_rows, h_rows = self._build_kernels()
        n, D = p.n, p.Dmax
        W = max(int(width), 1)
        dom = jnp.asarray(p.dom_sizes)
        INF = jnp.float32(np.inf)

        def body(carry, k):
            assign, g, ok = carry             # [W,n], [W], [W]
            ks = jnp.full((W,), k, jnp.int32)
            inc = inc_rows(assign, ks)        # [W, D]
            g_c = g[:, None] + inc
            vals = jnp.arange(D, dtype=jnp.int32)
            child = jnp.where(
                jnp.arange(n, dtype=jnp.int32)[None, None, :] == k,
                vals[None, :, None], assign[:, None, :],
            )                                 # [W, D, n]
            h = h_rows(child, jnp.minimum(ks + 1, n))
            f = jnp.where(
                ok[:, None] & (vals[None, :] < dom[k]),
                g_c + h, INF,
            ).reshape(-1)
            _, idx = jax.lax.top_k(-f, W)
            w_i, d_i = idx // D, idx % D
            return (
                child[w_i, d_i], g_c[w_i, d_i], f[idx] < INF
            ), None

        def dive(a0, g0, ok0):
            (assign, g, ok), _ = jax.lax.scan(
                body, (a0, g0, ok0), jnp.arange(n, dtype=jnp.int32)
            )
            leaf_g = jnp.where(ok, g, INF)
            best = jnp.argmin(leaf_g)
            return assign[best], leaf_g[best]

        # one bring-up program per beam width, cached like the chunk
        # runner (but outside the steady-state trace discipline: it
        # runs once before the chunk loop, never inside it)
        fn = self._dive_fns.get(W)
        if fn is None:
            fn = self._dive_fns[W] = jax.jit(dive)
        assign, g = fn(
            jnp.zeros((W, n), jnp.int32),
            jnp.zeros((W,), jnp.float32),
            jnp.zeros((W,), bool).at[0].set(True),
        )
        return np.asarray(assign), float(g)

    def lower_bound(self, st):
        """Global bound: min over every open row's f, clamped by the
        incumbent (traced — part of the chunk program)."""
        import jax.numpy as jnp

        INF = jnp.float32(np.inf)
        s = self.shape
        lb = jnp.minimum(
            jnp.min(jnp.where(st["f_live"], st["f_f"], INF)),
            jnp.min(jnp.where(
                jnp.arange(s.R + 1, dtype=jnp.int32) < st["r_count"],
                st["r_f"], INF,
            )),
        )
        lb = jnp.minimum(lb, jnp.min(jnp.where(
            jnp.arange(s.A + 1, dtype=jnp.int32) < st["x_count"],
            st["x_f"], INF,
        )))
        lb = jnp.minimum(lb, jnp.min(jnp.where(
            jnp.arange(s.A, dtype=jnp.int32) < st["j_count"],
            st["j_f"], INF,
        )))
        return jnp.minimum(st["incumbent"], lb)

    def chunk_runner(self):
        """ONE jitted runner per engine: scans ``shape.steps`` expand
        steps and returns ``(state, [incumbent, bound'])`` — the state
        donated and device-resident, the two scalars the only
        steady-state host traffic (bound' carries the spill flag)."""
        if self._runner is not None:
            return self._runner
        import jax
        import jax.numpy as jnp

        from pydcop_tpu.algorithms.base import donation_supported

        step = self._make_step()
        steps = self.shape.steps

        def run_chunk(state):
            self._trace_counts["chunk"] = (
                self._trace_counts.get("chunk", 0) + 1
            )
            state, _ = jax.lax.scan(
                lambda st, _: (step(st), None), state, None,
                length=steps,
            )
            lb = self.lower_bound(state)
            # NaN = "annex needs draining": an exact sentinel — an
            # additive flag offset would cost the bound up to an
            # f32 ulp of the offset (enough to fake a proof)
            enc = jnp.where(
                state["x_count"] > 0, jnp.float32(jnp.nan), lb
            )
            return state, jnp.stack([state["incumbent"], enc])

        donate = (0,) if donation_supported() else ()
        self._runner = jax.jit(run_chunk, donate_argnums=donate)
        return self._runner

    def trace_count(self) -> int:
        return sum(self._trace_counts.values())

    def program_budget(self):
        return frontier_chunk_budget(self.plan.table_bytes)
