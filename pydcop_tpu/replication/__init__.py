"""Replica placement for k-resiliency.

Equivalent capability to the reference's
pydcop/replication/dist_ucs_hostingcosts.py (:52-74,
build_replication_computation): place k replicas of every active
computation on distinct other agents, minimizing route-distance + hosting
cost, under agent capacities.

The reference runs a distributed uniform-cost search among agents; the
placement objective is identical here but solved centrally: shortest route
distances via Dijkstra over the agents' route graph (the UCS cost), then
per-computation greedy assignment of the k cheapest feasible agents.
Determinism: ties break on agent name.
"""
from __future__ import annotations

import heapq
from typing import Callable, Dict, Iterable, List, Optional

from pydcop_tpu.dcop.objects import AgentDef
from pydcop_tpu.distribution.objects import Distribution


class ReplicaDistribution:
    """computation → list of replica-holder agents."""

    def __init__(self, mapping: Dict[str, List[str]]):
        self._mapping = {c: list(agents) for c, agents in mapping.items()}

    def replicas(self, computation: str) -> List[str]:
        return list(self._mapping.get(computation, []))

    def mapping(self) -> Dict[str, List[str]]:
        return {c: list(a) for c, a in self._mapping.items()}

    def agents_holding(self, agent: str) -> List[str]:
        return [c for c, agents in self._mapping.items() if agent in agents]

    def __repr__(self):
        return f"ReplicaDistribution({self._mapping})"


def route_distances(agents: List[AgentDef]) -> Dict[str, Dict[str, float]]:
    """All-pairs shortest route costs (Dijkstra per agent) — the UCS metric
    of the reference (replication/path_utils.py cheapest_path_to)."""
    names = [a.name for a in agents]
    by_name = {a.name: a for a in agents}
    dist: Dict[str, Dict[str, float]] = {}
    for src in names:
        d = {src: 0.0}
        heap = [(0.0, src)]
        while heap:
            cost, cur = heapq.heappop(heap)
            if cost > d.get(cur, float("inf")):
                continue
            for other in names:
                if other == cur:
                    continue
                step = by_name[cur].route(other)
                nd = cost + step
                if nd < d.get(other, float("inf")):
                    d[other] = nd
                    heapq.heappush(heap, (nd, other))
        dist[src] = d
    return dist


def place_replicas(
    computations: Iterable[str],
    distribution: Distribution,
    agents: Iterable[AgentDef],
    k: int,
    computation_memory: Optional[Callable[[str], float]] = None,
    hosting_weight: float = 1.0,
    route_weight: float = 1.0,
) -> ReplicaDistribution:
    """Place k replicas of each computation on distinct agents ≠ its host,
    minimizing route(host→candidate) + hosting cost, respecting remaining
    capacities."""
    agents = list(agents)
    by_name = {a.name: a for a in agents}
    dists = route_distances(agents)
    mem = computation_memory or (lambda c: 0.0)

    remaining = {}
    for a in agents:
        used = sum(
            mem(c) for c in distribution.computations_hosted(a.name)
        ) if distribution else 0.0
        cap = a.capacity if a.capacity is not None else float("inf")
        remaining[a.name] = cap - used

    mapping: Dict[str, List[str]] = {}
    for comp in sorted(computations):
        try:
            host = distribution.agent_for(comp)
        except KeyError:
            host = None
        candidates = []
        for a in agents:
            if a.name == host:
                continue
            route = dists.get(host, {}).get(a.name, a.route(host or a.name)) \
                if host else 0.0
            cost = route_weight * route + \
                hosting_weight * a.hosting_cost(comp)
            candidates.append((cost, a.name))
        candidates.sort()
        chosen: List[str] = []
        for cost, name in candidates:
            if len(chosen) >= k:
                break
            if remaining[name] >= mem(comp):
                chosen.append(name)
                remaining[name] -= mem(comp)
        mapping[comp] = chosen
    return ReplicaDistribution(mapping)
