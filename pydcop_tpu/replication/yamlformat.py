"""Replica-distribution YAML (de)serialization.

Equivalent capability to the reference's pydcop/replication/yamlformat.py
(:44-58) and the `replica_dist` command's result envelope
(commands/replica_dist.py:219-233): the file holds a ``replica_dist``
mapping computation → list of replica-holder agents, optionally alongside
an ``inputs`` block recording how it was produced.
"""
from __future__ import annotations

from typing import Dict, Optional

import yaml

from pydcop_tpu.replication import ReplicaDistribution


def yaml_replica_dist(
    replicas: ReplicaDistribution, inputs: Optional[Dict] = None
) -> str:
    """Serialize a replica distribution (with an optional ``inputs``
    provenance block, like the reference command output)."""
    result: Dict = {}
    if inputs is not None:
        result["inputs"] = inputs
    result["replica_dist"] = replicas.mapping()
    return yaml.safe_dump(result, default_flow_style=False)


def load_replica_dist(dist_str: str) -> ReplicaDistribution:
    """Parse a replica distribution (reference yamlformat.py:50-58)."""
    loaded = yaml.safe_load(dist_str)
    if not isinstance(loaded, dict) or "replica_dist" not in loaded:
        raise ValueError("Invalid replica distribution file")
    mapping = loaded["replica_dist"]
    if not isinstance(mapping, dict):
        raise ValueError("Invalid replica distribution file")
    clean: Dict[str, list] = {}
    for c, agents in mapping.items():
        if not isinstance(agents, list):
            raise ValueError(
                f"Invalid replica distribution file: replicas of "
                f"'{c}' must be a list, got {type(agents).__name__}"
            )
        clean[str(c)] = [str(a) for a in agents]
    return ReplicaDistribution(clean)


def load_replica_dist_from_file(filename: str) -> ReplicaDistribution:
    with open(filename, mode="r", encoding="utf-8") as f:
        return load_replica_dist(f.read())
