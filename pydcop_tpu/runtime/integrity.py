"""In-jit integrity sentinels + silent-data-corruption primitives.

The resilience tiers so far (PR 1/7/11) trust the device: a rank can
crash, a scheduler can wedge, a replica can die — but a bit flipped in
a boundary slab mid-collective would sail straight through every one of
them and come out as a *wrong answer*.  This module is the device-tier
half of the elastic-mesh story (parallel/elastic.py drives it):

* **sentinel blocks** — cheap invariant reductions computed INSIDE the
  sharded cycle programs and combined with one extra ``psum`` pair per
  chunk, so the host read stays one tensor per chunk (the PR 4
  discipline).  Three invariants ride one int32[4] vector:

  - ``nonfinite`` — count of non-finite entries in the message/state
    carries (a flipped exponent bit is very likely to land here);
  - ``state checksum`` — a wrapping uint32 sum of the bitcast state
    words.  Wrapping integer addition is associative and commutative,
    so the checksum is *layout-independent*: the same per-edge messages
    stacked under any shard partition (zero-padded dummies included)
    produce the same word sum — which is what lets a shadow engine
    built under a permuted shard assignment be compared bit-for-bit;
  - ``operand checksum`` — the same wrapping sum over the staged cost
    slabs.  Operands never change during a run, so ANY drift from the
    reference recorded at build time is corruption, with zero false
    positives by construction;
  - ``residual`` — the belief-normalization invariant of the BP
    engines: outgoing q messages are mean-centred, so each edge's
    domain-row must sum to ~0; the sentinel carries the psum of the
    per-shard max |row sum| (bitcast into the int vector).

* **seeded bit-flips** (:func:`flip_bit`) — the ``corrupt_slab`` fault
  kind's payload: deterministically flip one bit of one word of a host
  array copy, so tests and the bench can inject SDC reproducibly.

* **host-side checksums** (:func:`wrapsum_host`) — the same wrapping
  sum computed with numpy, bit-for-bit equal to the in-jit one; the
  elastic driver records operand references with it at build time.

Exactness tier: the *state* checksum comparison between a primary and
a shadow run is bit-exact whenever the arithmetic itself is exact
(integer-valued costs, power-of-two domain sizes and damping — the
same tier the sharded DPOP bit-identity pins ride).  The *operand*
checksum needs no exactness at all: it compares a constant against
itself.  docs/resilience.rst ("Device loss and data integrity") states
the contract.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

#: width of a sentinel vector: [nonfinite, state cksum, operand cksum,
#: residual bits]
SENTINEL_WIDTH = 4


def wrapsum_words(x):
    """In-jit wrapping uint32 word sum of one array (any dtype).

    float32 arrays are bitcast (not cast) so every mantissa bit
    counts; integer/bool arrays sum their values.  Zero padding
    contributes zero, and the modular sum is order-independent — the
    two properties the layout-independence argument above rests on.
    """
    import jax
    import jax.numpy as jnp

    if x.size == 0:
        return jnp.uint32(0)
    if jnp.issubdtype(x.dtype, jnp.floating):
        w = jax.lax.bitcast_convert_type(
            x.astype(jnp.float32), jnp.uint32
        )
    else:
        w = x.astype(jnp.uint32)
    return jnp.sum(w, dtype=jnp.uint32)


def sentinel_block(state_leaves, operand_leaves, resid=None):
    """Per-shard sentinel partial (call INSIDE shard_map, then psum).

    Returns ``(ints uint32[3], resid float32[1])`` — the two vectors
    the caller combines with one ``psum`` each (integer invariants
    cannot ride a float reduction without losing bits, hence the
    pair).  ``resid`` defaults to 0 for engines without a
    normalization invariant (local search)."""
    import jax.numpy as jnp

    nf = jnp.uint32(0)
    cks = jnp.uint32(0)
    for leaf in state_leaves:
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            nf = nf + jnp.sum(
                ~jnp.isfinite(leaf), dtype=jnp.uint32
            )
        cks = cks + wrapsum_words(leaf)
    opk = jnp.uint32(0)
    for leaf in operand_leaves:
        opk = opk + wrapsum_words(leaf)
    ints = jnp.stack([nf, cks, opk])
    if resid is None:
        resid = jnp.float32(0.0)
    return ints, jnp.reshape(resid.astype(jnp.float32), (1,))


def combine_sentinel(ints, resid, axis_name: str):
    """psum the two sentinel partials across the mesh and pack them
    into ONE replicated int32[4] vector (the residual rides bitcast in
    lane 3)."""
    import jax
    import jax.numpy as jnp

    ints = jax.lax.psum(ints, axis_name)
    resid = jax.lax.psum(resid, axis_name)
    rbits = jax.lax.bitcast_convert_type(resid, jnp.uint32)
    return jnp.concatenate([ints, rbits]).astype(jnp.int32)


@dataclasses.dataclass
class SentinelReading:
    """Host-side decode of one sentinel vector."""

    nonfinite: int
    state_checksum: int
    operand_checksum: int
    residual: float

    def trip_reason(self, operand_ref: Optional[int] = None,
                    resid_tol: float = 1e-2) -> Optional[str]:
        """The first tripped invariant, or None when all hold.
        ``operand_ref`` is the build-time operand checksum (skipped
        when None — e.g. before the first chunk established it)."""
        if self.nonfinite:
            return "nonfinite"
        if not (abs(self.residual) <= resid_tol):  # NaN-safe
            return "residual"
        if operand_ref is not None \
                and self.operand_checksum != operand_ref:
            return "operand"
        return None


def decode_sentinel(vec) -> SentinelReading:
    """int32[4] sentinel vector (device or host) → reading."""
    v = np.asarray(vec)
    if v.shape[-1] != SENTINEL_WIDTH:
        raise ValueError(
            f"sentinel vector has width {v.shape[-1]}, "
            f"expected {SENTINEL_WIDTH}"
        )
    u = v.astype(np.int64) & 0xFFFFFFFF
    resid = float(
        np.asarray(u[3], dtype=np.uint32).view(np.float32)
    )
    return SentinelReading(
        nonfinite=int(u[0]),
        state_checksum=int(u[1]),
        operand_checksum=int(u[2]),
        residual=resid,
    )


def wrapsum_host(arrays: Sequence[np.ndarray]) -> int:
    """Host twin of the in-jit operand checksum: the wrapping uint32
    word sum over ``arrays``, bit-for-bit equal to what
    :func:`sentinel_block` computes over the same values on device."""
    total = np.uint32(0)
    with np.errstate(over="ignore"):
        for a in arrays:
            a = np.ascontiguousarray(a)
            if a.dtype == np.float32:
                w = a.view(np.uint32)
            elif a.dtype.kind == "f":
                w = a.astype(np.float32).view(np.uint32)
            else:
                w = a.astype(np.uint32)
            total = np.uint32(
                (int(total) + int(np.sum(w, dtype=np.uint64)))
                & 0xFFFFFFFF
            )
    return int(total)


def flip_bit(arr: np.ndarray, seed: int,
             shard: Optional[int] = None,
             n_shards: int = 1) -> np.ndarray:
    """Return a copy of ``arr`` with ONE seeded bit flipped — the
    ``corrupt_slab`` payload.  ``shard`` restricts the flip to that
    shard's leading-axis block (shard-major stacking, ``n_shards``
    blocks); same seed + same shape → same flipped bit."""
    import random

    a = np.ascontiguousarray(np.array(arr, copy=True))
    if a.dtype == np.float32:
        words = a.view(np.uint32).ravel()
    elif a.dtype == np.int32:
        words = a.view(np.uint32).ravel()
    else:
        raise ValueError(
            f"corrupt_slab targets float32/int32 operands, got "
            f"{a.dtype}"
        )
    if words.size == 0:
        raise ValueError("cannot corrupt an empty operand")
    lo, hi = 0, words.size
    if shard is not None and n_shards > 1:
        block = words.size // n_shards
        if block:
            lo = min(int(shard), n_shards - 1) * block
            hi = lo + block
    rng = random.Random(seed)
    pos = rng.randrange(lo, hi)
    bit = rng.randrange(32)
    words[pos] ^= np.uint32(1 << bit)
    return a
