"""Durable checkpoint / resume of solver and mesh state.

The reference has no checkpointing (resilience is replication-based,
SURVEY.md §5); for a dense tensor solver a checkpoint is just the state
pytree, so we add it — and harden it for long-running jobs where a
partial or bit-rotted file must NEVER be loaded as state:

* **atomic write**: temp file in the same directory + flush + fsync +
  ``os.replace`` — a crash mid-write leaves the previous snapshot
  intact, never a half-written one under the final name;
* **per-array CRC32** + a **schema version** in the metadata;
  :func:`load_checkpoint` rejects truncated, corrupted or
  version-mismatched files with a clear ``ValueError`` instead of
  returning garbage state;
* **periodic snapshots with rotation** via :class:`CheckpointManager`
  (every *k* cycles, keep the newest *n*), whose ``latest_valid()``
  transparently skips damaged snapshots — the auto-resume path of
  runtime/process.py and the orchestrator.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: current checkpoint schema version.  v1 = the original unversioned,
#: unchecksummed format (still readable); v2 adds per-array CRC32;
#: v3 adds the warm-repair headroom layout (claimed/free slot maps +
#: capacity host metadata) so ``--resume`` restores a MUTATED problem
#: at its exact padded shape (ISSUE 8).  v1/v2 files remain readable.
CHECKPOINT_VERSION = 3


# --------------------------------------------------------------------------
# low-level hardened container (.npz + meta JSON + CRCs)
# --------------------------------------------------------------------------

def _crc(a: np.ndarray) -> int:
    a = np.ascontiguousarray(a)
    return zlib.crc32(a.tobytes()) & 0xFFFFFFFF


def write_state_npz(path: str, arrays: Dict[str, np.ndarray],
                    meta: Dict[str, Any]) -> None:
    """Atomically persist ``arrays`` + ``meta`` to ``path``.

    The metadata is stamped with the schema version and a CRC32 per
    array; the write goes through a same-directory temp file + fsync +
    rename so a crash at any point leaves either the old file or the
    new one — never a torn mix.
    """
    meta = dict(meta)
    meta["version"] = CHECKPOINT_VERSION
    meta["crc"] = {k: _crc(np.asarray(v)) for k, v in arrays.items()}
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".ck_tmp_", suffix=".npz")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta),
                     **{k: np.asarray(v) for k, v in arrays.items()})
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_state_npz(path: str) -> Tuple[Dict[str, Any],
                                       Dict[str, np.ndarray]]:
    """Load and VERIFY a checkpoint container.

    Raises ``ValueError`` (with the reason) on: unreadable/truncated
    zip, missing metadata, unsupported schema version, or any array
    whose CRC32 does not match the recorded one.  v1 files (no version
    field, no CRCs) are still accepted — there is nothing to verify.
    """
    import zipfile

    try:
        with np.load(path, allow_pickle=False) as data:
            if "__meta__" not in data:
                raise ValueError(
                    f"checkpoint {path!r} has no __meta__ entry — not a "
                    f"pydcop_tpu checkpoint"
                )
            meta = json.loads(str(data["__meta__"]))
            arrays = {k: data[k] for k in data.files if k != "__meta__"}
    except (zipfile.BadZipFile, OSError, EOFError, KeyError) as e:
        raise ValueError(
            f"checkpoint {path!r} is unreadable or truncated: {e}"
        ) from e
    version = int(meta.get("version", 1))
    if version > CHECKPOINT_VERSION:
        raise ValueError(
            f"checkpoint {path!r} has schema version {version}, this "
            f"build reads <= {CHECKPOINT_VERSION} — refusing to guess"
        )
    crcs = meta.get("crc") or {}
    for name, want in crcs.items():
        if name not in arrays:
            raise ValueError(
                f"checkpoint {path!r} is missing array {name!r} listed "
                f"in its checksum table — truncated or tampered file"
            )
        got = _crc(arrays[name])
        if got != int(want):
            raise ValueError(
                f"checkpoint {path!r}: checksum mismatch on {name!r} "
                f"(recorded {int(want):#010x}, computed {got:#010x}) — "
                f"corrupt file, refusing to load"
            )
    return meta, arrays


# --------------------------------------------------------------------------
# solver-level save/load (thread-mode runtime)
# --------------------------------------------------------------------------

def save_checkpoint(path: str, solver, extra: Optional[Dict] = None,
                    cycle: Optional[int] = None) -> None:
    """Persist a solver's last run state (host-transferred) + metadata."""
    import jax

    state = getattr(solver, "_last_state", None)
    if state is None:
        raise ValueError("Solver has no state yet — run() it first")
    leaves, treedef = jax.tree.flatten(state)
    meta = {
        "kind": "solver",
        "algo": solver.algo_def.algo,
        "params": solver.algo_def.params,
        "seed": solver.seed,
        # precision tier the state leaves were produced under: int8 leaves
        # carry quantized tables, bf16 leaves carry bfloat16 messages — a
        # restore into a solver staged at another tier would silently mix
        # representations, so load_checkpoint refuses on mismatch
        "precision": getattr(solver, "precision", "f32"),
        "n_leaves": len(leaves),
        "extra": extra or {},
    }
    if cycle is not None:
        meta["cycle"] = int(cycle)
    # schema v3: warm-repair solvers persist their headroom layout so a
    # resume restores a mutated problem at its exact padded shape (the
    # mutated ARRAYS already ride in the state leaves — the layout's
    # claimed/free slot maps + host metadata make them addressable)
    layout = getattr(solver, "layout", None)
    if layout is not None and hasattr(layout, "to_meta"):
        t = solver.tensors
        hmeta = {
            "layout": layout.to_meta(),
            "var_names": list(t.var_names),
            "domain_values": [list(d) for d in t.domain_values],
            "factor_names": list(t.factor_names),
        }
        try:
            json.dumps(hmeta)
        except (TypeError, ValueError):
            logger.warning(
                "headroom metadata is not JSON-serializable (exotic "
                "domain values?); checkpoint saved without it"
            )
        else:
            meta["headroom"] = hmeta
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    # the PRNG key travels with the state: a warm run after restore must
    # CONTINUE the random stream, not replay it from the seed
    key = getattr(solver, "_last_key", None)
    if key is not None:
        arrays["__prng_key__"] = np.asarray(key)
    write_state_npz(path, arrays, meta)


def load_checkpoint(path: str, solver) -> Dict[str, Any]:
    """Restore a solver's state; returns the checkpoint metadata.

    The solver must have been built for the same problem (leaf shapes
    are validated against a freshly initialized state).  Corrupt,
    truncated or version-mismatched files raise ``ValueError`` before
    any state is touched.
    """
    import jax

    meta, arrays = read_state_npz(path)
    try:
        leaves = [arrays[f"leaf_{i}"] for i in range(meta["n_leaves"])]
    except KeyError as e:
        raise ValueError(
            f"checkpoint {path!r} is missing state leaf {e} — truncated "
            f"or foreign file"
        ) from e
    key = arrays.get("__prng_key__")
    ckpt_tier = meta.get("precision", "f32")
    solver_tier = getattr(solver, "precision", "f32")
    if ckpt_tier != solver_tier:
        from ..ops.precision import PrecisionError

        raise PrecisionError(
            f"checkpoint {path!r} was saved at precision={ckpt_tier!r} but "
            f"the restoring solver is staged at precision={solver_tier!r}; "
            f"rebuild the solver with precision={ckpt_tier!r} to resume "
            f"this checkpoint (state leaves are tier-specific)"
        )
    ref_state = solver.initial_state()
    ref_leaves, treedef = jax.tree.flatten(ref_state)
    if len(ref_leaves) != len(leaves):
        raise ValueError(
            f"Checkpoint has {len(leaves)} state leaves, solver expects "
            f"{len(ref_leaves)}"
        )
    for got, want in zip(leaves, ref_leaves):
        if np.shape(got) != np.shape(want):
            raise ValueError(
                f"Checkpoint leaf shape {np.shape(got)} != solver "
                f"{np.shape(want)} — different problem?"
            )
    solver._last_state = jax.tree.unflatten(treedef, leaves)
    if key is not None:
        import jax.numpy as jnp

        solver._last_key = jnp.asarray(key)
    hmeta = meta.get("headroom")
    if hmeta and hasattr(solver, "restore_headroom_meta"):
        # v3: re-adopt the claimed/free slot maps so the restored
        # (possibly mutated) arrays are addressable by name again
        solver.restore_headroom_meta(hmeta)
    return meta


# --------------------------------------------------------------------------
# snapshot directories: periodic saves + rotation + resume
# --------------------------------------------------------------------------

class CheckpointManager:
    """Rotating snapshot directory: ``<dir>/ck_<cycle>.npz``.

    ``save*()`` writes a snapshot for a cycle and prunes all but the
    ``keep`` newest; ``latest_valid*()`` walks snapshots newest-first,
    skipping (and logging) any that fail verification — one corrupt
    file costs one snapshot of progress, not the run.
    """

    PREFIX = "ck_"

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = max(1, keep)

    def path_for(self, cycle: int) -> str:
        return os.path.join(self.directory,
                            f"{self.PREFIX}{int(cycle):08d}.npz")

    def snapshots(self) -> List[Tuple[int, str]]:
        """(cycle, path) list, newest (highest cycle) first."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if not (name.startswith(self.PREFIX)
                    and name.endswith(".npz")):
                continue
            try:
                cycle = int(name[len(self.PREFIX):-len(".npz")])
            except ValueError:
                continue
            out.append((cycle, os.path.join(self.directory, name)))
        return sorted(out, reverse=True)

    def latest(self) -> Optional[Tuple[int, str]]:
        snaps = self.snapshots()
        return snaps[0] if snaps else None

    def _rotate(self) -> None:
        for _cycle, path in self.snapshots()[self.keep:]:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- raw state (mesh ranks) ---------------------------------------------

    def save_state(self, cycle: int, arrays: Dict[str, np.ndarray],
                   meta: Dict[str, Any]) -> str:
        os.makedirs(self.directory, exist_ok=True)
        meta = dict(meta)
        meta["cycle"] = int(cycle)
        path = self.path_for(cycle)
        write_state_npz(path, arrays, meta)
        self._rotate()
        return path

    def latest_valid_state(self) -> Optional[
            Tuple[int, Dict[str, Any], Dict[str, np.ndarray]]]:
        for cycle, path in self.snapshots():
            try:
                meta, arrays = read_state_npz(path)
            except ValueError as e:
                logger.warning("skipping damaged checkpoint %s: %s",
                               path, e)
                continue
            return cycle, meta, arrays
        return None

    # -- solver state (thread-mode runtime) ---------------------------------

    def save_solver(self, solver, cycle: int,
                    extra: Optional[Dict] = None) -> str:
        os.makedirs(self.directory, exist_ok=True)
        path = self.path_for(cycle)
        save_checkpoint(path, solver, extra=extra, cycle=cycle)
        self._rotate()
        return path

    def load_latest_into(self, solver) -> Optional[Dict[str, Any]]:
        """Restore the newest loadable snapshot into ``solver``; skips
        corrupt files (logged) AND shape-mismatched ones (a different
        problem's directory should not brick the run when resuming is
        best-effort).  Returns its metadata, or None."""
        for _cycle, path in self.snapshots():
            try:
                return load_checkpoint(path, solver)
            except ValueError as e:
                logger.warning("skipping unusable checkpoint %s: %s",
                               path, e)
        return None
