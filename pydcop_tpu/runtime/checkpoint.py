"""Checkpoint / resume of solver state.

The reference has no checkpointing (resilience is replication-based,
SURVEY.md §5); for a dense tensor solver a checkpoint is just the state
pytree, so we add it: save/restore the solver's device state + metadata to
a single .npz file.  Used by the orchestrator for resilience and by
long-running batch solves.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def save_checkpoint(path: str, solver, extra: Optional[Dict] = None) -> None:
    """Persist a solver's last run state (host-transferred) + metadata."""
    state = getattr(solver, "_last_state", None)
    if state is None:
        raise ValueError("Solver has no state yet — run() it first")
    leaves, treedef = jax.tree.flatten(state)
    meta = {
        "algo": solver.algo_def.algo,
        "params": solver.algo_def.params,
        "seed": solver.seed,
        "n_leaves": len(leaves),
        "extra": extra or {},
    }
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    # the PRNG key travels with the state: a warm run after restore must
    # CONTINUE the random stream, not replay it from the seed
    key = getattr(solver, "_last_key", None)
    if key is not None:
        arrays["__prng_key__"] = np.asarray(key)
    np.savez(path, __meta__=json.dumps(meta), **arrays)


def load_checkpoint(path: str, solver) -> Dict[str, Any]:
    """Restore a solver's state; returns the checkpoint metadata.

    The solver must have been built for the same problem (leaf shapes are
    validated against a freshly initialized state).
    """
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        leaves = [data[f"leaf_{i}"] for i in range(meta["n_leaves"])]
        key = data["__prng_key__"] if "__prng_key__" in data else None
    ref_state = solver.initial_state()
    ref_leaves, treedef = jax.tree.flatten(ref_state)
    if len(ref_leaves) != len(leaves):
        raise ValueError(
            f"Checkpoint has {len(leaves)} state leaves, solver expects "
            f"{len(ref_leaves)}"
        )
    for got, want in zip(leaves, ref_leaves):
        if np.shape(got) != np.shape(want):
            raise ValueError(
                f"Checkpoint leaf shape {np.shape(got)} != solver "
                f"{np.shape(want)} — different problem?"
            )
    solver._last_state = jax.tree.unflatten(treedef, leaves)
    if key is not None:
        import jax.numpy as jnp

        solver._last_key = jnp.asarray(key)
    return meta
