"""Virtual orchestrator: the host-side control plane.

Equivalent capability to the reference's
pydcop/infrastructure/orchestrator.py (Orchestrator :62, AgentsMgt :531,
deploy :203, start_replication :223, run(scenario) :245, scenario pump
:336, agent-removal repair handshake :943-1125) — with the actor plumbing
removed: deploy/run/pause/stop are host control flow over one tensor
solver, scenario events mutate the placement metadata, and the repair
handshake becomes build-repair-DCOP → solve-with-MGM-kernel → update
Distribution.

The solver state lives on device across events (warm restart), matching
the reference's behavior where computations keep their state when re-hosted
from replicas.
"""
from __future__ import annotations

from time import perf_counter
from typing import Any, Callable, Dict, List, Optional, Union

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.scenario import Scenario
from pydcop_tpu.distribution import load_distribution_module
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.graph import load_graph_module
from pydcop_tpu.replication import ReplicaDistribution, place_replicas
from pydcop_tpu.reparation import build_repair_dcop, solve_repair_dcop
from pydcop_tpu.runtime.events import event_bus, send_fault
from pydcop_tpu.runtime.faults import FaultPlan
from pydcop_tpu.runtime.stats import FaultCounters


class VirtualOrchestrator:
    def __init__(
        self,
        dcop: DCOP,
        algo: Union[str, AlgorithmDef],
        distribution: Union[str, Distribution] = "oneagent",
        graph: Optional[str] = None,
        collect_on: str = "value_change",
        period: Optional[float] = None,
        collector: Optional[Callable[[float, Dict], None]] = None,
        seed: int = 0,
        fault_plan: Optional[FaultPlan] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 10,
        auto_resume: bool = False,
        warm_repair: bool = False,
        headroom: float = 0.25,
    ):
        self.dcop = dcop
        self.algo_def = (
            algo
            if isinstance(algo, AlgorithmDef)
            else AlgorithmDef.build_with_default_params(
                algo, mode=dcop.objective
            )
        )
        self.algo_module = load_algorithm_module(self.algo_def.algo)
        graph_type = graph or self.algo_module.GRAPH_TYPE
        self.graph_module = load_graph_module(graph_type)
        self.cg = self.graph_module.build_computation_graph(dcop)

        if isinstance(distribution, Distribution):
            self.distribution = distribution
        else:
            dist_module = load_distribution_module(distribution)
            self.distribution = dist_module.distribute(
                self.cg,
                dcop.agents.values(),
                hints=getattr(dcop, "dist_hints", None),
                computation_memory=self.algo_module.computation_memory,
                communication_load=self.algo_module.communication_load,
            )

        # warm repair (ISSUE 8): scenario mutations and agent churn
        # become fixed-shape buffer writes on a headroom-padded solver
        # instead of cold restarts — runtime/repair.WarmRepairController
        self.warm = None
        if warm_repair:
            from pydcop_tpu.runtime.repair import WarmRepairController

            self.warm = WarmRepairController(
                dcop, self.algo_def.algo, algo_def=self.algo_def,
                seed=seed, headroom=headroom,
            )
            self.solver = self.warm.solver
        else:
            self.solver = self.algo_module.build_solver(
                dcop, self.cg, self.algo_def, seed=seed
            )
        self.replicas: Optional[ReplicaDistribution] = None
        self.seed = seed
        self.status = "INITIAL"
        self.collect_on = collect_on
        self.period = period
        self.collector = collector
        self.run_metrics_log: List[Dict] = []
        self.events_log: List[Dict] = []
        self._resume_next = False
        self._pre_pause_status = "INITIAL"
        self._last_result: Optional[SolveResult] = None
        self._cycles_done = 0
        self.start_time: Optional[float] = None
        #: measured device rate (cycles/s) for scenario delay budgets
        self._cycle_rate: Optional[float] = None
        # -- resilience: fault injection + checkpoint/auto-resume ----------
        self.fault_plan = fault_plan
        self.fault_counters = FaultCounters()
        # kill_agent + the seeded churn kinds (remove/add_agent_burst,
        # edit_factor) all fire at phase boundaries through one pending
        # list — the churn stream and the fault story share a path
        self._pending_agent_kills = list(
            fault_plan.churn_faults()) if fault_plan else []
        self.checkpoint_every = max(1, checkpoint_every)
        self.auto_resume = auto_resume
        self._ckpt_mgr = None
        self._last_ckpt_cycle = 0
        self._resume_done = False
        if checkpoint_dir:
            from pydcop_tpu.runtime.checkpoint import CheckpointManager

            self._ckpt_mgr = CheckpointManager(checkpoint_dir)

    # -- lifecycle (reference: deploy/run/pause/stop broadcasts) ------------

    def deploy_computations(self) -> None:
        missing = [
            n.name for n in self.cg.nodes
            if not self.distribution.has_computation(n.name)
        ]
        if missing:
            raise ValueError(
                f"Distribution does not host computations: {missing}"
            )
        self.status = "DEPLOYED"
        for a in self.distribution.agents:
            for c in self.distribution.computations_hosted(a):
                event_bus.send(f"agents.add_computation.{a}", c)

    def pause_computations(self) -> None:
        """Reference parity (PauseMessage broadcast, orchestrator.py
        :1127): between phases every computation is naturally paused —
        device state is retained and nothing advances until the next
        run; this marks the status and blocks further phases until
        :meth:`resume_computations`."""
        if self.status == "INITIAL":
            raise RuntimeError(
                "nothing to pause: deploy_computations() first"
            )
        if self.status == "STOPPED":
            raise RuntimeError("orchestrator was stopped; cannot pause")
        if self.status == "PAUSED":
            return  # idempotent: keep the original pre-pause status
        self._pre_pause_status = self.status
        self.status = "PAUSED"

    def resume_computations(self) -> None:
        """Reference parity (ResumeMessage broadcast): continue from the
        retained solver state — the next run() warm-restarts from
        exactly where pause left off."""
        if self.status == "PAUSED":
            self.status = self._pre_pause_status
            self._resume_next = True

    def stop_agents(self, timeout: Optional[float] = None) -> None:
        """Reference parity (StopMessage broadcast, orchestrator.py
        :290): no agent threads exist to join; marks the run stopped."""
        self.status = "STOPPED"

    def start_replication(self, k: int) -> ReplicaDistribution:
        """Place k replicas of every computation (reference:
        orchestrator.py:223 → distributed UCS)."""
        self.replicas = place_replicas(
            [n.name for n in self.cg.nodes],
            self.distribution,
            self.dcop.agents.values(),
            k,
            computation_memory=lambda c: self.algo_module.computation_memory(
                self.cg.computation(c)
            ),
        )
        self.status = "REPLICATING" if self.status == "INITIAL" \
            else self.status
        return self.replicas

    # -- solving ------------------------------------------------------------

    def _run_phase(
        self, cycles: Optional[int], timeout: Optional[float], resume: bool
    ) -> SolveResult:
        if self.warm is not None:
            # a repack may have swapped the solver; one PINNED chunk
            # size so every phase reuses the same compiled runner
            self.solver = self.warm.solver
        res = self.solver.run(
            cycles=cycles,
            timeout=timeout,
            collect_cycles=self.collect_on == "cycle_change"
            or self.collector is not None,
            resume=resume,
            chunk=self.warm.chunk if self.warm is not None else None,
        )
        if self.warm is not None:
            self.warm.phase_done(res)
        self._cycles_done += res.cycle
        self._last_result = res
        if self.collector is not None and res.history:
            for h in res.history:
                m = {**res.metrics(), **h, "status": "RUNNING"}
                self.collector(h["time"], m)
                self.run_metrics_log.append(m)
        event_bus.send("computations.cycle.*", self._cycles_done)
        self._fire_due_agent_kills()
        self._maybe_checkpoint()
        return res

    # -- resilience hooks (phase boundaries) --------------------------------

    def _fire_due_agent_kills(self) -> None:
        """Fault-plan churn faults fire at the first phase boundary past
        their cycle — kill_agent (the fault-injection twin of a
        scenario's remove_agent event) plus the seeded churn kinds
        (remove_agent_burst / add_agent_burst / edit_factor), all
        routed through the same replica-repair / warm-repair
        handshake."""
        due = [f for f in self._pending_agent_kills
               if f.cycle <= self._cycles_done]
        self._pending_agent_kills = [
            f for f in self._pending_agent_kills
            if f.cycle > self._cycles_done
        ]
        for f in due:
            self._fire_churn_fault(f)

    def _fire_churn_fault(self, f) -> None:
        seed = self.fault_plan.seed if self.fault_plan else 0
        if f.kind == "kill_agent":
            if f.agent not in self.dcop.agents:
                return  # already removed (scenario or earlier fault)
            targets = [f.agent]
        elif f.kind == "remove_agent_burst":
            import numpy as _np

            alive = sorted(self.dcop.agents)
            rng = _np.random.default_rng(
                (int(seed) * 6151 + int(f.cycle)) % (2 ** 32))
            n = min(f.count or 1, max(0, len(alive) - 1))
            if n <= 0:
                return
            targets = sorted(
                rng.choice(len(alive), size=n, replace=False).tolist()
            )
            targets = [alive[i] for i in targets]
        elif f.kind == "add_agent_burst":
            from pydcop_tpu.dcop.objects import AgentDef

            for i in range(f.count or 1):
                name = f"churn_a{f.cycle}_{i}"
                if name not in self.dcop.agents:
                    self.dcop.agents[name] = AgentDef(name)
                    self.distribution.host_on_agent(name, [])
            self.fault_counters.inc("faults_injected")
            send_fault("injected.add_agent_burst", {
                "count": f.count or 1, "cycle": self._cycles_done,
            })
            self.events_log.append(
                {"fault": "add_agent_burst", "count": f.count or 1,
                 "cycle": self._cycles_done}
            )
            return
        elif f.kind == "edit_factor":
            name = self._edit_factor_fault(f, seed)
            self.fault_counters.inc("faults_injected")
            send_fault("injected.edit_factor", {
                "constraint": name, "cycle": self._cycles_done,
            })
            self.events_log.append(
                {"fault": "edit_factor", "constraint": name,
                 "cycle": self._cycles_done}
            )
            return
        else:  # pragma: no cover - churn_faults() filters the kinds
            return
        self.fault_counters.inc("faults_injected")
        send_fault(f"injected.{f.kind}", {
            "agents": targets, "cycle": self._cycles_done,
        })
        self._agents_removal(targets)
        self.events_log.append(
            {"fault": f.kind, "agents": targets,
             "cycle": self._cycles_done}
        )

    def _edit_factor_fault(self, f, seed: int) -> str:
        """An edit_factor churn fault: warm path mutates in place; the
        cold path requires a hot-swap capable solver (maxsum_dynamic)
        and pays its compiled-chunk flush — exactly the gap the warm
        layer closes."""
        if self.warm is not None:
            return self.warm.edit_factor_fault(f, seed)
        from pydcop_tpu.runtime.repair import perturbed_constraint

        if not hasattr(self.solver, "change_factor_function"):
            raise ValueError(
                f"algorithm {self.algo_def.algo!r} cannot hot-swap "
                "factors; use --warm-repair (or maxsum_dynamic) for "
                "edit_factor fault plans"
            )
        names = sorted(self.dcop.constraints)
        name = f.constraint
        if name is None:
            import numpy as _np

            rng = _np.random.default_rng(
                (int(seed) * 7919 + int(f.cycle)) % (2 ** 32))
            name = names[int(rng.integers(len(names)))]
        elif name not in self.dcop.constraints:
            raise ValueError(
                f"edit_factor fault: unknown constraint {name!r}")
        new_c = perturbed_constraint(
            self.dcop.constraints[name], seed=seed + f.cycle)
        self.solver.change_factor_function(new_c)
        return name

    def _maybe_checkpoint(self) -> None:
        if self._ckpt_mgr is None:
            return
        if self._cycles_done - self._last_ckpt_cycle < self.checkpoint_every:
            return
        if getattr(self.solver, "_last_state", None) is None:
            return  # host-driven solver without retained device state
        try:
            self._ckpt_mgr.save_solver(self.solver, self._cycles_done)
        except ValueError:
            return
        self._last_ckpt_cycle = self._cycles_done
        self.fault_counters.inc("checkpoints_saved")

    def _maybe_resume(self) -> None:
        """Auto-resume: warm-start from the newest valid snapshot once,
        before the first phase (corrupt snapshots are skipped by the
        manager with a warning — one bad file must not cost the run)."""
        if not (self.auto_resume and self._ckpt_mgr) or self._resume_done:
            return
        self._resume_done = True
        n_snaps = len(self._ckpt_mgr.snapshots())
        meta = self._ckpt_mgr.load_latest_into(self.solver)
        if meta is None:
            if n_snaps:
                self.fault_counters.inc("checkpoints_rejected", n_snaps)
            return
        self._resume_next = True
        cycle = int(meta.get("cycle", 0) or 0)
        self._cycles_done = cycle
        self._last_ckpt_cycle = cycle
        self.fault_counters.inc("resumes")
        send_fault("recovered.resume", {"cycle": cycle})
        self.events_log.append({"resumed_from": cycle})

    def run(
        self,
        scenario: Optional[Scenario] = None,
        timeout: Optional[float] = None,
        cycles: Optional[int] = None,
    ) -> SolveResult:
        """Run to completion; with a scenario, interleave solving phases
        with the event stream (reference: orchestrator.py:245,336)."""
        if self.status == "PAUSED":
            raise RuntimeError(
                "orchestrator is paused; call resume_computations() first"
            )
        if self.status == "STOPPED":
            raise RuntimeError(
                "orchestrator was stopped; create a new one to run again"
            )
        self.start_time = perf_counter()
        if self.status == "INITIAL":
            self.deploy_computations()
        self.status = "RUNNING"
        self._maybe_resume()
        resume = getattr(self, "_resume_next", False)
        self._resume_next = False

        if scenario is None or not len(scenario):
            res = self._run_plain(cycles, timeout, resume=resume)
            self.status = res.status
            return self._finalize(res)
        res: Optional[SolveResult] = None
        for event in scenario:
            if timeout is not None and \
                    perf_counter() - self.start_time > timeout:
                break
            if event.is_delay:
                # a delay = let the system run for that much wall time.
                # Scenario delays are written in seconds of solver
                # activity (reference: the actor system simply keeps
                # running, orchestrator.py:336); here the device rate is
                # measured on the first phase and each delay converts to
                # a cycle budget, so `delay: 2` runs ~2s worth of cycles
                # instead of an arbitrary fixed count.  The effective
                # delay also bounds the phase as a timeout (safety when
                # the rate estimate is stale) and is clamped to the
                # run-level timeout's remaining budget.
                eff = event.delay
                if timeout is not None:
                    remaining = timeout - (
                        perf_counter() - self.start_time
                    )
                    eff = max(0.0, min(eff, remaining))
                if eff > 0:
                    res = self._delay_phase(eff, cycles, resume)
                    resume = True
            else:
                for action in event.actions:
                    self._apply_action(action)
                self.events_log.append(
                    {"id": event.id,
                     "actions": [a.type for a in event.actions]}
                )
        # final phase to (re)converge after the last event: the explicit
        # per-phase cycle count unbounded (caller's contract), else the
        # budget of a 1-second delay clamped to the remaining run timeout
        if cycles is not None:
            res = self._run_phase(cycles, timeout=None, resume=resume)
        else:
            final_delay = 1.0
            if timeout is not None:
                remaining = timeout - (perf_counter() - self.start_time)
                final_delay = min(1.0, remaining)
            if final_delay > 0 or res is None:
                res = self._delay_phase(
                    max(final_delay, 0.05), None, resume
                )
        if timeout is not None and \
                perf_counter() - self.start_time > timeout:
            res.status = "TIMEOUT"
        self.status = res.status
        return self._finalize(res)

    def _run_plain(self, cycles: Optional[int], timeout: Optional[float],
                   resume: bool) -> SolveResult:
        """A scenario-less run; with an explicit cycle budget the run is
        split at fault-plan agent-kill cycles (so each kill fires
        MID-run and the solve re-converges after the repair) and at
        checkpoint boundaries (so snapshots land every *k* cycles, not
        only at the end).  With no explicit budget the solver runs its
        default phase unbroken."""
        target = None if cycles is None else self._cycles_done + cycles
        res = None
        while True:
            n = cycles
            stops = [f.cycle for f in self._pending_agent_kills
                     if f.cycle > self._cycles_done]
            if self._ckpt_mgr is not None:
                stops.append(self._cycles_done + self.checkpoint_every)
            if target is not None:
                stop = min(stops + [target])
                n = stop - self._cycles_done
            res = self._run_phase(n, timeout, resume=resume)
            resume = True
            if target is None or self._cycles_done >= target \
                    or res.status == "TIMEOUT":
                return res

    #: cycles of the rate-calibration phase (first delay event) and the
    #: upper bound on any single delay phase's budget
    CALIBRATION_CYCLES = 20
    MAX_PHASE_CYCLES = 200_000

    def _delay_phase(self, delay: float, cycles: Optional[int],
                     resume: bool) -> SolveResult:
        """One scenario solving phase worth ``delay`` seconds.

        With an explicit per-phase ``cycles`` the caller's count wins
        (back-compat / deterministic tests), bounded by the delay.
        Otherwise the first phase runs CALIBRATION_CYCLES to measure the
        device rate, then every delay converts to ``delay * rate``
        cycles; the rate is refreshed from each phase so drift (bigger
        tables after repair, metric collection) is tracked.
        """
        if cycles is not None:
            return self._normalize(
                self._run_phase(cycles, timeout=delay, resume=resume)
            )
        if self._cycle_rate is not None:
            res = self._run_phase(
                self._budget(delay), timeout=delay, resume=resume
            )
            self._update_rate(res)
            return self._normalize(res)
        # cold start: the calibration phase's wall time includes jit
        # compilation, so its rate wildly underestimates the device.
        # Top up against the REMAINING wall budget of this delay (so one
        # event never runs ~2x its duration) until it is consumed; the
        # warm top-up rates replace the compile-skewed first estimate.
        t0 = perf_counter()
        res = self._run_phase(
            self.CALIBRATION_CYCLES, timeout=delay, resume=resume
        )
        self._update_rate(res)
        for _ in range(4):
            remaining = delay - (perf_counter() - t0)
            if remaining <= max(0.05 * delay, 1e-3):
                break
            res = self._run_phase(
                self._budget(remaining), timeout=remaining, resume=True
            )
            self._update_rate(res)
        return self._normalize(res)

    @staticmethod
    def _normalize(res: SolveResult) -> SolveResult:
        """A delay phase cut by its wall budget behaved exactly as asked
        ("run for that much time") — that is not a run-level TIMEOUT.
        run() re-applies TIMEOUT when the RUN deadline is exhausted."""
        if res.status == "TIMEOUT":
            res.status = "FINISHED"
        return res

    def _budget(self, delay: float) -> int:
        return max(1, min(
            self.MAX_PHASE_CYCLES, int(round(delay * self._cycle_rate))
        ))

    def _update_rate(self, res: SolveResult) -> None:
        if res.cycle > 0 and res.time > 0:
            self._cycle_rate = res.cycle / res.time

    def _finalize(self, res: SolveResult) -> SolveResult:
        res.cycle = self._cycles_done
        res.time = perf_counter() - self.start_time
        if self._ckpt_mgr is not None \
                and self._cycles_done > self._last_ckpt_cycle:
            # final snapshot: a new orchestrator can auto-resume from
            # exactly where this run ended
            self._last_ckpt_cycle = self._cycles_done
            if getattr(self.solver, "_last_state", None) is not None:
                self._ckpt_mgr.save_solver(self.solver, self._cycles_done)
                self.fault_counters.inc("checkpoints_saved")
        return res

    # -- scenario actions ---------------------------------------------------

    def _apply_action(self, action) -> None:
        if action.type == "remove_agent":
            self._agents_removal([action.parameters["agent"]])
        elif action.type == "add_agent":
            # new agents become available hosts (computations stay put until
            # a repair needs them)
            from pydcop_tpu.dcop.objects import AgentDef

            name = action.parameters["agent"]
            if name not in self.dcop.agents:
                self.dcop.agents[name] = AgentDef(name)
            self.distribution.host_on_agent(name, [])
        elif action.type == "set_external":
            if self.warm is not None:
                self.warm.external_change(
                    action.parameters["variable"],
                    action.parameters["value"],
                )
                return
            ev = self.dcop.external_variables[
                action.parameters["variable"]
            ]
            ev.value = action.parameters["value"]
            if hasattr(self.solver, "on_external_change"):
                self.solver.on_external_change(ev.name, ev.value)
        elif action.type in ("add_constraint", "remove_constraint",
                             "add_variable", "remove_variable"):
            # structural mutations (ISSUE 8): only the warm-repair
            # layer can rewire a compiled problem at a fixed shape
            if self.warm is None:
                raise ValueError(
                    f"scenario action {action.type!r} needs the "
                    "warm-repair layer; run with warm_repair=True "
                    "(CLI: --warm-repair)"
                )
            self._apply_structural(action)
        elif action.type == "change_factor":
            # factor hot-swap mid-scenario (∅→+ over the reference's
            # add/remove_agent events; pairs with maxsum_dynamic's
            # change_factor_function, ref maxsum_dynamic.py:188)
            from pydcop_tpu.dcop.relations import constraint_from_str

            if self.warm is None and not hasattr(
                    self.solver, "change_factor_function"):
                raise ValueError(
                    f"algorithm {self.algo_def.algo!r} cannot hot-swap "
                    "factors; use maxsum_dynamic (or --warm-repair) "
                    "for change_factor scenarios"
                )
            name = action.parameters["constraint"]
            if name not in self.dcop.constraints:
                raise ValueError(
                    f"change_factor: unknown constraint {name!r}"
                )
            old = self.dcop.constraints[name]
            expr = action.parameters.get("expression")
            if expr is None:
                # seeded-perturbation form (dcop/scenario.churn_scenario
                # and the edit_factor fault kind share the jitter)
                from pydcop_tpu.runtime.repair import (
                    perturbed_constraint,
                )

                new_c = perturbed_constraint(
                    old, seed=int(action.parameters.get("seed", 0))
                )
            else:
                scope = list(old.dimensions) + [
                    ev for ev in self.dcop.external_variables.values()
                ]
                new_c = constraint_from_str(name, expr, scope)
            if self.warm is not None:
                self.warm.edit_factor(new_c)
            else:
                self.solver.change_factor_function(new_c)
        else:
            raise ValueError(f"Unknown scenario action {action.type!r}")

    def _apply_structural(self, action) -> None:
        """Warm-only structural scenario actions: grow/shrink the live
        problem inside the reserved headroom (zero retraces; one
        counted repack when exhausted)."""
        from pydcop_tpu.dcop.relations import constraint_from_str

        p = action.parameters
        if action.type == "add_constraint":
            scope = [self.dcop.variables[n] for n in p["scope"]] + [
                ev for ev in self.dcop.external_variables.values()
            ]
            new_c = constraint_from_str(
                p["constraint"], p["expression"], scope
            )
            self.warm.add_constraint(new_c)
        elif action.type == "remove_constraint":
            self.warm.remove_constraint(p["constraint"])
        elif action.type == "add_variable":
            from pydcop_tpu.dcop.objects import Variable

            domain = self.dcop.domains[p["domain"]]
            self.warm.add_variable(Variable(p["variable"], domain))
        else:  # remove_variable
            self.warm.remove_variable(p["variable"])

    def _agents_removal(self, removed: List[str]) -> None:
        """Orphaned computations are re-hosted on their replicas via a
        repair DCOP solved with MGM (reference: orchestrator.py:943-1125 +
        agents.py:1044-1355)."""
        orphans: List[str] = []
        for a in removed:
            orphans.extend(self.distribution.remove_agent(a))
            self.dcop.agents.pop(a, None)
            event_bus.send(f"agents.rem_agent.{a}", a)
        if not orphans:
            return
        surviving = {a.name: a for a in self.dcop.agents.values()}
        candidates: Dict[str, List[str]] = {}
        for c in orphans:
            if self.replicas is not None:
                cand = [
                    a for a in self.replicas.replicas(c) if a in surviving
                ]
            else:
                cand = []
            # fall back to every surviving agent when no replica survives
            candidates[c] = cand or sorted(surviving)
        neighbors = {
            c: list(self.cg.computation(c).neighbors) for c in orphans
        }
        repair, vars_by_comp = build_repair_dcop(
            orphans,
            candidates,
            surviving,
            self.distribution,
            computation_memory=lambda c: self.algo_module.computation_memory(
                self.cg.computation(c)
            ),
            communication_load=lambda c, t: self.algo_module.
            communication_load(self.cg.computation(c), t),
            neighbors=neighbors,
        )
        placement = solve_repair_dcop(repair, vars_by_comp, seed=self.seed)
        for comp, agent in placement.items():
            self.distribution.host_on_agent(agent, [comp])
        if self.warm is not None:
            # warm re-seat: reparation picked the hosts; the solver
            # keeps its device state and only re-converges — time it
            self.warm.mark_recovery()
        self.events_log.append({"repaired": placement})
        self.fault_counters.inc("repairs")
        send_fault("recovered.repair", {
            "orphans": orphans, "placement": placement,
        })

    # -- metrics ------------------------------------------------------------

    def end_metrics(self) -> Dict[str, Any]:
        if self._last_result is None:
            return {"status": self.status}
        m = self._last_result.metrics()
        m["status"] = self.status
        m["distribution"] = self.distribution.mapping()
        if self.replicas is not None:
            m["replicas"] = self.replicas.mapping()
        m["events"] = self.events_log
        m["resilience"] = self.fault_counters.as_dict()
        if self.warm is not None:
            m["repair"] = self.warm.counters.as_dict()
        return m
