"""Warm-repair control plane: one mechanism for agent churn and live
mutations (ISSUE 8 tentpole).

The orchestrator used to treat every scenario mutation as a cold
restart; with ``warm_repair=True`` it routes all of them through this
controller instead:

* scenario actions (``change_factor``, ``set_external``, the new
  ``add_constraint`` / ``remove_constraint`` / ``add_variable`` /
  ``remove_variable``) become fixed-shape mutations on a warm solver
  (algorithms/warm) — in-place buffer writes, ZERO retraces;
* agent churn (scenario ``remove_agent`` / fault-plan ``kill_agent`` /
  the new seeded ``remove_agent_burst`` / ``add_agent_burst`` /
  ``edit_factor`` churn kinds, runtime/faults.CHURN_KINDS) rides the
  SAME path: ``reparation/`` still picks the new hosts from the
  replicas, and the warm solver re-seats the computation from its
  retained device state instead of solving from scratch;
* when the seeded headroom runs out the controller performs exactly
  ONE counted repack that re-reserves headroom (``repair.repack``
  event, one retrace — never an exception mid-run).

The controller owns the :class:`~pydcop_tpu.runtime.stats.
RepairCounters` scorecard (``SolveResult.metrics()["repair"]``,
forwarded as ``repair.*`` ws/SSE events) including the retrace audit:
every chunk-runner trace beyond the first phase's compile is charged
to ``repair_retraces`` — the churn acceptance test pins it at 0 while
headroom holds.
"""
from __future__ import annotations

from time import perf_counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.algorithms.warm import (
    WARM_ALGOS,
    build_warm_solver,
    repack_solver,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops.headroom import (
    AddFactor,
    AddVariable,
    EditFactor,
    HeadroomExhausted,
    RemoveFactor,
    RemoveVariable,
)
from pydcop_tpu.runtime.events import send_repair
from pydcop_tpu.runtime.stats import RepairCounters


def perturbed_constraint(c, seed: int, scale: float = 0.25):
    """A seeded perturbation of a constraint's cost table (the
    ``edit_factor`` churn fault): same scope, every entry jittered by
    uniform(-scale, scale) · (1 + |table|) — deterministic per (seed,
    constraint name), so the same plan replays the same mutation."""
    t = np.asarray(c.to_tensor(), dtype=np.float64)
    rng = np.random.default_rng(
        (int(seed) * 1_000_003 + hash(c.name) % 1_000_003) % (2 ** 32)
    )
    jitter = rng.uniform(-scale, scale, size=t.shape) * (1.0 + np.abs(t))
    return NAryMatrixRelation(list(c.dimensions), t + jitter, name=c.name)


class WarmRepairController:
    """Owns a warm solver and turns repairs/mutations into fixed-shape
    buffer writes; repacks once when headroom is exhausted."""

    def __init__(
        self,
        dcop: DCOP,
        algo,
        algo_def: Optional[AlgorithmDef] = None,
        seed: int = 0,
        headroom: float = 0.25,
        min_free: int = 4,
        chunk: int = 16,
        tensors=None,
    ):
        algo_name = algo if isinstance(algo, str) else algo.algo
        if algo_name not in WARM_ALGOS:
            raise ValueError(
                f"--warm-repair supports {WARM_ALGOS}; {algo_name!r} "
                f"falls back to the cold repack path (drop the flag)"
            )
        self.dcop = dcop
        self.seed = seed
        self.headroom = headroom
        self.min_free = min_free
        #: ONE chunk size for every phase: the masked fixed-shape
        #: runner compiles once and every later phase (any cycle
        #: budget, any deadline shrink) reuses it — vital for the
        #: zero-retrace guarantee
        self.chunk = int(chunk)
        self.counters = RepairCounters()
        self.solver = build_warm_solver(
            dcop, algo=algo_name, algo_def=algo_def, seed=seed,
            headroom=headroom, min_free=min_free, tensors=tensors,
        )
        self.solver.repair_counters = self.counters
        #: traces of retired (pre-repack) solvers
        self._trace_base = 0
        #: trace floor after the first phase's compiles — anything
        #: above it is charged to repair_retraces
        self._baseline: Optional[int] = None
        self._recover_t0: Optional[float] = None

    # -- trace audit --------------------------------------------------------

    def total_traces(self) -> int:
        return self._trace_base + self.solver.trace_count()

    def phase_done(self, res: SolveResult) -> None:
        """Called by the orchestrator after every solving phase:
        settles the retrace audit and, when a mutation was pending,
        records its time-to-recover."""
        cur = self.total_traces()
        if self._baseline is None:
            self._baseline = cur
        elif cur > self._baseline:
            self.counters.inc("repair_retraces", cur - self._baseline)
            self._baseline = cur
        if self._recover_t0 is not None:
            dt = perf_counter() - self._recover_t0
            self._recover_t0 = None
            self.counters.inc("time_to_recover_s", dt)
            send_repair("recovered", {
                "time_to_recover_s": round(dt, 6),
                "cycle": res.cycle,
                "cost": res.cost,
            })

    def mark_recovery(self) -> None:
        """Start the time-to-recover clock without a tensor mutation —
        the agent-churn repair handshake (re-hosting keeps the device
        state, but the run still re-converges)."""
        self._recover_t0 = perf_counter()

    # -- mutation entry points ----------------------------------------------

    def _claims_of(self, muts: Sequence) -> Dict[str, int]:
        claimed = sum(
            1 for m in muts if isinstance(m, (AddFactor, AddVariable))
        )
        released = sum(
            1 for m in muts if isinstance(m, (RemoveFactor, RemoveVariable))
        )
        return {"claimed": claimed, "released": released}

    def apply(self, muts: Sequence, kind: str, target: str) -> None:
        """Apply mutations warm; on exhaustion repack ONCE and retry —
        callers never see HeadroomExhausted."""
        self._recover_t0 = perf_counter()
        try:
            self.solver.apply_mutations(muts)
        except HeadroomExhausted as e:
            self.repack(str(e))
            self.solver.apply_mutations(muts)
        c = self._claims_of(muts)
        self.counters.inc("mutations_applied", len(muts))
        if c["claimed"]:
            self.counters.inc("headroom_claimed", c["claimed"])
        if c["released"]:
            self.counters.inc("headroom_released", c["released"])
        send_repair("mutation.applied", {
            "kind": kind,
            "target": target,
            "mutations": len(muts),
            "free_var_slots": len(self.solver.layout.free_var_slots()),
        })

    def repack(self, reason: str) -> None:
        """The graceful-degradation path: one repack that re-reserves
        headroom, state carried by name (algorithms/warm.repack_solver).
        Costs exactly one retrace on the next chunk — counted, evented,
        never an exception mid-run."""
        self._trace_base += self.solver.trace_count()
        self.solver = repack_solver(
            self.solver, headroom=self.headroom, min_free=self.min_free,
        )
        self.counters.inc("headroom_exhausted_repacks")
        send_repair("repack", {
            "reason": reason,
            "capacity_vars": self.solver.layout.n_vars_cap,
        })

    # -- scenario-action translation -----------------------------------------

    def edit_factor(self, new_constraint) -> None:
        name = new_constraint.name
        if name not in self.dcop.constraints:
            raise ValueError(f"change_factor: unknown constraint {name!r}")
        ext = {
            ev.name: ev.value
            for ev in self.dcop.external_variables.values()
        }
        sliced = (
            new_constraint.slice(ext)
            if any(n in ext for n in new_constraint.scope_names)
            else new_constraint
        )
        self.apply([EditFactor(sliced)], "edit_factor", name)
        self.dcop.constraints[name] = new_constraint

    def add_constraint(self, constraint) -> None:
        if constraint.name in self.dcop.constraints:
            raise ValueError(
                f"add_constraint: {constraint.name!r} already exists"
            )
        self.apply([AddFactor(constraint)], "add_factor", constraint.name)
        self.dcop.constraints[constraint.name] = constraint

    def remove_constraint(self, name: str) -> None:
        if name not in self.dcop.constraints:
            raise ValueError(f"remove_constraint: unknown {name!r}")
        self.apply([RemoveFactor(name)], "remove_factor", name)
        del self.dcop.constraints[name]

    def add_variable(self, variable) -> None:
        if variable.name in self.dcop.variables:
            raise ValueError(
                f"add_variable: {variable.name!r} already exists"
            )
        self.apply([AddVariable(variable)], "add_variable", variable.name)
        self.dcop.add_variable(variable)

    def remove_variable(self, name: str) -> None:
        if name not in self.dcop.variables:
            raise ValueError(f"remove_variable: unknown {name!r}")
        incident = [
            c.name for c in self.dcop.constraints.values()
            if name in c.scope_names
        ]
        muts: List = [RemoveFactor(c) for c in incident]
        muts.append(RemoveVariable(name))
        self.apply(muts, "remove_variable", name)
        for c in incident:
            del self.dcop.constraints[c]
        del self.dcop.variables[name]

    def external_change(self, ext_name: str, value) -> None:
        self.dcop.external_variables[ext_name].value = value
        ext = {
            ev.name: ev.value
            for ev in self.dcop.external_variables.values()
        }
        muts = [
            EditFactor(c.slice(ext))
            for n, c in self.dcop.constraints.items()
            if ext_name in c.scope_names
            and self.solver.layout.has_factor(n)
        ]
        if muts:
            self.apply(muts, "set_external", ext_name)

    # -- churn faults --------------------------------------------------------

    def edit_factor_fault(self, fault, plan_seed: int) -> str:
        """Fire one ``edit_factor`` churn fault: seeded constraint
        choice (unless named) + seeded table perturbation."""
        names = sorted(self.dcop.constraints)
        if not names:
            raise ValueError("edit_factor fault: DCOP has no constraints")
        if fault.constraint is not None:
            if fault.constraint not in self.dcop.constraints:
                raise ValueError(
                    f"edit_factor fault: unknown constraint "
                    f"{fault.constraint!r}"
                )
            name = fault.constraint
        else:
            rng = np.random.default_rng(
                (int(plan_seed) * 7919 + int(fault.cycle)) % (2 ** 32)
            )
            name = names[int(rng.integers(len(names)))]
        new_c = perturbed_constraint(
            self.dcop.constraints[name],
            seed=plan_seed + fault.cycle,
        )
        self.edit_factor(new_c)
        return name
