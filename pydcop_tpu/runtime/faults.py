"""Deterministic fault injection + liveness primitives.

The reference's resilience story is exercised by scenario events
(remove_agent) only; nothing in either codebase could *test* the
machinery against real failures — a crashed OS rank, a stalled rank
wedged inside a collective, a checkpoint file cut short by a power
loss.  This module is the harness for all of those:

* :class:`FaultPlan` — a seedable, YAML-loadable list of
  :class:`Fault` specs (kill rank *r* at cycle *c*, stall a rank,
  kill an agent mid-scenario, corrupt/truncate a checkpoint file).
  Driven from ``pydcop_tpu run --fault-plan plan.yaml`` and usable
  directly from tests.
* :class:`RankFaultInjector` — the rank-side consumer: the multihost
  agent consults it at every cycle-chunk boundary and the injector
  kills (``os._exit``) or stalls (``SIGSTOP``) the process exactly
  once per matching fault.
* :class:`HeartbeatWriter` / :func:`stalled_ranks` — the liveness
  channel between ranks and the coordinator watchdog: a daemon thread
  touches a per-rank file; a rank whose heartbeat goes stale is
  declared stalled.  ``SIGSTOP`` freezes the writer thread too, so an
  injected stall is indistinguishable from a real one.
* :func:`corrupt_checkpoint` — deterministic byte-flips / truncation
  for hardening tests of runtime/checkpoint.py.

Every random choice flows from an explicit seed; the same plan + seed
produces the same failure at the same point on every run.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: exit code of a fault-injected rank kill — the coordinator watchdog
#: classifies it (like signal deaths) as a retryable crash
KILL_EXIT_CODE = 101

#: env channel coordinator → ranks (a spawned rank cannot take the plan
#: as a Python object); value is ``FaultPlan.to_json()``
ENV_FAULT_PLAN = "PYDCOP_TPU_FAULT_PLAN"
#: env channel for the launch attempt counter (0 on the first launch,
#: +1 per watchdog relaunch) so faults can target one attempt only
ENV_FAULT_ATTEMPT = "PYDCOP_TPU_FAULT_ATTEMPT"

#: serve-layer fault kinds (consumed by ServeFaultInjector /
#: pydcop_tpu.serve.SolveService) — ``raise_in_step`` throws inside a
#: bucket's chunk step, ``nan_lane`` poisons one lane's float state,
#: ``torn_journal_write`` cuts a journal append short mid-line, and
#: ``stall_tick`` wedges one scheduler tick for ``duration`` seconds,
#: and ``corrupt_cache_entry`` flips bytes in a persisted solution-
#: cache entry right after it is written (serve/memo.py) — the CRC
#: check at rehydrate/adopt time must skip-and-count it, never serve it
SERVE_KINDS = ("raise_in_step", "nan_lane", "torn_journal_write",
               "stall_tick", "corrupt_cache_entry")

#: agent-churn / live-mutation fault kinds (consumed by the
#: orchestrator's warm-repair path, runtime/repair.py) —
#: ``remove_agent_burst`` removes ``count`` seeded-chosen agents at one
#: phase boundary (each routed through the replica-repair handshake),
#: ``add_agent_burst`` adds ``count`` fresh agents, and ``edit_factor``
#: hot-swaps a (seeded-chosen or named) constraint's cost table with a
#: seeded perturbation — the live-mutation twin of kill_agent, and the
#: driver of the sustained-churn bench leg (bench.py churn_recover)
CHURN_KINDS = ("remove_agent_burst", "add_agent_burst", "edit_factor")

#: fleet-layer fault kinds (consumed by the solve fleet's supervisor,
#: pydcop_tpu.serve.fleet.SolveFleet, through the same
#: :class:`ServeFaultInjector`) — ``kill_replica`` hard-stops one
#: replica's scheduler mid-trace (the thread-hosted twin of kill -9:
#: in-flight lanes are abandoned where they stand and only the
#: replica's journal survives), ``stall_replica`` wedges a replica's
#: scheduler for ``duration`` seconds (its heartbeat goes stale, the
#: router must route around it WITHOUT re-seating — a stall is not a
#: death), and ``partition_replica`` makes a replica unreachable for
#: new placements for ``duration`` seconds (0 = rest of the run) while
#: its in-flight jobs keep running
FLEET_KINDS = ("kill_replica", "stall_replica", "partition_replica")

#: process-fleet fault kinds (consumed by the process fleet's
#: supervisor, pydcop_tpu.serve.procfleet.ProcessFleet, through the
#: same :class:`ServeFaultInjector`) — ``kill_process`` SIGKILLs an
#: entire replica child process mid-trace (the REAL kill -9: every
#: lane, thread and socket of that process dies at once; detection is
#: heartbeat staleness + waitpid, recovery is the PR 6 re-seat),
#: ``partition_socket`` severs a replica's journal socket and refuses
#: its re-dials for ``duration`` seconds (frames buffer client-side
#: and replay-from-offset on heal — in-flight jobs keep running,
#: nothing double-applies), and ``corrupt_artifact`` flips one seeded
#: byte in a replica's exported runner artifact (the next loader must
#: reject it loudly on CRC and recompile)
PROCESS_KINDS = ("kill_process", "partition_socket", "corrupt_artifact")

#: runtime-layer (rank/agent/checkpoint) fault kinds — the original
#: PR 1 set, consumed by RankFaultInjector and the coordinator watchdog
RUNTIME_KINDS = ("kill_rank", "stall_rank", "kill_agent",
                 "corrupt_checkpoint", "truncate_checkpoint")

#: device-tier fault kinds (consumed by the elastic sharded driver,
#: pydcop_tpu.parallel.elastic.ElasticRunner / ElasticDpop) —
#: ``kill_device`` drops one mesh device at the next chunk boundary
#: (the solve shrinks onto the survivors; with a ``replica`` it instead
#: targets a fleet replica, which advertises reduced capacity to the
#: router), ``shrink_mesh`` shrinks the mesh to ``devices`` devices in
#: one step, and ``corrupt_slab`` flips one seeded bit in a named
#: staged device operand (``operand``, e.g. ``bucket0``/``q``/``x``/
#: ``local``) at a cycle boundary — the silent-data-corruption probe
#: the integrity sentinels and the shadow scrub must catch
DEVICE_KINDS = ("kill_device", "shrink_mesh", "corrupt_slab")

KINDS = (RUNTIME_KINDS + SERVE_KINDS + CHURN_KINDS + FLEET_KINDS
         + PROCESS_KINDS + DEVICE_KINDS)

#: the one catalog of which OPTIONAL fields each kind may address —
#: the machine-readable half of the fault-kind table in
#: docs/resilience.rst ("Fault-kind catalog"): the docs test pins that
#: every kind here is documented there and vice versa, and
#: :meth:`FaultPlan.validate` rejects a fault addressing a field its
#: kind never reads (the classic silent-no-op plan bug: a
#: ``stall_tick`` with a ``rank``, a ``kill_replica`` with an
#: ``agent``).  ``kind``/``cycle``/``attempt`` are legal on every
#: fault and not listed.
KIND_FIELDS: Dict[str, Tuple[str, ...]] = {
    "kill_rank": ("rank",),
    "stall_rank": ("rank", "duration"),
    "kill_agent": ("agent",),
    "corrupt_checkpoint": ("path",),
    "truncate_checkpoint": ("path",),
    "raise_in_step": ("jid",),
    "nan_lane": ("jid",),
    "torn_journal_write": ("jid",),
    "stall_tick": ("duration",),
    "corrupt_cache_entry": ("jid",),
    "edit_factor": ("constraint",),
    "remove_agent_burst": ("count",),
    "add_agent_burst": ("count",),
    "kill_replica": ("replica",),
    "stall_replica": ("replica", "duration"),
    "partition_replica": ("replica", "duration"),
    "kill_process": ("replica",),
    "partition_socket": ("replica", "duration"),
    "corrupt_artifact": ("replica", "path"),
    "kill_device": ("device", "replica"),
    "shrink_mesh": ("devices",),
    "corrupt_slab": ("operand", "device"),
}


@dataclasses.dataclass
class Fault:
    """One fault spec.  ``cycle`` faults fire at the first cycle-chunk
    boundary >= cycle (rank faults) or phase boundary (agent faults);
    ``attempt`` restricts a fault to one launch attempt (default 0 =
    the first launch only, so a relaunch can demonstrate recovery;
    None = every attempt)."""

    kind: str
    rank: Optional[int] = None  # kill_rank / stall_rank
    cycle: int = 0  # rank faults: cycle-chunk boundary; serve: tick
    duration: float = 0.0  # stall_rank / stall_tick: seconds stopped
    agent: Optional[str] = None  # kill_agent
    path: Optional[str] = None  # checkpoint faults: explicit file
    attempt: Optional[int] = 0
    #: serve faults: target job id.  A serve fault WITHOUT a jid fires
    #: once (a transient glitch the service must absorb); WITH a jid it
    #: keeps firing for that job (a poison job the quarantine must
    #: escalate to a terminal ERROR).
    jid: Optional[str] = None
    #: churn bursts: how many agents the burst removes/adds (default 1)
    count: Optional[int] = None
    #: edit_factor: the constraint to hot-swap (None = seeded choice)
    constraint: Optional[str] = None
    #: fleet faults: target replica index (kill_replica / stall_replica
    #: / partition_replica).  On ``kill_device`` a replica makes the
    #: fault a FLEET fault instead: that replica loses one device and
    #: advertises reduced capacity to the router.
    replica: Optional[int] = None
    #: kill_device: the mesh device index to drop; corrupt_slab: the
    #: shard whose slab block takes the bit-flip (None = anywhere)
    device: Optional[int] = None
    #: shrink_mesh: the target device count after the shrink
    devices: Optional[int] = None
    #: corrupt_slab: the named staged operand to flip a bit in (the
    #: elastic engines publish their addressable operand names via
    #: ``operand_names()`` — e.g. ``bucket0``, ``q``, ``r``, ``x``,
    #: ``local``)
    operand: Optional[str] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of "
                f"{KINDS}"
            )
        if self.kind in ("kill_rank", "stall_rank") and self.rank is None:
            raise ValueError(f"{self.kind} fault needs a 'rank'")
        if self.kind in ("stall_rank", "stall_tick",
                         "stall_replica") and self.duration <= 0:
            raise ValueError(f"{self.kind} fault needs a 'duration' > 0")
        if (self.kind in FLEET_KINDS
                or self.kind in ("kill_process", "partition_socket")) \
                and self.replica is None:
            raise ValueError(f"{self.kind} fault needs a 'replica'")
        if self.kind == "kill_agent" and not self.agent:
            raise ValueError("kill_agent fault needs an 'agent'")
        if self.kind in ("remove_agent_burst", "add_agent_burst") \
                and self.count is not None and self.count < 1:
            raise ValueError(f"{self.kind} fault needs a 'count' >= 1")
        if self.kind == "kill_device" and self.device is None:
            raise ValueError("kill_device fault needs a 'device'")
        if self.kind == "shrink_mesh" and (
                self.devices is None or self.devices < 1):
            raise ValueError("shrink_mesh fault needs 'devices' >= 1")
        if self.kind == "corrupt_slab" and not self.operand:
            raise ValueError("corrupt_slab fault needs an 'operand'")

    def to_dict(self) -> Dict:
        # 'attempt' must survive even as None (None = every attempt —
        # dropping it would deserialize back to the default of 0)
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None or k == "attempt"}


@dataclasses.dataclass
class FaultPlan:
    """An ordered, seedable set of faults.

    YAML schema (see docs/resilience.rst)::

        seed: 7
        faults:
          - kind: kill_rank
            rank: 1
            cycle: 8          # fire at first chunk boundary >= 8
            attempt: 0        # first launch only (default)
          - kind: stall_rank
            rank: 0
            cycle: 4
            duration: 60      # seconds SIGSTOPped
          - kind: kill_agent
            agent: a3
            cycle: 10         # thread-mode phase boundary
          - kind: corrupt_checkpoint   # or truncate_checkpoint
            attempt: 1        # mangle the latest snapshot before
                              # relaunch attempt 1 resumes from it
          - kind: raise_in_step        # serve: throw in a bucket step
            jid: job-000002   # poison job (persists until ERROR);
            cycle: 2          # first scheduler tick >= 2
          - kind: nan_lane             # serve: NaN a lane's state
            jid: job-000002
          - kind: torn_journal_write   # serve: cut an append mid-line
          - kind: stall_tick           # serve: wedge one tick
            duration: 0.5
          - kind: corrupt_cache_entry  # serve: flip bytes in the
            jid: job-000002            # solution-cache npz written for
                                       # this job (omit jid: the next
                                       # insert); rehydrate/adopt must
                                       # skip-and-count it, never serve
          - kind: edit_factor          # churn: hot-swap a constraint's
            cycle: 10                  # table (seeded perturbation);
            constraint: c12            # omit 'constraint' for a seeded
                                       # choice
          - kind: remove_agent_burst   # churn: remove `count` seeded-
            cycle: 20                  # chosen agents at one phase
            count: 3                   # boundary (replica repair x3)
          - kind: add_agent_burst      # churn: add fresh agents
            cycle: 30
            count: 2
          - kind: kill_replica         # fleet: hard-stop one replica's
            replica: 1                 # scheduler mid-trace (thread-
            cycle: 4                   # hosted kill -9; journal only)
          - kind: stall_replica        # fleet: wedge a replica; the
            replica: 0                 # router routes around it, no
            cycle: 2                   # re-seat (a stall != a death)
            duration: 0.5
          - kind: partition_replica    # fleet: unreachable for NEW
            replica: 1                 # placements for `duration`
            cycle: 3                   # seconds (0 = rest of run)
            duration: 1.0
          - kind: kill_process         # process fleet: SIGKILL the
            replica: 1                 # whole replica child process
            cycle: 4                   # (the real kill -9; heartbeat
                                       # staleness + waitpid detect it,
                                       # survivors re-seat its jobs)
          - kind: partition_socket     # process fleet: sever replica
            replica: 0                 # 0's journal socket and refuse
            cycle: 6                   # re-dials for `duration` s
            duration: 1.0              # (0 = rest of run); frames
                                       # buffer + replay on heal
          - kind: corrupt_artifact     # process fleet: flip one seeded
            cycle: 2                   # byte in an exported runner
                                       # artifact (CRC must catch it;
                                       # `replica`/`path` narrow the
                                       # target, omit for seeded pick)
          - kind: kill_device          # device: drop mesh device 7 at
            device: 7                  # the next chunk boundary >= 8;
            cycle: 8                   # with `replica: N` the fleet
                                       # replica N loses a device and
                                       # advertises reduced capacity
          - kind: shrink_mesh          # device: shrink the mesh to 4
            devices: 4                 # devices in one step
            cycle: 16
          - kind: corrupt_slab         # device: flip one seeded bit in
            operand: bucket0           # a named staged operand (SDC
            cycle: 12                  # probe); `device` restricts the
            device: 2                  # flip to that shard's block
    """

    faults: List[Fault] = dataclasses.field(default_factory=list)
    seed: int = 0

    # -- construction -------------------------------------------------------

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultPlan":
        if not isinstance(d, dict) or "faults" not in d:
            raise ValueError(
                "fault plan must be a mapping with a 'faults' list"
            )
        faults = []
        for i, f in enumerate(d["faults"] or []):
            if not isinstance(f, dict) or "kind" not in f:
                raise ValueError(
                    f"fault #{i} must be a mapping with a 'kind'"
                )
            known = {fl.name for fl in dataclasses.fields(Fault)}
            unknown = set(f) - known
            if unknown:
                raise ValueError(
                    f"fault #{i} has unknown fields {sorted(unknown)}"
                )
            faults.append(Fault(**f))
        return cls(faults=faults, seed=int(d.get("seed", 0)))

    @classmethod
    def from_yaml(cls, path: str) -> "FaultPlan":
        import yaml

        with open(path, encoding="utf-8") as f:
            plan = cls.from_dict(yaml.safe_load(f))
        # a plan from disk is the chaos contract of a whole run: a
        # misaddressed field (a stall_tick with a rank, a kill_replica
        # with an agent) would silently never fire — fail loudly here
        plan.validate()
        return plan

    def validate(self) -> List[str]:
        """Check every fault only addresses fields its kind consumes
        (:data:`KIND_FIELDS` — the catalog docs/resilience.rst's
        fault-kind table documents) and return the sorted kinds the
        plan uses.  ``__post_init__`` already enforces required
        fields; this catches the opposite bug — a field the kind will
        never read, i.e. a fault that cannot mean what its author
        wrote."""
        targeted = ("rank", "agent", "path", "jid", "count",
                    "constraint", "replica", "device", "devices",
                    "operand")
        for i, f in enumerate(self.faults):
            allowed = KIND_FIELDS[f.kind]
            extras = sorted(
                name for name in targeted
                if getattr(f, name) is not None and name not in allowed
            )
            if f.duration and "duration" not in allowed:
                extras.append("duration")
            if extras:
                raise ValueError(
                    f"fault #{i} ({f.kind}) addresses field(s) "
                    f"{extras} that {f.kind!r} never consumes; it "
                    f"accepts only {sorted(allowed)} (see the "
                    f"fault-kind catalog in docs/resilience.rst)"
                )
        return sorted({f.kind for f in self.faults})

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "faults": [f.to_dict() for f in self.faults]}
        )

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        return cls.from_dict(json.loads(s))

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        raw = os.environ.get(ENV_FAULT_PLAN)
        return cls.from_json(raw) if raw else None

    # -- queries ------------------------------------------------------------

    def for_rank(self, rank: int) -> List[Fault]:
        return [f for f in self.faults
                if f.kind in ("kill_rank", "stall_rank") and f.rank == rank]

    def agent_kills(self) -> List[Fault]:
        return [f for f in self.faults if f.kind == "kill_agent"]

    def checkpoint_faults(self, attempt: Optional[int] = None) -> List[Fault]:
        out = [f for f in self.faults
               if f.kind in ("corrupt_checkpoint", "truncate_checkpoint")]
        if attempt is not None:
            out = [f for f in out
                   if f.attempt is None or f.attempt == attempt]
        return out

    def serve_faults(self) -> List[Fault]:
        return [f for f in self.faults if f.kind in SERVE_KINDS]

    def fleet_faults(self) -> List[Fault]:
        """Replica-level faults (kill/stall/partition, plus
        replica-scoped ``kill_device``) consumed by the solve fleet's
        supervisor (serve/fleet.py) through the same
        :class:`ServeFaultInjector` consultation protocol."""
        return [f for f in self.faults
                if f.kind in FLEET_KINDS
                or (f.kind == "kill_device" and f.replica is not None)]

    def process_faults(self) -> List[Fault]:
        """Process-fleet faults (kill_process / partition_socket /
        corrupt_artifact) consumed by the process fleet's supervisor
        (serve/procfleet.ProcessFleet) — the OS-level escalation of
        :meth:`fleet_faults`."""
        return [f for f in self.faults if f.kind in PROCESS_KINDS]

    def device_faults(self) -> List[Fault]:
        """Device-tier faults (kill_device/shrink_mesh/corrupt_slab)
        consumed by the elastic sharded driver
        (parallel/elastic.ElasticRunner) at chunk boundaries, ordered
        by cycle.  A ``kill_device`` carrying a ``replica`` belongs to
        the fleet (see :meth:`fleet_faults`) and is excluded here."""
        out = [f for f in self.faults
               if f.kind in DEVICE_KINDS
               and not (f.kind == "kill_device"
                        and f.replica is not None)]
        return sorted(out, key=lambda f: f.cycle)

    def churn_faults(self) -> List[Fault]:
        """Agent-churn / live-mutation faults (kill_agent + the burst
        and edit kinds), ordered by cycle — the seeded churn stream the
        orchestrator replays at phase boundaries."""
        out = [f for f in self.faults
               if f.kind == "kill_agent" or f.kind in CHURN_KINDS]
        return sorted(out, key=lambda f: f.cycle)

    @property
    def has_rank_faults(self) -> bool:
        return any(f.kind in ("kill_rank", "stall_rank")
                   for f in self.faults)


# --------------------------------------------------------------------------
# serve-side injection (pydcop_tpu.serve.SolveService)
# --------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Raised by :class:`ServeFaultInjector` to simulate a component
    failure (``raise_in_step``) — handled by the same isolation
    machinery (bucket quarantine, supervisor backoff) as a real
    exception, which is the point."""


class ServeFaultInjector:
    """Consulted by the solve service's scheduler at tick boundaries.

    ``due(kind, tick, ...)`` returns the first pending fault of that
    kind whose ``cycle`` (tick threshold) has been reached and whose
    target matches.  One-shot vs persistent semantics follow the
    fault's ``jid``:

    * ``jid=None`` — a *transient* fault: consumed on first fire.  The
      service should absorb it (quarantine retry, supervisor restart)
      and every job should still complete correctly.
    * ``jid`` set — a *poison job*: the fault keeps firing whenever
      that job is in the blast radius, so the retry →
      sequential-fallback escalation must end the job in a terminal
      ``ERROR`` — never take down its bucket-mates, let alone the
      service.  :meth:`poisoned` lets the fallback path honor the
      persistence too.
    """

    def __init__(self, plan: FaultPlan,
                 faults: Optional[List[Fault]] = None):
        """``faults`` overrides the consumed subset: the solve service
        passes nothing (``plan.serve_faults()``); the solve fleet's
        supervisor passes ``plan.fleet_faults()`` so replica-level
        kill/stall/partition kinds flow through the SAME consultation
        protocol (``due`` at tick boundaries, one-shot unless
        jid-targeted)."""
        self.plan = plan
        self._pending: List[Fault] = list(
            plan.serve_faults() if faults is None else faults
        )
        self.fired: List[Fault] = []

    def due(self, kind: str, tick: int,
            jid: Optional[str] = None,
            jids: Optional[Sequence[str]] = None) -> Optional[Fault]:
        for f in list(self._pending):
            if f.kind != kind or f.cycle > tick:
                continue
            if f.jid is not None:
                if jids is not None:
                    if f.jid not in jids:
                        continue
                elif jid is not None:
                    if f.jid != jid:
                        continue
                else:
                    continue  # targeted fault, no target in scope
                # persistent: a poison job stays poisoned
            else:
                self._pending.remove(f)
            self.fired.append(f)
            return f
        return None

    def poisoned(self, jid: str) -> bool:
        """True while a persistent (jid-targeted) fault still targets
        ``jid`` — the sequential-fallback escalation checks this so an
        injected poison job cannot 'recover' by falling back."""
        return any(f.jid == jid for f in self._pending)


# --------------------------------------------------------------------------
# rank-side injection
# --------------------------------------------------------------------------

def _default_stall(duration: float) -> None:
    """Freeze THIS process (all threads, heartbeat writer included) for
    ``duration`` seconds: a helper process sends SIGCONT later, then we
    SIGSTOP ourselves.  From outside this is a genuine stall — exactly
    what a wedged collective or a livelocked rank looks like."""
    pid = os.getpid()
    subprocess.Popen(
        [sys.executable, "-c",
         "import time, os, signal, sys\n"
         f"time.sleep({float(duration)})\n"
         "try:\n"
         f"    os.kill({pid}, signal.SIGCONT)\n"
         "except ProcessLookupError:\n"
         "    pass\n"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    os.kill(pid, signal.SIGSTOP)


class RankFaultInjector:
    """Consulted by a mesh rank at every cycle-chunk boundary.

    ``at_cycle(c)`` fires every not-yet-fired fault addressed to this
    rank whose cycle is <= c and whose attempt matches — a kill
    ``os._exit``\\ s with :data:`KILL_EXIT_CODE`, a stall freezes the
    process.  The exit/stall hooks are injectable for unit tests.
    """

    def __init__(self, plan: FaultPlan, rank: int,
                 attempt: Optional[int] = None,
                 _exit=os._exit, _stall=_default_stall):
        if attempt is None:
            attempt = int(os.environ.get(ENV_FAULT_ATTEMPT, "0"))
        self.rank = rank
        self.attempt = attempt
        self._exit = _exit
        self._stall = _stall
        self._pending = [
            f for f in plan.for_rank(rank)
            if f.attempt is None or f.attempt == attempt
        ]

    @property
    def cycle_faults_pending(self) -> bool:
        return bool(self._pending)

    def next_cycle(self) -> Optional[int]:
        """The earliest pending fault cycle (chunking hint), or None."""
        return min((f.cycle for f in self._pending), default=None)

    def at_cycle(self, cycle: int) -> None:
        due = [f for f in self._pending if f.cycle <= cycle]
        self._pending = [f for f in self._pending if f.cycle > cycle]
        for f in due:
            if f.kind == "stall_rank":
                self._stall(f.duration)
            elif f.kind == "kill_rank":
                self._exit(KILL_EXIT_CODE)


# --------------------------------------------------------------------------
# liveness: heartbeat files + stall detection
# --------------------------------------------------------------------------

class HeartbeatWriter:
    """Daemon thread touching ``path`` every ``interval`` seconds.

    Started before the rank's heavy imports so the watchdog sees a live
    rank from the first second.  A SIGSTOP (injected or real) freezes
    this thread with the rest of the process, so staleness of the file
    is a faithful liveness signal — unlike a heartbeat written only
    from the main solve loop, it does NOT go stale during long compiles.
    """

    def __init__(self, path: str, interval: float = 0.5):
        self.path = path
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        with open(self.path, "a", encoding="utf-8"):
            os.utime(self.path, None)

    def start(self) -> "HeartbeatWriter":
        self.beat()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"heartbeat-{os.path.basename(self.path)}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.beat()
            except OSError:  # pragma: no cover - tmpdir vanished
                return

    def stop(self) -> None:
        self._stop.set()


def stalled_ranks(
    hb_paths: Dict[int, str],
    stall_timeout: float,
    now: Optional[float] = None,
) -> List[int]:
    """Ranks whose heartbeat file exists but has not been touched for
    more than ``stall_timeout`` seconds.  A missing file is NOT a stall
    (the rank may still be forking); rank death is detected separately
    through the exit code."""
    now = time.time() if now is None else now
    out = []
    for rank, path in sorted(hb_paths.items()):
        try:
            age = now - os.stat(path).st_mtime
        except OSError:
            continue
        if age > stall_timeout:
            out.append(rank)
    return out


# --------------------------------------------------------------------------
# checkpoint file faults
# --------------------------------------------------------------------------

def corrupt_checkpoint(path: str, seed: int = 0,
                       mode: str = "corrupt") -> None:
    """Deterministically damage a checkpoint file in place.

    ``mode='corrupt'`` flips 16 bytes in the data region (positions
    drawn from ``random.Random(seed)``); ``mode='truncate'`` cuts the
    file to a seed-chosen fraction (30-70%) of its length.  Same seed,
    same file size → same damage, so tests are reproducible.
    """
    import random

    rng = random.Random(seed)
    size = os.path.getsize(path)
    if mode == "truncate":
        keep = max(1, int(size * (0.3 + 0.4 * rng.random())))
        with open(path, "r+b") as f:
            f.truncate(keep)
        return
    if mode != "corrupt":
        raise ValueError(f"unknown corruption mode {mode!r}")
    with open(path, "r+b") as f:
        # skip the first 512 bytes: flipping the zip local-file header
        # is indistinguishable from truncation; aim at array data so
        # the CRC check (not the zip layer) is what must catch it
        lo = min(512, size // 2)
        for _ in range(16):
            pos = rng.randrange(lo, size)
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def apply_checkpoint_faults(plan: FaultPlan, directory: Optional[str],
                            attempt: int) -> List[str]:
    """Host-side: fire the plan's checkpoint faults due at ``attempt``
    against their explicit paths or the newest snapshot in
    ``directory``.  Returns the damaged paths (for logging/metrics)."""
    from pydcop_tpu.runtime.checkpoint import CheckpointManager

    damaged = []
    for f in plan.checkpoint_faults(attempt):
        path = f.path
        if path is None and directory:
            latest = CheckpointManager(directory).latest()
            path = latest[1] if latest else None
        if path and os.path.exists(path):
            mode = ("truncate" if f.kind == "truncate_checkpoint"
                    else "corrupt")
            corrupt_checkpoint(path, seed=plan.seed, mode=mode)
            damaged.append(path)
    return damaged
