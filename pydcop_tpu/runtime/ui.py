"""Live observation server.

Equivalent capability to the reference's pydcop/infrastructure/ui.py
(UiServer :43-120): the reference pushes event-bus topics to GUI clients
over websockets (websocket-server dependency).  That library is not in this
image, so the same capability is served with stdlib HTTP:

* ``GET /state``  — current status, cycle, cost, assignment (JSON);
* ``GET /events`` — Server-Sent Events stream of event-bus topics
  (consumable from any browser/EventSource, no extra deps).
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pydcop_tpu.runtime.events import event_bus


class UiServer:
    def __init__(self, port: int = 10001, address: str = "127.0.0.1"):
        self.port = port
        self.address = address
        self._state = {"status": "INITIAL"}
        self._lock = threading.Lock()
        self._subscribers: list[queue.Queue] = []
        self._server: Optional[ThreadingHTTPServer] = None
        event_bus.subscribe("*", self._on_event)

    # -- event plumbing -----------------------------------------------------

    def _on_event(self, topic: str, evt) -> None:
        payload = json.dumps({"topic": topic, "event": repr(evt)})
        with self._lock:
            for q in list(self._subscribers):
                try:
                    q.put_nowait(payload)
                except queue.Full:
                    pass

    def update_state(self, **kwargs) -> None:
        with self._lock:
            self._state.update(kwargs)

    # -- server -------------------------------------------------------------

    def start(self) -> None:
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/state":
                    with ui._lock:
                        body = json.dumps(ui._state).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/events":
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    q: queue.Queue = queue.Queue(maxsize=1000)
                    with ui._lock:
                        ui._subscribers.append(q)
                    try:
                        while True:
                            payload = q.get(timeout=30)
                            self.wfile.write(
                                f"data: {payload}\n\n".encode()
                            )
                            self.wfile.flush()
                    except (queue.Empty, OSError):
                        pass
                    finally:
                        with ui._lock:
                            if q in ui._subscribers:
                                ui._subscribers.remove(q)
                else:
                    self.send_response(404)
                    self.end_headers()

        self._server = ThreadingHTTPServer((self.address, self.port),
                                           Handler)
        thread = threading.Thread(target=self._server.serve_forever,
                                  daemon=True)
        thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
