"""Live observation server.

Equivalent capability to the reference's pydcop/infrastructure/ui.py
(UiServer :43-120), speaking the SAME websocket protocol to GUI clients
— via the stdlib RFC 6455 implementation in runtime/ws.py (the
reference's websocket-server dependency is not needed):

* client commands (JSON ``{"cmd": ...}``): ``test``, ``agent``,
  ``computations`` — answered with ``{"cmd": ..., ...}`` payloads in
  the reference's shapes (ui.py:118-195);
* pushed events (JSON ``{"evt": ...}``): ``cycle``, ``value``,
  ``add_comp``, ``rem_comp`` from the event bus, and an
  application-level ``{"cmd": "close"}`` on shutdown (ui.py:89-91).

An HTTP fallback runs alongside on ``port``:

* ``GET /state``  — current status, cycle, cost, assignment (JSON);
* ``GET /events`` — Server-Sent Events stream of event-bus topics
  (consumable from any browser/EventSource, no extra deps).

The websocket endpoint listens on ``ws_port`` (default ``port + 1``,
matching the reference's one-ws-port-per-agent layout).
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from pydcop_tpu.runtime.events import event_bus


class UiServer:
    def __init__(self, port: int = 10001, address: str = "127.0.0.1",
                 ws_port: Optional[int] = None, orchestrator=None):
        self.port = port
        self.ws_port = ws_port if ws_port is not None else port + 1
        self.address = address
        self.orchestrator = orchestrator
        self._state = {"status": "INITIAL"}
        self._lock = threading.Lock()
        self._subscribers: list[queue.Queue] = []
        self._server: Optional[ThreadingHTTPServer] = None
        self._ws = None
        event_bus.subscribe("*", self._on_event)
        event_bus.subscribe("computations.cycle.*", self._cb_cycle)
        event_bus.subscribe("computations.value.*", self._cb_value)
        event_bus.subscribe("agents.add_computation.*", self._cb_add_comp)
        event_bus.subscribe("agents.rem_computation.*", self._cb_rem_comp)
        event_bus.subscribe("faults.*", self._cb_fault)
        event_bus.subscribe("integrity.*", self._cb_integrity)
        event_bus.subscribe("elastic.*", self._cb_elastic)
        event_bus.subscribe("repair.*", self._cb_repair)
        event_bus.subscribe("batch.*", self._cb_batch)
        event_bus.subscribe("harness.*", self._cb_harness)
        event_bus.subscribe("shard.*", self._cb_shard)
        event_bus.subscribe("dpop.*", self._cb_dpop)
        event_bus.subscribe("search.*", self._cb_search)
        event_bus.subscribe("serve.*", self._cb_serve)
        event_bus.subscribe("memo.*", self._cb_memo)
        event_bus.subscribe("fleet.*", self._cb_fleet)
        event_bus.subscribe("portfolio.*", self._cb_portfolio)
        event_bus.subscribe("slo.*", self._cb_slo)

    # -- event plumbing -----------------------------------------------------

    def _on_event(self, topic: str, evt) -> None:
        payload = json.dumps({"topic": topic, "event": repr(evt)})
        with self._lock:
            for q in list(self._subscribers):
                try:
                    q.put_nowait(payload)
                except queue.Full:
                    pass

    def update_state(self, **kwargs) -> None:
        with self._lock:
            self._state.update(kwargs)

    # -- websocket protocol (reference ui.py command/event shapes) ----------

    def _ws_message(self, client, text: str) -> None:
        try:
            msg = json.loads(text)
        except ValueError:
            return
        cmd = msg.get("cmd") if isinstance(msg, dict) else None
        if cmd == "test":
            self._ws.send_all(json.dumps({"cmd": "test", "data": "foo"}))
        elif cmd == "agent":
            self._ws.send(client, json.dumps(
                {"cmd": "agent", "agent": self._agent_data()}))
        elif cmd == "computations":
            self._ws.send(client, json.dumps(
                {"cmd": "computations",
                 "computations": self._computations()}))

    def _agent_data(self) -> dict:
        """The reference's agent payload (ui.py:135-147), with the
        virtual orchestrator standing in for the per-agent view."""
        with self._lock:
            state = dict(self._state)
        return {
            "name": "orchestrator",
            "extra": {},
            "computations": self._computations(),
            "replicas": self._replicas(),
            "address": f"{self.address}:{self.port}",
            "is_orchestrator": True,
            "status": state.get("status"),
        }

    def _computations(self) -> list:
        """The reference's computation payloads (ui.py:155-194)."""
        orch = self.orchestrator
        if orch is None:
            return []
        with self._lock:
            assignment = dict(self._state.get("assignment") or {})
        # mid-run values: the last completed phase's assignment (the
        # end metrics only land in _state after the run)
        last = getattr(orch, "_last_result", None)
        if not assignment and last is not None:
            assignment = dict(last.assignment or {})
        algo = {"name": orch.algo_def.algo,
                "params": dict(orch.algo_def.params)}
        out = []
        for node in orch.cg.nodes:
            # variable-vs-factor from the node class, not from the
            # assignment (which is empty before the first phase ends)
            is_var = hasattr(node, "variable")
            out.append({
                "id": node.name,
                "name": node.name,
                "type": "variable" if is_var else "factor",
                "value": assignment.get(node.name),
                "neighbors": list(node.neighbors),
                "algo": algo,
                "msg_count": 0,
                "msg_size": 0,
                "cycles": self._state.get("cycle", 0),
                "footprint": orch.algo_module.computation_memory(node),
            })
        return out

    def _replicas(self) -> list:
        orch = self.orchestrator
        if orch is None or orch.replicas is None:
            return []
        return sorted(orch.replicas.mapping())

    def _cb_cycle(self, topic: str, evt) -> None:
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "cycle", "computation": topic.rsplit(".", 1)[-1],
                 "cycles": evt}))

    def _cb_value(self, topic: str, evt) -> None:
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "value", "computation": topic.rsplit(".", 1)[-1],
                 "value": evt}))

    def _cb_add_comp(self, topic: str, evt) -> None:
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "add_comp", "computation": evt}))

    def _cb_rem_comp(self, topic: str, evt) -> None:
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "rem_comp", "computation": evt}))

    def _cb_fault(self, topic: str, evt) -> None:
        """Fault + recovery lifecycle (faults.injected.*, .detected.*,
        .recovered.*) pushed to GUI clients; the SSE /events stream gets
        them through the wildcard subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "fault",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_integrity(self, topic: str, evt) -> None:
        """Data-integrity lifecycle (integrity.sentinel.trip,
        integrity.scrub.run|mismatch, integrity.injected,
        integrity.restore) pushed to GUI clients in the same envelope
        shape as the fault family; the SSE /events stream gets them
        through the wildcard subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "integrity",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_elastic(self, topic: str, evt) -> None:
        """Elastic-mesh lifecycle (elastic.device.lost,
        elastic.shrink, elastic.repack, elastic.resumed) pushed to GUI
        clients; the SSE /events stream gets them through the wildcard
        subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "elastic",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_repair(self, topic: str, evt) -> None:
        """Warm-repair lifecycle (repair.mutation.applied,
        repair.headroom.claimed|released, repair.repack,
        repair.recovered) pushed to GUI clients in the same envelope
        shape as the batch/harness families; the SSE /events stream
        gets them through the wildcard subscription like every
        topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "repair",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_batch(self, topic: str, evt) -> None:
        """Batched-solve lifecycle (batch.bucket.formed,
        batch.compile.hit|miss, batch.instance.converged,
        batch.run.done) pushed to GUI clients; the SSE /events stream
        gets them through the wildcard subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "batch",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_harness(self, topic: str, evt) -> None:
        """Solve-harness lifecycle (harness.run.done with the
        HarnessCounters host↔device traffic scorecard) pushed to GUI
        clients; the SSE /events stream gets them through the wildcard
        subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "harness",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_serve(self, topic: str, evt) -> None:
        """Solve-service lifecycle (serve.job.submitted|admitted|
        progress|done, serve.bucket.opened|merged|closed,
        serve.prewarm.scheduled, serve.resume.done) plus the
        fault-isolation surface (serve.fault.injected|bucket_failed|
        bisect|nan_lane|retry|quarantined|scheduler_restart|
        scheduler_dead, serve.job.shed|rejected, serve.stream.lossy,
        serve.journal.torn|compacted) pushed to GUI clients — the
        streaming front door's anytime assignments, continuous-
        batching events and chaos/overload alerts ride the same
        channel as ``batch.*``; the SSE /events stream gets them
        through the wildcard subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "serve",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_memo(self, topic: str, evt) -> None:
        """Solution-cache lifecycle (memo.hit.exact|variant, memo.miss,
        memo.insert, memo.invalidate, memo.fallback.cold,
        memo.corrupt.skipped — the cross-request cache's hit taxonomy
        and invalidation audit, docs/serving.rst "Solution cache and
        warm-start serving") pushed to GUI clients in the same
        envelope shape as the serve.* forwarding; the SSE /events
        stream gets them through the wildcard subscription like every
        topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "memo",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_fleet(self, topic: str, evt) -> None:
        """Solve-fleet lifecycle (fleet.replica.up|down|stalled|
        healed|partitioned, fleet.router.placed, fleet.job.reseated|
        rejected, fleet.recovery.done — the replicated front door's
        routing decisions, failover re-seats and recovery-time
        records) pushed to GUI clients in the same envelope shape as
        the serve.* forwarding; the SSE /events stream gets them
        through the wildcard subscription like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "fleet",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_shard(self, topic: str, evt) -> None:
        """Sharded-engine collective/partition lifecycle
        (shard.comm.selected with the ShardCommCounters partition-
        quality scorecard) pushed to GUI clients; the SSE /events
        stream gets them through the wildcard subscription like every
        topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "shard",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_dpop(self, topic: str, evt) -> None:
        """Exact-inference engine lifecycle (dpop.shard.plan,
        dpop.shard.sweep.done, dpop.minibucket.bounds — the
        separator-sharded sweep's tiling/wire scorecards and the
        mini-bucket fallback's bound sandwich) pushed to GUI clients in
        the same envelope shape as the shard.* forwarding; the SSE
        /events stream gets them through the wildcard subscription like
        every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "dpop",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_search(self, topic: str, evt) -> None:
        """Anytime exact-search lifecycle (search.bounds — the
        tightening lower/upper sandwich per device chunk —
        search.spill.drain and search.done) pushed to GUI clients in
        the same envelope shape as the dpop.* forwarding; the SSE
        /events stream gets them through the wildcard subscription
        like every topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "search",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_portfolio(self, topic: str, evt) -> None:
        """Portfolio auto-selection lifecycle
        (portfolio.dataset.progress|done, portfolio.model.loaded,
        portfolio.config.selected, portfolio.solve.done — the learned
        cost model's dataset sweeps, selections and predicted-vs-
        actual audits) pushed to GUI clients in the same envelope
        shape as the shard/dpop forwarding; the SSE /events stream
        gets them through the wildcard subscription like every
        topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "portfolio",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    def _cb_slo(self, topic: str, evt) -> None:
        """SLO guardrail-ladder lifecycle (slo.tier.breach,
        slo.ladder.escalated|released, slo.shed.bronze,
        slo.clamp.silver, slo.reroute.gold, slo.scorecard — the city
        twin's deterministic degradation ladder and its per-tier
        attainment summary) pushed to GUI clients in the same envelope
        shape as the serve/fleet forwarding; the SSE /events stream
        gets them through the wildcard subscription like every
        topic."""
        if self._ws is not None:
            self._ws.send_all(json.dumps(
                {"evt": "slo",
                 "kind": topic.split(".", 1)[-1],
                 "data": evt if isinstance(evt, (dict, list, str, int,
                                                 float, bool, type(None)))
                 else repr(evt)}))

    # -- server -------------------------------------------------------------

    def start(self) -> None:
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/state":
                    with ui._lock:
                        body = json.dumps(ui._state).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/events":
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    q: queue.Queue = queue.Queue(maxsize=1000)
                    with ui._lock:
                        ui._subscribers.append(q)
                    try:
                        while True:
                            payload = q.get(timeout=30)
                            self.wfile.write(
                                f"data: {payload}\n\n".encode()
                            )
                            self.wfile.flush()
                    except (queue.Empty, OSError):
                        pass
                    finally:
                        with ui._lock:
                            if q in ui._subscribers:
                                ui._subscribers.remove(q)
                else:
                    self.send_response(404)
                    self.end_headers()

        self._server = ThreadingHTTPServer((self.address, self.port),
                                           Handler)
        thread = threading.Thread(target=self._server.serve_forever,
                                  daemon=True)
        thread.start()

        from pydcop_tpu.runtime.ws import WebSocketServer

        self._ws = WebSocketServer(
            self.ws_port, host=self.address, on_message=self._ws_message
        )
        self._ws.start()

    def stop(self) -> None:
        for cb in (self._on_event, self._cb_cycle, self._cb_value,
                   self._cb_add_comp, self._cb_rem_comp, self._cb_fault,
                   self._cb_batch, self._cb_harness, self._cb_shard,
                   self._cb_dpop, self._cb_serve, self._cb_repair,
                   self._cb_memo, self._cb_fleet, self._cb_portfolio,
                   self._cb_slo):
            event_bus.unsubscribe(cb)
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        if self._ws is not None:
            # application-level close first (reference ui.py:89-91: the
            # ws close alone does not reach the GUI client)
            self._ws.send_all(json.dumps({"cmd": "close"}))
            self._ws.stop()
            self._ws = None
