"""One-call solve API.

Equivalent capability to the reference's pydcop/infrastructure/run.py
(solve :52, run_local_thread_dcop :145, run_local_process_dcop :225) —
without the thread/process agent plumbing: build graph → (optionally)
distribute → compile to tensors → run jitted rounds.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.graph import load_graph_module


def _build_algo_def(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    algo_params: Optional[Dict[str, Any]],
) -> AlgorithmDef:
    if isinstance(algo, AlgorithmDef):
        return algo
    return AlgorithmDef.build_with_default_params(
        algo, algo_params or {}, mode=dcop.objective
    )


def solve_result(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Optional[str] = None,
    graph: Optional[str] = None,
    timeout: Optional[float] = None,
    cycles: Optional[int] = None,
    algo_params: Optional[Dict[str, Any]] = None,
    seed: int = 0,
    collect_cycles: bool = False,
) -> SolveResult:
    """Solve a DCOP and return the full result + metrics.

    The reference twin is infrastructure/run.py:solve (used by all api
    tests); ``distribution`` is accepted for parity and validated, though a
    single-host tensor solve does not need a placement to run.
    """
    algo_def = _build_algo_def(dcop, algo, algo_params)
    algo_module = load_algorithm_module(algo_def.algo)

    graph_type = graph or algo_module.GRAPH_TYPE
    graph_module = load_graph_module(graph_type)
    cg = graph_module.build_computation_graph(dcop)

    if distribution is not None and dcop.agents:
        from pydcop_tpu.distribution import load_distribution_module

        dist_module = load_distribution_module(distribution)
        dist_hints = getattr(dcop, "dist_hints", None)
        dist_module.distribute(
            cg,
            dcop.agents.values(),
            hints=dist_hints,
            computation_memory=algo_module.computation_memory,
            communication_load=algo_module.communication_load,
        )

    solver = algo_module.build_solver(dcop, cg, algo_def, seed=seed)
    stop_cycle = (
        cycles
        if cycles is not None
        else (algo_def.params.get("stop_cycle") or None)
    )
    return solver.run(
        cycles=stop_cycle, timeout=timeout, collect_cycles=collect_cycles
    )


def solve(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Optional[str] = None,
    graph: Optional[str] = None,
    timeout: Optional[float] = None,
    cycles: Optional[int] = None,
    algo_params: Optional[Dict[str, Any]] = None,
    seed: int = 0,
) -> Dict[str, Any]:
    """Solve a DCOP and return the assignment (reference-parity signature:
    infrastructure/run.py:52 returns ``metrics['assignment']``)."""
    return solve_result(
        dcop, algo, distribution, graph, timeout, cycles, algo_params, seed
    ).assignment


def run_local_thread_dcop(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Union[str, Any] = "adhoc",
    graph: Optional[str] = None,
    collector=None,
    collect_moment: str = "value_change",
    period: Optional[float] = None,
    replication: Optional[str] = None,
    seed: int = 0,
):
    """Reference-parity constructor (infrastructure/run.py:145): returns a
    deployed orchestrator.  In the tensor runtime "thread mode" and
    "process mode" are the same engine — one process IS the whole agent
    population — so both names build a VirtualOrchestrator."""
    from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

    orch = VirtualOrchestrator(
        dcop, algo, distribution=distribution, graph=graph,
        collect_on=collect_moment, period=period, collector=collector,
        seed=seed,
    )
    orch.deploy_computations()
    return orch


#: reference-parity alias (infrastructure/run.py:225) — see
#: run_local_thread_dcop
run_local_process_dcop = run_local_thread_dcop
