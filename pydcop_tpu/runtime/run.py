"""One-call solve API.

Equivalent capability to the reference's pydcop/infrastructure/run.py
(solve :52, run_local_thread_dcop :145, run_local_process_dcop :225) —
without the thread/process agent plumbing: build graph → (optionally)
distribute → compile to tensors → run jitted rounds.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.graph import load_graph_module


def _build_algo_def(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    algo_params: Optional[Dict[str, Any]],
) -> AlgorithmDef:
    if isinstance(algo, AlgorithmDef):
        return algo
    return AlgorithmDef.build_with_default_params(
        algo, algo_params or {}, mode=dcop.objective
    )


def solve_result(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Optional[Union[str, Any]] = None,
    graph: Optional[str] = None,
    timeout: Optional[float] = None,
    cycles: Optional[int] = None,
    algo_params: Optional[Dict[str, Any]] = None,
    seed: int = 0,
    collect_cycles: bool = False,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
    resume: bool = False,
    pipeline: bool = False,
    chunk: Optional[int] = None,
    shard_overlap: Optional[str] = None,
    shard_boundary_threshold: float = 0.5,
    headroom: Optional[float] = None,
    fault_plan=None,
    elastic: Optional[Dict[str, Any]] = None,
) -> SolveResult:
    """Solve a DCOP and return the full result + metrics.

    ``fault_plan`` (a runtime/faults.FaultPlan) with device-tier kinds
    (``kill_device``/``shrink_mesh``/``corrupt_slab``) routes the
    solve through the ELASTIC sharded driver (parallel/elastic,
    docs/resilience.rst "Device loss and data integrity"): the solve
    runs chunked over the device mesh with chunk-boundary snapshots,
    in-jit integrity sentinels and the recovery ladder armed —
    ``metrics()['integrity']`` carries the scorecard.  ``elastic`` (a
    dict: chunk / scrub_every / min_devices / sentinel / use_packed /
    snapshot_dir) tunes the driver, and alone (without a fault plan)
    also selects it — how clean runs get sentinel + scrub coverage.

    ``shard_overlap`` selects the sharded engines' collective path on
    the placement-driven (multi-device) path: ``off`` keeps the dense
    whole-space psum, ``exact`` compacts the collective to the
    partition's boundary columns (bit-identical), ``stale``
    double-buffers the boundary exchange (staleness-1 halo); the
    default auto-policy compacts when the partition's cut fraction is
    under ``shard_boundary_threshold`` (docs/performance.rst,
    "Boundary-compacted sharding").  The chosen path is recorded in
    ``metrics()['shard']``.

    ``chunk`` overrides the harness's chunk-size policy
    (algorithms/base.default_chunk) for round-based solvers — the
    portfolio grid sweeps it as a first-class config knob (the
    per-chunk PRNG stream depends on it); solvers without a chunk
    loop (dpop, syncbb) ignore it.

    ``pipeline=True`` enables the harness's pipelined chunk dispatch
    for converging (open-ended) runs: the next chunk launches before
    the previous chunk's device-side convergence scalar is read, so
    host bookkeeping overlaps device compute at the cost of up to one
    chunk of extra cycles past the stop point (see
    docs/performance.rst, "Pipelined convergence").

    The reference twin is infrastructure/run.py:solve (used by all api
    tests).  ``distribution`` as a strategy NAME is computed and validated
    (a single-host tensor solve does not need a placement to run); as a
    ``Distribution`` OBJECT (e.g. loaded from a distribution YAML) it
    actually drives execution — factors are sharded onto the device mesh
    by their host agents (reference parity: pydcop/commands/solve.py
    :483-507 runs under the given placement).

    ``headroom`` (a float fraction, e.g. 0.25) builds the WARM-repair
    engine at a padded capacity (algorithms/warm + ops/headroom,
    docs/resilience.rst "Warm repair and agent churn"): live mutations
    become fixed-shape buffer writes with zero retraces.  Supported
    for the warm algo set (maxsum/maxsum_dynamic/mgm/dsa/adsa), single
    device path only.

    ``checkpoint_dir`` + ``checkpoint_every`` persist rotating state
    snapshots every *k* cycles (runtime/checkpoint.CheckpointManager);
    ``resume=True`` warm-starts from the newest valid snapshot in that
    directory (corrupt snapshots are skipped with a warning).  Not
    supported on the placement-driven path.
    """
    from pydcop_tpu.distribution.objects import Distribution

    algo_def = _build_algo_def(dcop, algo, algo_params)
    algo_module = load_algorithm_module(algo_def.algo)

    device_faults = (
        fault_plan.device_faults() if fault_plan is not None else []
    )
    if device_faults or elastic is not None:
        return _solve_elastic(
            dcop, algo_def, cycles, seed, fault_plan,
            dict(elastic or {}), shard_overlap,
        )

    if isinstance(distribution, Distribution):
        if checkpoint_dir or resume:
            raise ValueError(
                "checkpointing is not supported on the placement-"
                "driven solve path; rerun without an explicit "
                "distribution object"
            )
        # placement-driven path compiles straight from the dcop; don't
        # build the computation graph it would never read
        return _solve_under_placement(
            dcop, algo_def, distribution, cycles, timeout,
            collect_cycles, shard_overlap=shard_overlap,
            shard_boundary_threshold=shard_boundary_threshold,
        )

    graph_type = graph or algo_module.GRAPH_TYPE
    graph_module = load_graph_module(graph_type)
    cg = graph_module.build_computation_graph(dcop)

    if distribution is not None and dcop.agents:
        from pydcop_tpu.distribution import load_distribution_module

        dist_module = load_distribution_module(distribution)
        dist_hints = getattr(dcop, "dist_hints", None)
        dist_module.distribute(
            cg,
            dcop.agents.values(),
            hints=dist_hints,
            computation_memory=algo_module.computation_memory,
            communication_load=algo_module.communication_load,
        )

    if headroom is not None:
        from pydcop_tpu.algorithms.warm import build_warm_solver
        from pydcop_tpu.runtime.stats import RepairCounters

        solver = build_warm_solver(
            dcop, algo=algo_def.algo, algo_def=algo_def, seed=seed,
            headroom=headroom,
        )
        # standalone solves get the scorecard too: metrics()["repair"]
        # pins that the warm engine (not the cold one) actually ran
        solver.repair_counters = RepairCounters()
    else:
        solver = algo_module.build_solver(dcop, cg, algo_def, seed=seed)
    stop_cycle = (
        cycles
        if cycles is not None
        else (algo_def.params.get("stop_cycle") or None)
    )
    if checkpoint_dir:
        return _run_with_checkpoints(
            solver, checkpoint_dir, checkpoint_every or 10, stop_cycle,
            timeout, resume, collect_cycles,
        )
    return solver.run(
        cycles=stop_cycle, timeout=timeout, collect_cycles=collect_cycles,
        pipeline=pipeline,
        **({"chunk": chunk} if chunk is not None else {}),
    )


def _run_with_checkpoints(
    solver,
    checkpoint_dir: str,
    checkpoint_every: int,
    cycles: Optional[int],
    timeout: Optional[float],
    resume: bool,
    collect_cycles: bool,
) -> SolveResult:
    """Chunked solver run with periodic rotating snapshots.

    Every ``checkpoint_every`` cycles the solver state is snapshotted
    (atomic + checksummed); with ``resume`` the newest valid snapshot
    warm-starts the run and only the remaining cycles execute.  With no
    explicit cycle budget the run executes the solver's default budget
    with a final snapshot at the end.
    """
    from time import perf_counter

    from pydcop_tpu.runtime.checkpoint import CheckpointManager

    mgr = CheckpointManager(checkpoint_dir)
    done = 0
    warm = False
    if resume:
        meta = mgr.load_latest_into(solver)
        if meta is not None:
            done = int(meta.get("cycle", 0) or 0)
            warm = True
    if cycles is None:
        res = solver.run(timeout=timeout, collect_cycles=collect_cycles,
                         resume=warm)
        mgr.save_solver(solver, done + res.cycle)
        return res
    t0 = perf_counter()
    every = max(1, checkpoint_every)
    res = None
    history = []
    while done < cycles:
        n = min(every, cycles - done)
        left = None if timeout is None else timeout - (perf_counter() - t0)
        if left is not None and left <= 0:
            break
        res = solver.run(cycles=n, timeout=left,
                         collect_cycles=collect_cycles, resume=warm)
        warm = True
        done += res.cycle
        if res.history:
            history.extend(res.history)
        mgr.save_solver(solver, done)
        if res.status == "TIMEOUT":
            break
        if res.cycle < n:
            # the solver finished ahead of its cycle budget (e.g. the
            # frontier search proved optimality): burning the rest of
            # the budget in no-op chunks would just churn snapshots
            break
    if res is None:  # resumed at/after the requested budget
        res = solver.run(cycles=1, collect_cycles=collect_cycles,
                         resume=warm)
        done += res.cycle
        mgr.save_solver(solver, done)
    res.cycle = done
    res.time = perf_counter() - t0
    if history:
        res.history = history
    return res


#: algorithms the elastic device-fault tier can drive (the sharded
#: engine families; dpop rides ElasticDpop's one-shot sweep)
ELASTIC_ALGOS = ("maxsum", "amaxsum", "mgm", "dsa", "adsa", "dba",
                 "gdba", "dpop")


def _solve_elastic(
    dcop: DCOP,
    algo_def: AlgorithmDef,
    cycles: Optional[int],
    seed: int,
    fault_plan,
    opts: Dict[str, Any],
    shard_overlap: Optional[str],
) -> SolveResult:
    """Run a solve through the elastic sharded driver
    (parallel/elastic): chunked over the device mesh, chunk-boundary
    snapshots, integrity sentinels + shadow scrub, and the device
    fault plan consumed at chunk boundaries."""
    from time import perf_counter

    import numpy as np

    from pydcop_tpu.algorithms import DEFAULT_INFINITY
    from pydcop_tpu.parallel.elastic import ElasticDpop, ElasticRunner
    from pydcop_tpu.runtime.stats import resolved_config

    algo = algo_def.algo
    if algo not in ELASTIC_ALGOS:
        raise ValueError(
            f"a device fault plan needs one of the elastic engine "
            f"families {ELASTIC_ALGOS}, not {algo!r}"
        )
    t0 = perf_counter()
    if algo == "dpop":
        from pydcop_tpu.graph import pseudotree
        from pydcop_tpu.ops.dpop_sweep import compile_sweep

        tree = pseudotree.build_computation_graph(dcop)
        plan = compile_sweep(tree, dcop, dcop.objective)
        if plan is None:
            raise ValueError(
                "this problem does not compile to a whole-table DPOP "
                "sweep; the elastic tier cannot drive it"
            )
        runner = ElasticDpop(
            plan, fault_plan=fault_plan,
            scrub=bool(opts.get("scrub_every", 1)),
            min_devices=int(opts.get("min_devices", 1)),
        )
        res = runner.solve()
        assignment = {}
        for gidx, name in enumerate(plan.gid_to_name):
            v = dcop.variables[name]
            assignment[name] = v.domain[int(res.values[gidx])]
        for name, v in dcop.variables.items():
            if name not in assignment:
                costs = v.cost_vector()
                idx = int(np.argmin(costs) if dcop.objective == "min"
                          else np.argmax(costs))
                assignment[name] = v.domain[idx]
        n_cycles = 1
        tensors = None
    else:
        if algo in ("maxsum", "amaxsum"):
            from pydcop_tpu.ops.compile import compile_factor_graph

            tensors = compile_factor_graph(dcop)
            engine = "maxsum"
            activation = None
            if algo == "amaxsum":
                from pydcop_tpu.algorithms.amaxsum import (
                    DEFAULT_ACTIVATION,
                )

                activation = float(algo_def.params.get(
                    "activation", DEFAULT_ACTIVATION
                ))
            extra = {
                "damping": (
                    0.5 if algo_def.params.get("damping") is None
                    else float(algo_def.params["damping"])
                ),
                "activation": activation,
            }
        else:
            from pydcop_tpu.ops.compile import compile_constraint_graph

            tensors = compile_constraint_graph(dcop)
            engine = algo
            extra = {"algo_params": dict(algo_def.params)}
        runner = ElasticRunner(
            tensors, engine=engine, fault_plan=fault_plan,
            chunk=int(opts.get("chunk", 8)),
            scrub_every=int(opts.get("scrub_every", 0)),
            min_devices=int(opts.get("min_devices", 2)),
            snapshot_dir=opts.get("snapshot_dir"),
            sentinel=bool(opts.get("sentinel", True)),
            use_packed=bool(opts.get("use_packed", False)),
            overlap=shard_overlap or "off",
            **extra,
        )
        n_cycles = cycles or 30
        res = runner.solve(n_cycles, seed=seed)
        assignment = tensors.assignment_from_indices(
            np.asarray(res.values)
        )
    violation, cost = dcop.solution_cost(assignment, DEFAULT_INFINITY)
    config = resolved_config(algo, "elastic_mesh",
                             chunk=int(opts.get("chunk", 8)))
    shard = None
    eng = getattr(runner, "engine", None)
    if eng is not None and hasattr(eng, "comm_stats"):
        shard = eng.comm_stats()
    return SolveResult(
        status="FINISHED",
        assignment=assignment,
        cost=cost,
        violation=violation,
        cycle=res.cycles if algo != "dpop" else n_cycles,
        msg_count=0,
        msg_size=0.0,
        time=perf_counter() - t0,
        shard=shard,
        config=config,
        integrity=res.counters.as_dict(),
    )


def _solve_under_placement(
    dcop: DCOP,
    algo_def: AlgorithmDef,
    distribution,
    cycles: Optional[int],
    timeout: Optional[float],
    collect_cycles: bool = False,
    shard_overlap: Optional[str] = None,
    shard_boundary_threshold: float = 0.5,
) -> SolveResult:
    """Run a solve whose device sharding is driven by an explicit
    placement (Distribution object).  Supported for the factor-graph BP
    family; the complete host-driven algorithms have no device placement
    to drive, so asking for one fails loudly instead of being ignored."""
    from time import perf_counter

    import jax
    import numpy as np

    from pydcop_tpu.ops.compile import compile_factor_graph
    from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh
    from pydcop_tpu.parallel.partition import assigns_from_distribution

    if algo_def.algo not in ("maxsum", "amaxsum"):
        raise ValueError(
            f"an explicit distribution can only drive device sharding "
            f"for the factor-graph BP family (maxsum/amaxsum), not "
            f"{algo_def.algo!r}; rerun without -d or with a strategy name"
        )
    t0 = perf_counter()
    tensors = compile_factor_graph(dcop)
    n_devices = len(jax.devices())
    mesh = build_mesh(n_devices)
    assigns = assigns_from_distribution(distribution, tensors, n_devices)
    if n_devices == 1:
        import logging

        logging.getLogger("pydcop_tpu.run").warning(
            "placement-driven solve on a single device: all %d agents "
            "fold onto one shard", len(distribution.agents),
        )
    damping = algo_def.params.get("damping")
    damping = 0.5 if damping is None else float(damping)  # 0 is valid
    # amaxsum rides the same sharded engine with its per-edge activation
    # mask (ShardedMaxSum activation — the AMaxSumSolver emulation)
    activation = None
    if algo_def.algo == "amaxsum":
        from pydcop_tpu.algorithms.amaxsum import DEFAULT_ACTIVATION

        activation = float(
            algo_def.params.get("activation", DEFAULT_ACTIVATION)
        )
    precision = algo_def.params.get("precision")
    sharded = ShardedMaxSum(tensors, mesh, damping=damping,
                            assigns=assigns, activation=activation,
                            overlap=shard_overlap,
                            boundary_threshold=shard_boundary_threshold,
                            precision=precision)
    n_cycles = cycles or 30
    status = "FINISHED"
    history = []
    if timeout is None and not collect_cycles:
        values, _q, _r = sharded.run(cycles=n_cycles)
    else:
        # chunked so the timeout is honored (and per-cycle metrics are
        # collected) between device dispatches
        from pydcop_tpu.ops.compile import total_cost

        chunk = 1 if collect_cycles else max(1, min(10, n_cycles))
        done = 0
        q = r = None
        values = None
        while done < n_cycles:
            n = min(chunk, n_cycles - done)
            values, q, r = sharded.run(cycles=n, q=q, r=r)
            done += n
            if collect_cycles:
                import jax.numpy as jnp

                history.append({
                    "cycle": done,
                    "cost": float(total_cost(
                        tensors, jnp.asarray(values)
                    )) * tensors.sign,
                    "time": perf_counter() - t0,
                })
            if timeout is not None and perf_counter() - t0 > timeout:
                status = "TIMEOUT"
                break
        n_cycles = done
    from pydcop_tpu.algorithms import DEFAULT_INFINITY

    assignment = tensors.assignment_from_indices(np.asarray(values))
    violation, cost = dcop.solution_cost(assignment, DEFAULT_INFINITY)
    edges = int(tensors.edge_var.shape[0])
    from pydcop_tpu.runtime.stats import resolved_config

    config = resolved_config(
        algo_def.algo, "sharded_mesh",
        overlap=shard_overlap or "default",
        boundary_threshold=shard_boundary_threshold,
        precision=sharded.precision,
    )
    return SolveResult(
        status=status,
        assignment=assignment,
        cost=cost,
        violation=violation,
        cycle=n_cycles,
        msg_count=2 * edges * n_cycles,
        msg_size=float(
            2 * edges * n_cycles * tensors.max_domain_size
        ),
        time=perf_counter() - t0,
        history=history or None,
        shard=sharded.comm_stats(),
        config=config,
    )


def solve(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Optional[str] = None,
    graph: Optional[str] = None,
    timeout: Optional[float] = None,
    cycles: Optional[int] = None,
    algo_params: Optional[Dict[str, Any]] = None,
    seed: int = 0,
) -> Dict[str, Any]:
    """Solve a DCOP and return the assignment (reference-parity signature:
    infrastructure/run.py:52 returns ``metrics['assignment']``).

    >>> from pydcop_tpu.dcop import load_dcop
    >>> dcop = load_dcop('''
    ... name: mini
    ... objective: min
    ... domains: {d: {values: [0, 1]}}
    ... variables:
    ...   x: {domain: d}
    ...   y: {domain: d}
    ... constraints:
    ...   c: {type: intention, function: "10 if x == y else 0"}
    ... agents: [a1, a2, a3]
    ... ''')
    >>> a = solve(dcop, 'dpop')
    >>> a['x'] != a['y']
    True
    """
    return solve_result(
        dcop, algo, distribution, graph, timeout, cycles, algo_params, seed
    ).assignment


def run_local_thread_dcop(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Union[str, Any] = "adhoc",
    graph: Optional[str] = None,
    collector=None,
    collect_moment: str = "value_change",
    period: Optional[float] = None,
    replication: Optional[str] = None,
    seed: int = 0,
):
    """Reference-parity constructor (infrastructure/run.py:145): returns a
    deployed orchestrator.  In thread mode the tensor runtime is the whole
    agent population in one process."""
    from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

    orch = VirtualOrchestrator(
        dcop, algo, distribution=distribution, graph=graph,
        collect_on=collect_moment, period=period, collector=collector,
        seed=seed,
    )
    orch.deploy_computations()
    return orch


def run_local_process_dcop(
    dcop: DCOP,
    algo: Union[str, AlgorithmDef],
    distribution: Union[str, Any] = "adhoc",
    graph: Optional[str] = None,
    collector=None,
    collect_moment: str = "value_change",
    period: Optional[float] = None,
    replication: Optional[str] = None,
    seed: int = 0,
    n_processes: int = 2,
    platform: Optional[str] = "cpu",
    local_devices: Optional[int] = None,
    **resilience: Any,
):
    """Reference-parity constructor (infrastructure/run.py:225-287):
    returns a deployed orchestrator whose solve REALLY runs across
    ``n_processes`` OS processes on this host — each process is one rank
    of a global ``jax.distributed`` device mesh (Gloo on CPU, ICI/DCN on
    TPU pods) and the per-cycle ``psum`` replaces the reference's HTTP
    agent messaging.

    Supported for the sharded engine families (maxsum/amaxsum and
    mgm/dsa/dba/gdba); ``collector``/``collect_moment``/``period`` are
    accepted for signature parity but per-cycle collection is a
    thread-mode feature (ranks report end metrics only — documented
    deviation).  ``platform`` defaults to "cpu" so localhost ranks never
    fight over a single-tenant TPU chip; pass ``None`` on a real pod to
    autodetect the local chips.

    Extra keyword arguments (``fault_plan``, ``stall_timeout``,
    ``max_retries``, ``backoff_base``, ``checkpoint_every``,
    ``checkpoint_dir``, ``degrade_to_thread``, ...) configure the
    crash-resilience layer — see :class:`ProcessOrchestrator` and
    docs/resilience.rst.
    """
    from pydcop_tpu.runtime.process import ProcessOrchestrator

    orch = ProcessOrchestrator(
        dcop, algo, distribution=distribution, graph=graph, seed=seed,
        n_processes=n_processes, platform=platform,
        local_devices=local_devices, **resilience,
    )
    orch.deploy_computations()
    return orch
