"""Runtime: the high-level solve API, virtual orchestrator, metrics.

The TPU-native replacement for the reference's pydcop/infrastructure/
package: instead of threads + message queues + an orchestrator agent, the
runtime compiles the problem to tensors, runs jitted round kernels, and
reproduces the orchestration surface (deploy/run/pause/stop, scenario
events, metrics collection) as host-side control flow.
"""
from pydcop_tpu.runtime.faults import Fault, FaultPlan
from pydcop_tpu.runtime.run import (
    run_local_process_dcop,
    run_local_thread_dcop,
    solve,
    solve_result,
)

__all__ = ["solve", "solve_result", "run_local_thread_dcop",
           "run_local_process_dcop", "Fault", "FaultPlan"]
