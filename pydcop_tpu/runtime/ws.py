"""Minimal RFC 6455 WebSocket server — stdlib only.

The reference's UiServer speaks websockets via the ``websocket-server``
package (pydcop/infrastructure/ui.py:43-120); that dependency is not in
this image, so this module implements the small subset of RFC 6455 the
GUI protocol needs with nothing but ``socket``/``hashlib``/``base64``:

* HTTP Upgrade handshake (Sec-WebSocket-Accept);
* text frames in both directions (client→server frames are masked per
  the RFC, server→client unmasked), with 7/16/64-bit payload lengths;
* close (0x8) handshake and ping (0x9) → pong (0xA).

One thread per client, same threading model as the reference's server.
"""
from __future__ import annotations

import base64
import hashlib
import socket
import struct
import threading
from typing import Callable, List, Optional

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

#: refuse frames beyond this payload size — the length field is
#: client-controlled, and an uncapped 64-bit length is a trivial
#: memory-exhaustion vector
MAX_PAYLOAD = 8 * 2**20


class _BufferedSock:
    """recv() facade draining handshake-leftover bytes first (a client
    may pipeline its first frame with the HTTP upgrade request)."""

    def __init__(self, sock: socket.socket, leftover: bytes = b""):
        self._sock = sock
        self._buf = leftover

    def recv(self, n: int) -> bytes:
        if self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
            return out
        return self._sock.recv(n)


def _accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def encode_frame(payload: bytes, opcode: int = OP_TEXT,
                 mask: bool = False) -> bytes:
    """One FIN frame.  ``mask=True`` produces a client-side frame (the
    RFC requires clients to mask) — used by the test client."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < 1 << 16:
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = struct.pack(">I", 0x37FA213D)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


def read_frame(sock):
    """(opcode, payload) of the next frame, or (None, b"") on EOF or an
    oversized frame.  ``sock`` needs only a ``recv`` method."""

    def read_exact(n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    try:
        b0, b1 = read_exact(2)
    except (ConnectionError, OSError):
        return None, b""
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    try:
        if n == 126:
            (n,) = struct.unpack(">H", read_exact(2))
        elif n == 127:
            (n,) = struct.unpack(">Q", read_exact(8))
        if n > MAX_PAYLOAD:
            return None, b""
        key = read_exact(4) if masked else None
        payload = read_exact(n) if n else b""
    except (ConnectionError, OSError):
        return None, b""
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, payload


class WebSocketServer:
    """Tiny multicast websocket server.

    ``on_message(client_socket, text)`` is called for every text frame;
    reply with :meth:`send` / :meth:`send_all`.
    """

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        on_message: Optional[Callable[[socket.socket, str], None]] = None,
    ):
        self.host, self.port = host, port
        self.on_message = on_message
        self._clients: List[socket.socket] = []
        # per-client write locks: command replies and event broadcasts
        # come from different threads, and interleaved sendall calls
        # would corrupt the frame stream
        self._write_locks: dict = {}
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._running = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._sock = socket.create_server(
            (self.host, self.port), reuse_port=False
        )
        self._sock.settimeout(0.5)
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"ws-accept-{self.port}").start()

    def stop(self) -> None:
        self._running = False
        with self._lock:
            clients, self._clients = self._clients, []
        for c in clients:
            try:
                c.sendall(encode_frame(b"", OP_CLOSE))
                c.close()
            except OSError:
                pass
        if self._sock is not None:
            self._sock.close()

    # -- plumbing -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_client, args=(conn,),
                             daemon=True).start()

    def _serve_client(self, conn: socket.socket) -> None:
        try:
            leftover = self._handshake(conn)
            if leftover is None:
                conn.close()
                return
        except OSError:
            conn.close()
            return
        with self._lock:
            self._clients.append(conn)
            self._write_locks[conn] = threading.Lock()
        reader = _BufferedSock(conn, leftover)
        try:
            while self._running:
                opcode, payload = read_frame(reader)
                if opcode is None or opcode == OP_CLOSE:
                    break
                if opcode == OP_PING:
                    with self._lock:
                        wlock = self._write_locks.get(conn)
                    if wlock is None:  # a failed send() dropped the client
                        break
                    with wlock:
                        conn.sendall(encode_frame(payload, OP_PONG))
                elif opcode == OP_TEXT and self.on_message is not None:
                    try:
                        self.on_message(conn, payload.decode("utf-8"))
                    except Exception:  # noqa: BLE001 — one bad message
                        pass  # must not take the connection down
        finally:
            with self._lock:
                if conn in self._clients:
                    self._clients.remove(conn)
                self._write_locks.pop(conn, None)
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _handshake(conn: socket.socket) -> Optional[bytes]:
        """Returns bytes received past the header terminator (a client
        may pipeline its first frame with the upgrade request), or None
        on a failed handshake."""
        conn.settimeout(5)
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(4096)
            if not chunk:
                return None
            data += chunk
        head, _, leftover = data.partition(b"\r\n\r\n")
        headers = {}
        for line in head.split(b"\r\n")[1:]:
            if b":" in line:
                k, v = line.split(b":", 1)
                headers[k.strip().lower()] = v.strip()
        key = headers.get(b"sec-websocket-key")
        if key is None:
            return None
        conn.sendall(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\n"
            b"Connection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: "
            + _accept_key(key.decode()).encode() + b"\r\n\r\n"
        )
        conn.settimeout(None)
        return leftover

    # -- sending ------------------------------------------------------------

    def send(self, client: socket.socket, text: str) -> None:
        with self._lock:
            wlock = self._write_locks.get(client)
        if wlock is None:
            return  # client already gone
        try:
            with wlock:
                client.sendall(encode_frame(text.encode("utf-8")))
        except OSError:
            with self._lock:
                if client in self._clients:
                    self._clients.remove(client)
                self._write_locks.pop(client, None)

    def send_all(self, text: str) -> None:
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            self.send(c, text)

    @property
    def n_clients(self) -> int:
        with self._lock:
            return len(self._clients)
