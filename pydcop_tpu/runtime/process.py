"""Process-mode orchestrator: one OS process per mesh rank.

Equivalent capability to the reference's run_local_process_dcop
(pydcop/infrastructure/run.py:225-287): the solve really runs across N
separate OS processes on this host.  The reference gives every *agent* a
process and wires them with HTTP; here every process is one *rank* of a
global JAX device mesh (``jax.distributed`` — Gloo collectives on CPU,
ICI/DCN on real TPU pods) and each cycle's single ``psum`` replaces the
HTTP message traffic.  Ranks are the existing ``pydcop_tpu agent
--multihost`` CLI workers, spawned on localhost with an OS-assigned
coordinator port.

Crash resilience: every rank heartbeats a per-rank file
(runtime/faults.py HeartbeatWriter) and a coordinator watchdog monitors
exit codes + heartbeat staleness.  A rank that dies by signal or by an
injected kill, or whose heartbeat goes stale (a wedged collective),
triggers a clean teardown of the whole mesh (no orphan processes, no
indefinite hang) and a relaunch with exponential backoff that resumes
from the latest valid checkpoint (maxsum family, ``checkpoint_every``);
after ``max_retries`` failed relaunches the solve degrades to thread
mode instead of failing.  Deterministic rank *errors* (a Python
exception, a bad argument) still raise immediately — retrying a
deterministic bug only hides it.

Scope (documented deviation): the multi-process mesh executes the sharded
engine families — factor-graph BP (maxsum/amaxsum) and local search
(mgm/dsa/dba/gdba).  Dynamic scenarios and per-cycle collection remain
thread-mode features; the complete host-driven algorithms (dpop, syncbb,
ncbb) gain nothing from extra processes and are rejected loudly.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from time import perf_counter
from typing import Any, Dict, List, Optional, Union

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.distribution import load_distribution_module
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.graph import load_graph_module
from pydcop_tpu.runtime.events import send_fault
from pydcop_tpu.runtime.faults import (
    ENV_FAULT_ATTEMPT,
    ENV_FAULT_PLAN,
    KILL_EXIT_CODE,
    FaultPlan,
    apply_checkpoint_faults,
    stalled_ranks,
)
from pydcop_tpu.runtime.stats import FaultCounters

logger = logging.getLogger(__name__)

#: algorithms with a sharded multi-process engine (parallel/multihost.py)
PROCESS_MODE_ALGOS = ("maxsum", "amaxsum", "mgm", "dsa", "dba", "gdba")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _CoordinatorBindError(RuntimeError):
    """The jax.distributed coordinator could not bind its port (lost the
    race for the probed free port) — the rendezvous can be retried."""


#: stderr fragments that identify a coordinator-port bind failure
_BIND_FAILURE_TOKENS = (
    "address already in use",
    "failed to bind",
    "bind address",
    "unavailable: connection",
)


class _RankFailure(RuntimeError):
    """A RETRYABLE rank failure: killed by signal / injected kill, or
    declared stalled by the heartbeat watchdog.  Deterministic errors
    (clean nonzero exits) do NOT raise this — they raise RuntimeError
    straight out, as retrying a reproducible bug only hides it."""

    def __init__(self, rank: int, stalled: bool,
                 returncode: Optional[int] = None, stderr: str = ""):
        self.rank = rank
        self.stalled = stalled
        self.returncode = returncode
        self.stderr = stderr
        what = (
            f"stalled (heartbeat stale)" if stalled
            else f"died (rc={returncode})"
        )
        super().__init__(f"process-mode rank {rank} {what}")


class ProcessOrchestrator:
    """Orchestrates a solve across N real localhost processes.

    Mirrors the VirtualOrchestrator lifecycle surface used by the
    library API (deploy_computations / run / stop_agents / stop /
    end_metrics) for the process-mode subset.
    """

    def __init__(
        self,
        dcop: DCOP,
        algo: Union[str, AlgorithmDef],
        distribution: Union[str, Distribution] = "adhoc",
        graph: Optional[str] = None,
        seed: int = 0,
        n_processes: int = 2,
        platform: Optional[str] = "cpu",
        local_devices: Optional[int] = None,
        fault_plan: Optional[FaultPlan] = None,
        stall_timeout: float = 30.0,
        heartbeat_interval: float = 0.5,
        max_retries: int = 2,
        backoff_base: float = 0.5,
        backoff_max: float = 8.0,
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        degrade_to_thread: bool = True,
    ):
        if n_processes < 1:
            raise ValueError("n_processes must be >= 1")
        self.dcop = dcop
        self.algo_def = (
            algo
            if isinstance(algo, AlgorithmDef)
            else AlgorithmDef.build_with_default_params(
                algo, mode=dcop.objective
            )
        )
        if self.algo_def.algo not in PROCESS_MODE_ALGOS:
            raise ValueError(
                f"process mode runs the sharded engine families "
                f"{PROCESS_MODE_ALGOS}, not {self.algo_def.algo!r}; "
                f"use run_local_thread_dcop for host-driven algorithms"
            )
        self.algo_module = load_algorithm_module(self.algo_def.algo)
        graph_type = graph or self.algo_module.GRAPH_TYPE
        self.graph_module = load_graph_module(graph_type)
        self.cg = self.graph_module.build_computation_graph(dcop)
        if isinstance(distribution, Distribution):
            self.distribution = distribution
        else:
            self.distribution = load_distribution_module(
                distribution
            ).distribute(
                self.cg,
                dcop.agents.values(),
                hints=getattr(dcop, "dist_hints", None),
                computation_memory=self.algo_module.computation_memory,
                communication_load=self.algo_module.communication_load,
            )
        self.seed = seed
        self.n_processes = n_processes
        self.platform = platform
        self.local_devices = local_devices
        self.fault_plan = fault_plan
        self.stall_timeout = stall_timeout
        self.heartbeat_interval = heartbeat_interval
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.checkpoint_every = checkpoint_every
        self.checkpoint_dir = checkpoint_dir
        self.degrade_to_thread = degrade_to_thread
        self.fault_counters = FaultCounters()
        self.fault_log: List[Dict[str, Any]] = []
        self.status = "INITIAL"
        self._procs: List[subprocess.Popen] = []
        self._last_result: Optional[SolveResult] = None
        self._dcop_file: Optional[str] = None
        self._owns_ckpt_dir = False

    # -- lifecycle ---------------------------------------------------------

    def deploy_computations(self) -> None:
        """Serialize the DCOP for the ranks (every rank loads the same
        file — SPMD) and validate the placement hosts everything."""
        missing = [
            n.name for n in self.cg.nodes
            if not self.distribution.has_computation(n.name)
        ]
        if missing:
            raise ValueError(
                f"Distribution does not host computations: {missing}"
            )
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        fd, path = tempfile.mkstemp(
            prefix="pydcop_tpu_proc_", suffix=".yaml"
        )
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(dcop_yaml(self.dcop))
        self._dcop_file = path
        if self.checkpoint_every and not self.checkpoint_dir:
            # the snapshot directory must OUTLIVE each launch attempt
            # (a relaunch resumes from it), so it is not part of the
            # per-attempt rank tmpdir
            self.checkpoint_dir = tempfile.mkdtemp(
                prefix="pydcop_tpu_ckpt_"
            )
            self._owns_ckpt_dir = True
        self.status = "DEPLOYED"

    def _spawn(self, rank: int, port: int, cycles: int,
               timeout: Optional[float], out_file: str,
               err_file, hb_file: Optional[str] = None,
               attempt: int = 0) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "pydcop_tpu",
            "--output", out_file,
            "agent", "--multihost",
            "--coordinator", f"127.0.0.1:{port}",
            "--num-processes", str(self.n_processes),
            "--process-id", str(rank),
            "--dcop", self._dcop_file,
            "--algo", self.algo_def.algo,
            "--cycles", str(cycles),
            "--seed", str(self.seed),
        ]
        if timeout is not None:
            # global option: goes before the `agent` subcommand
            cmd[3:3] = ["--timeout", str(timeout)]
        if self.platform:
            cmd += ["--platform", self.platform]
        if self.local_devices:
            cmd += ["--local-devices", str(self.local_devices)]
        if hb_file:
            cmd += ["--heartbeat-file", hb_file,
                    "--heartbeat-interval", str(self.heartbeat_interval)]
        if self.checkpoint_every and self.checkpoint_dir:
            cmd += ["--checkpoint-dir", self.checkpoint_dir,
                    "--checkpoint-every", str(self.checkpoint_every)]
        for name, value in (self.algo_def.params or {}).items():
            if value is not None:
                cmd += ["--algo_params", f"{name}:{value}"]
        env = {**os.environ}
        env[ENV_FAULT_ATTEMPT] = str(attempt)
        if self.fault_plan is not None and self.fault_plan.has_rank_faults:
            env[ENV_FAULT_PLAN] = self.fault_plan.to_json()
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", ""
        )
        if self.local_devices:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count"
                f"={self.local_devices}"
            ).strip()
        # stderr goes to a FILE, not a pipe: ranks are coupled by the
        # per-cycle collective, so one rank blocking on a full stderr
        # pipe would wedge every other rank inside the psum
        return subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=err_file,
            text=True, env=env,
        )

    #: watchdog poll period (exit codes + heartbeat staleness)
    WATCH_POLL = 0.1

    def _classify_exit(self, rank: int, rc: int, err_path: str):
        """Map a nonzero rank exit onto the failure taxonomy (raises)."""
        try:
            with open(err_path, encoding="utf-8") as f:
                stderr = f.read()
        except OSError:
            stderr = ""
        if rc == KILL_EXIT_CODE or rc < 0:
            # injected kill or death by signal (OOM-kill, preemption,
            # kill -9 ...) — the retryable class
            raise _RankFailure(rank, stalled=False, returncode=rc,
                               stderr=stderr[-2000:])
        low = stderr.lower()
        if any(t in low for t in _BIND_FAILURE_TOKENS):
            raise _CoordinatorBindError(stderr[-500:])
        raise RuntimeError(
            f"process-mode rank failed "
            f"(rc={rc}): {stderr[-2000:]}"
        )

    def _run_once(self, n_cycles: int, timeout: Optional[float],
                  attempt: int = 0):
        """One rendezvous attempt: spawn every rank, watch, parse.

        The watchdog loop polls exit codes and heartbeat files: a rank
        dead by signal/injected kill raises :class:`_RankFailure`
        (retryable), a stale heartbeat raises it with ``stalled=True``,
        a deterministic error raises RuntimeError, and the whole mesh
        is torn down in ``finally`` on every path — no orphans, no
        indefinite hang.  Returns the per-rank result dicts, or None on
        timeout (budget exhausted or a rank force-exited by the CLI
        watchdog).
        """
        port = _free_port()
        tmpdir = tempfile.mkdtemp(prefix="pydcop_tpu_ranks_")
        out_files: List[str] = []
        err_paths: List[str] = []
        hb_paths: Dict[int, str] = {}
        err_handles = []
        try:
            for rank in range(self.n_processes):
                out_file = os.path.join(tmpdir, f"rank{rank}.json")
                err_path = os.path.join(tmpdir, f"rank{rank}.err")
                hb_path = os.path.join(tmpdir, f"rank{rank}.hb")
                out_files.append(out_file)
                err_paths.append(err_path)
                hb_paths[rank] = hb_path
                fh = open(err_path, "w", encoding="utf-8")
                err_handles.append(fh)
                self._procs.append(
                    self._spawn(rank, port, n_cycles, timeout, out_file,
                                fh, hb_file=hb_path, attempt=attempt)
                )
            self.status = "RUNNING"
            deadline = None
            if timeout is not None:
                # generous grace over the solve timeout: rank startup +
                # gloo rendezvous + compile are not solve time
                deadline = perf_counter() + max(30.0, timeout * 3)
            procs = list(self._procs)
            pending = set(range(self.n_processes))
            while pending:
                for rank in sorted(pending):
                    rc = procs[rank].poll()
                    if rc is None:
                        continue
                    pending.discard(rank)
                    if rc == 0:
                        continue
                    if rc == 42:
                        # the rank's own CLI watchdog force-exited it
                        # at timeout + slack (cli.py TIMEOUT_SLACK)
                        return None
                    self._classify_exit(rank, rc, err_paths[rank])
                if not pending:
                    break
                stalled = stalled_ranks(
                    {r: hb_paths[r] for r in pending},
                    self.stall_timeout,
                )
                if stalled:
                    raise _RankFailure(stalled[0], stalled=True)
                if deadline is not None and perf_counter() > deadline:
                    return None
                time.sleep(self.WATCH_POLL)
            results = []
            for out_file in out_files:
                with open(out_file, encoding="utf-8") as f:
                    results.append(json.load(f))
            return results
        finally:
            self._kill_all()
            for fh in err_handles:
                fh.close()
            for f in out_files + err_paths + list(hb_paths.values()):
                try:
                    os.unlink(f)
                except OSError:
                    pass
            try:
                os.rmdir(tmpdir)
            except OSError:
                pass

    def run(
        self,
        scenario=None,
        timeout: Optional[float] = None,
        cycles: Optional[int] = None,
    ) -> SolveResult:
        if scenario is not None and getattr(scenario, "events", None):
            raise ValueError(
                "dynamic scenarios run in thread mode "
                "(run_local_thread_dcop); process mode solves static "
                "DCOPs across OS processes"
            )
        if self.status == "INITIAL":
            raise RuntimeError("deploy_computations() first")
        n_cycles = cycles if cycles is not None else 30
        t0 = perf_counter()
        results = None
        bind_failures = 0
        attempt = 0  # fault-relaunch attempt (0 = first launch)
        while True:
            if self.fault_plan is not None:
                damaged = apply_checkpoint_faults(
                    self.fault_plan, self.checkpoint_dir, attempt
                )
                if damaged:
                    self.fault_counters.inc("faults_injected",
                                            len(damaged))
                    self.fault_log.append(
                        {"fault": "checkpoint", "paths": damaged,
                         "attempt": attempt}
                    )
            try:
                results = self._run_once(n_cycles, timeout, attempt)
                break
            except _CoordinatorBindError:
                # _free_port() is inherently racy (the probed port is
                # released before rank 0 re-binds it as coordinator);
                # retry the whole rendezvous on a fresh port.  Not a
                # fault: does not consume a fault-retry attempt.
                bind_failures += 1
                if bind_failures >= 3:
                    raise
            except _RankFailure as failure:
                kind = "rank_stalls" if failure.stalled \
                    else "rank_crashes"
                self.fault_counters.inc(kind)
                if failure.returncode == KILL_EXIT_CODE:
                    # the kill was ours (fault plan), not the world's
                    self.fault_counters.inc("faults_injected")
                self.fault_log.append({
                    "fault": "stall" if failure.stalled else "crash",
                    "rank": failure.rank,
                    "returncode": failure.returncode,
                    "attempt": attempt,
                })
                send_fault(f"detected.rank{failure.rank}", {
                    "rank": failure.rank,
                    "stalled": failure.stalled,
                    "attempt": attempt,
                })
                logger.warning("watchdog: %s (attempt %d)", failure,
                               attempt)
                if attempt >= self.max_retries:
                    if self.degrade_to_thread:
                        return self._degrade(n_cycles, timeout, t0,
                                             failure)
                    raise RuntimeError(
                        f"{failure}; giving up after {attempt} "
                        f"relaunch(es)"
                    ) from failure
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** attempt))
                time.sleep(delay)
                attempt += 1
                self.fault_counters.inc("retries")
        if results is None:  # timed out
            self.status = "TIMEOUT"
            self._last_result = SolveResult(
                status="TIMEOUT", assignment={}, cost=None,
                violation=None, cycle=0, msg_count=0, msg_size=0.0,
                time=perf_counter() - t0,
            )
            return self._last_result

        # SPMD invariant: every rank computed the same global solve
        first = results[0]
        if int(first.get("resumed_from", 0) or 0) > 0:
            self.fault_counters.inc("resumes")
            send_fault("recovered.resume", {
                "cycle": int(first["resumed_from"]),
                "attempt": attempt,
            })
        for other in results[1:]:
            if other["assignment"] != first["assignment"]:
                raise RuntimeError(
                    "process-mode ranks diverged: assignments differ "
                    "across processes (SPMD invariant broken)"
                )
        n_edges = sum(
            len(n.neighbors) for n in self.cg.nodes
        ) // 2
        self._last_result = SolveResult(
            status=first["status"],
            assignment=first["assignment"],
            cost=first["cost"],
            violation=first["violation"],
            cycle=first["cycle"],
            msg_count=2 * n_edges * first["cycle"],
            msg_size=float(first.get("msg_size", 0.0)
                           or 2 * n_edges * first["cycle"]),
            time=perf_counter() - t0,
        )
        self.status = "FINISHED" if first["status"] == "FINISHED" \
            else first["status"]
        self.n_global_devices = int(first.get("n_global_devices", 0))
        return self._last_result

    def _degrade(self, n_cycles: int, timeout: Optional[float],
                 t0: float, failure: _RankFailure) -> SolveResult:
        """Last-resort graceful degradation: after max_retries failed
        relaunches the solve runs in thread mode (one process IS the
        whole agent population) — slower scale-out, same answer."""
        logger.error(
            "process mode unrecoverable after %d relaunch(es) (%s); "
            "degrading to thread mode", self.max_retries, failure,
        )
        self.fault_counters.inc("degraded_to_thread")
        send_fault("recovered.degrade", {"reason": str(failure)})
        from pydcop_tpu.runtime.run import solve_result

        res = solve_result(
            self.dcop, self.algo_def, timeout=timeout,
            cycles=n_cycles, seed=self.seed,
        )
        res.time = perf_counter() - t0
        self._last_result = res
        self.status = res.status
        self.n_global_devices = 0
        return res

    def _kill_all(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
        self._procs = []

    def stop_agents(self, timeout: Optional[float] = None) -> None:
        self._kill_all()
        self.status = "STOPPED"

    def stop(self) -> None:
        self._kill_all()
        if self._dcop_file:
            try:
                os.unlink(self._dcop_file)
            except OSError:
                pass
            self._dcop_file = None
        if self._owns_ckpt_dir and self.checkpoint_dir:
            shutil.rmtree(self.checkpoint_dir, ignore_errors=True)
            self.checkpoint_dir = None
            self._owns_ckpt_dir = False
        if self.status != "FINISHED":
            self.status = "STOPPED"

    def end_metrics(self) -> Dict[str, Any]:
        if self._last_result is None:
            return {"status": self.status}
        m = self._last_result.metrics()
        m["status"] = self.status
        m["distribution"] = self.distribution.mapping()
        m["n_processes"] = self.n_processes
        if self.checkpoint_dir:
            from pydcop_tpu.runtime.checkpoint import CheckpointManager

            self.fault_counters.counts["checkpoints_saved"] = len(
                CheckpointManager(self.checkpoint_dir).snapshots()
            )
        m["resilience"] = self.fault_counters.as_dict()
        if self.fault_log:
            m["fault_log"] = list(self.fault_log)
        return m
