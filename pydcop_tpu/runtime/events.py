"""In-process event bus.

Equivalent capability to the reference's pydcop/infrastructure/Events.py
(:41-96): topic-based pub/sub with ``*`` wildcard suffix matching, disabled
by default; topics follow the reference's naming
(``computations.value.<name>``, ``computations.cycle.<name>``,
``agents.add_computation.<agent>``, ...).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Tuple


class EventDispatcher:
    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._subs: List[Tuple[str, Callable]] = []

    def subscribe(self, topic: str, callback: Callable) -> None:
        self._subs.append((topic, callback))

    def unsubscribe(self, callback: Callable) -> None:
        self._subs = [(t, cb) for t, cb in self._subs if cb != callback]

    def send(self, topic: str, evt) -> None:
        if not self.enabled:
            return
        for pattern, cb in list(self._subs):
            if self._match(pattern, topic):
                cb(topic, evt)

    @staticmethod
    def _match(pattern: str, topic: str) -> bool:
        if pattern == topic or pattern == "*":
            return True
        if pattern.endswith("*"):
            return topic.startswith(pattern[:-1])
        return False


#: process-global bus, disabled unless observability is turned on
#: (reference: Events.py event_bus :103)
event_bus = EventDispatcher()


#: fault/recovery topic prefix (runtime/faults.py).  Topics:
#: ``faults.injected.<kind>``, ``faults.detected.<rank|agent>``,
#: ``faults.recovered.<resume|repair|degrade>`` — subscribe with
#: ``faults.*`` (the UI server pushes them to ws/SSE clients).
FAULT_TOPIC_PREFIX = "faults."


def send_fault(event: str, payload) -> None:
    """Publish a fault/recovery event on the global bus (no-op unless
    observability is enabled, like every other topic)."""
    event_bus.send(FAULT_TOPIC_PREFIX + event, payload)


#: batched-solve topic prefix (pydcop_tpu.batch).  Topics:
#: ``batch.bucket.formed`` (signature, size, waste),
#: ``batch.compile.hit`` / ``batch.compile.miss`` (cache key),
#: ``batch.instance.converged`` (label, cycle),
#: ``batch.run.done`` (instances, buckets, wall) — subscribe with
#: ``batch.*`` (the UI server pushes them to ws/SSE clients).
BATCH_TOPIC_PREFIX = "batch."


def send_batch(event: str, payload) -> None:
    """Publish a batched-solve lifecycle event on the global bus
    (no-op unless observability is enabled)."""
    event_bus.send(BATCH_TOPIC_PREFIX + event, payload)


#: solve-service topic prefix (pydcop_tpu.serve).  Topics:
#: ``serve.job.submitted`` (jid, tenant, priority, algo),
#: ``serve.job.admitted`` (jid, signature, lane, midflight),
#: ``serve.job.progress`` (jid, cycle, cost — the anytime assignment
#: stream at chunk boundaries),
#: ``serve.job.done`` (jid, status, cycle, cost, latency),
#: ``serve.bucket.opened`` / ``serve.bucket.merged`` /
#: ``serve.bucket.closed`` (signature, lanes),
#: ``serve.prewarm.scheduled`` (runners) and ``serve.resume.done``
#: (jobs) — plus the fault-isolation/overload surface (ISSUE 7):
#: ``serve.fault.injected`` (a fault-plan serve fault fired),
#: ``serve.fault.bucket_failed`` / ``serve.fault.bisect`` (a bucket
#: step threw; its jobs split into isolated suspect groups),
#: ``serve.fault.nan_lane`` (non-finite lane state/cost detected),
#: ``serve.fault.retry`` / ``serve.fault.quarantined`` (the poison-job
#: escalation ladder), ``serve.fault.scheduler_restart`` /
#: ``serve.fault.scheduler_dead`` (the supervisor), ``serve.job.shed``
#: / ``serve.job.rejected`` (admission control), ``serve.stream.lossy``
#: (a slow stream consumer started dropping progress events) and
#: ``serve.journal.torn`` / ``serve.journal.compacted`` — subscribe
#: with ``serve.*`` (the UI server pushes them to ws/SSE clients
#: alongside ``batch.*``/``harness.*``).
SERVE_TOPIC_PREFIX = "serve."


def send_serve(event: str, payload) -> None:
    """Publish a solve-service lifecycle event on the global bus
    (no-op unless observability is enabled)."""
    event_bus.send(SERVE_TOPIC_PREFIX + event, payload)


#: solution-cache topic prefix (pydcop_tpu.serve.memo).  Topics:
#: ``memo.hit.exact`` (jid, tenant, key — a submitted instance's
#: content hash matched a cached solve, served bit-identically),
#: ``memo.hit.variant`` (jid, tenant, key, edits, distance — a
#: near-duplicate served by warm-repairing the nearest cached
#: instance), ``memo.miss`` (jid, tenant), ``memo.insert`` (key,
#: tenant, cost), ``memo.invalidate`` (tenant, reason ∈ {ttl, churn},
#: dropped), ``memo.fallback.cold`` (jid, reason — a warm repair
#: converged worse than its seed or exhausted headroom; the cold
#: result was served instead, upholding the never-worse guarantee)
#: and ``memo.corrupt.skipped`` (path — a CRC-failed entry skipped on
#: rehydrate/adopt) — subscribe with ``memo.*`` (the UI server pushes
#: them to ws/SSE clients alongside ``serve.*``).
MEMO_TOPIC_PREFIX = "memo."


def send_memo(event: str, payload) -> None:
    """Publish a solution-cache event on the global bus (no-op unless
    observability is enabled)."""
    event_bus.send(MEMO_TOPIC_PREFIX + event, payload)


#: solve-fleet topic prefix (pydcop_tpu.serve.fleet).  Topics:
#: ``fleet.replica.up`` / ``fleet.replica.down`` (name, reason — a
#: replica joined the fleet / was declared dead by the supervisor),
#: ``fleet.replica.stalled`` / ``fleet.replica.healed`` (stale
#: heartbeat detected / recovered — routed around, never re-seated),
#: ``fleet.replica.partitioned`` (unreachable for new placements),
#: ``fleet.router.placed`` (jid, replica, key, warm — one per routed
#: job: the compile-cache routing-key decision made for it),
#: ``fleet.job.reseated`` (jid, from, to, checkpoint — a dead
#: replica's in-flight job re-seated on a peer via the resume
#: protocol), ``fleet.job.rejected`` (fleet-level admission control)
#: and ``fleet.recovery.done`` (replica, jobs, rto_s — every job of a
#: lost replica completed elsewhere; rto_s is the recovery-time
#: objective measured from kill detection) — subscribe with
#: ``fleet.*`` (the UI server pushes them to ws/SSE clients alongside
#: ``serve.*``).
FLEET_TOPIC_PREFIX = "fleet."


def send_fleet(event: str, payload) -> None:
    """Publish a solve-fleet lifecycle event on the global bus (no-op
    unless observability is enabled)."""
    event_bus.send(FLEET_TOPIC_PREFIX + event, payload)


#: sharded-collective topic prefix (parallel/mesh).  Topics:
#: ``shard.comm.selected`` (mode, collective, cut_fraction,
#: boundary_columns, bytes_per_cycle_dense/compact, exchange_rounds —
#: the engine's chosen collective path, emitted once at build time) —
#: subscribe with ``shard.*`` (the UI server pushes them to ws/SSE
#: clients alongside ``harness.*``/``batch.*``).
SHARD_TOPIC_PREFIX = "shard."


def send_shard(event: str, payload) -> None:
    """Publish a sharded-engine collective/partition event on the
    global bus (no-op unless observability is enabled)."""
    event_bus.send(SHARD_TOPIC_PREFIX + event, payload)


#: data-integrity topic prefix (runtime/integrity +
#: parallel/elastic).  Topics:
#: ``integrity.sentinel.trip`` (reason nonfinite/residual/operand,
#: chunk, reading — an in-jit invariant sentinel fired),
#: ``integrity.scrub.run`` (chunk, shadow mode) and
#: ``integrity.scrub.mismatch`` (chunk, primary/shadow checksums — the
#: shadow re-execution disagreed with the primary: silent data
#: corruption detected),
#: ``integrity.injected`` (operand, chunk — a corrupt_slab fault
#: fired),
#: ``integrity.restore`` (cycle, snapshot — state restored from a
#: CRC'd chunk-boundary snapshot) — subscribe with ``integrity.*``
#: (the UI server pushes them to ws/SSE clients alongside
#: ``faults.*``).
INTEGRITY_TOPIC_PREFIX = "integrity."


def send_integrity(event: str, payload) -> None:
    """Publish a data-integrity event on the global bus (no-op unless
    observability is enabled)."""
    event_bus.send(INTEGRITY_TOPIC_PREFIX + event, payload)


#: elastic-mesh topic prefix (parallel/elastic).  Topics:
#: ``elastic.device.lost`` (device, cycle — a kill_device/shrink_mesh
#: fault dropped mesh devices),
#: ``elastic.shrink`` (from/to device counts, cycle, exact_restore —
#: the solve repartitioned onto the survivors and continued),
#: ``elastic.repack`` (devices, cycle — the ladder floor: one counted
#: cold repack + replay),
#: ``elastic.resumed`` (cycle, devices — the shrunk solve is running
#: again) — subscribe with ``elastic.*`` (the UI server pushes them to
#: ws/SSE clients alongside ``shard.*``).
ELASTIC_TOPIC_PREFIX = "elastic."


def send_elastic(event: str, payload) -> None:
    """Publish an elastic-mesh lifecycle event on the global bus
    (no-op unless observability is enabled)."""
    event_bus.send(ELASTIC_TOPIC_PREFIX + event, payload)


#: exact-inference (DPOP) topic prefix (algorithms/dpop +
#: ops/dpop_shard).  Topics:
#: ``dpop.shard.plan`` (n_shards, levels, bytes_per_device,
#: wire_bytes_pruned/dense, pruned_fraction — the separator-tiling
#: layout chosen for the sweep, emitted once at plan time),
#: ``dpop.shard.sweep.done`` (time, bytes shipped — after the tiled
#: UTIL+VALUE sweep),
#: ``dpop.minibucket.bounds`` (i_bound, lower_bound, upper_bound, gap —
#: after a bounded mini-bucket solve) — subscribe with ``dpop.*`` (the
#: UI server pushes them to ws/SSE clients alongside ``shard.*``).
DPOP_TOPIC_PREFIX = "dpop."


def send_dpop(event: str, payload) -> None:
    """Publish an exact-inference engine event on the global bus
    (no-op unless observability is enabled)."""
    event_bus.send(DPOP_TOPIC_PREFIX + event, payload)


#: warm-repair topic prefix (runtime/repair).  Topics:
#: ``repair.mutation.applied`` (kind, target, dirty variables),
#: ``repair.headroom.claimed`` / ``repair.headroom.released`` (slot
#: kind, remaining free slots),
#: ``repair.repack`` (reason, retraces — fired exactly once per
#: headroom exhaustion, never an exception mid-run),
#: ``repair.recovered`` (time_to_recover_s, cycles, cost after a
#: mutation re-converged) — subscribe with ``repair.*`` (the UI server
#: pushes them to ws/SSE clients alongside ``faults.*``).
REPAIR_TOPIC_PREFIX = "repair."


def send_repair(event: str, payload) -> None:
    """Publish a warm-repair lifecycle event on the global bus (no-op
    unless observability is enabled)."""
    event_bus.send(REPAIR_TOPIC_PREFIX + event, payload)


#: portfolio topic prefix (pydcop_tpu.portfolio).  Topics:
#: ``portfolio.dataset.progress`` (cell key, status, done/skipped
#: counts — one per labeled sweep cell) and ``portfolio.dataset.done``
#: (summary) from the self-labeling harness,
#: ``portfolio.model.loaded`` (path, input width, meta) when an auto
#: solve loads a trained cost model,
#: ``portfolio.config.selected`` (chosen config, fallback flag,
#: predicted normalized time, feasible/masked counts) at selection
#: time, and ``portfolio.solve.done`` (config, status, predicted vs
#: actual seconds — the honesty audit) after the winner ran —
#: subscribe with ``portfolio.*`` (the UI server pushes them to
#: ws/SSE clients alongside ``batch.*``/``serve.*``).
PORTFOLIO_TOPIC_PREFIX = "portfolio."


def send_portfolio(event: str, payload) -> None:
    """Publish a portfolio auto-selection/dataset event on the global
    bus (no-op unless observability is enabled)."""
    event_bus.send(PORTFOLIO_TOPIC_PREFIX + event, payload)


#: SLO guardrail topic prefix (pydcop_tpu.scenario — the city-twin
#: runner's degradation ladder).  Topics:
#: ``slo.tier.breach`` (tier, attainment, floor — a tier's rolling
#: deadline attainment fell under its floor),
#: ``slo.ladder.escalated`` (rung, rung_name, tiers — one
#: deterministic step up: shed bronze → clamp silver chunks → force
#: gold onto the emptiest healthy replica),
#: ``slo.ladder.released`` (rung, rung_name — one hysteresis step
#: down after `hold` clean evaluations),
#: ``slo.shed.bronze`` (tier, jid-label — a rung-1 admission refused
#: at the twin's front door), ``slo.clamp.silver`` (pressure — rung 2
#: engaged deadline pressure on the fleet), ``slo.reroute.gold``
#: (label — a rung-3 emptiest-healthy placement) and
#: ``slo.scorecard`` (the final per-tier attainment/latency summary)
#: — subscribe with ``slo.*`` (the UI server pushes them to ws/SSE
#: clients alongside ``serve.*``/``fleet.*``).
SLO_TOPIC_PREFIX = "slo."


def send_slo(event: str, payload) -> None:
    """Publish an SLO guardrail-ladder event on the global bus (no-op
    unless observability is enabled)."""
    event_bus.send(SLO_TOPIC_PREFIX + event, payload)


#: anytime exact-search topic prefix (pydcop_tpu.search).  Topics:
#: ``search.bounds`` (chunk, incumbent, lower_bound, upper_bound, gap,
#: proved — the anytime bound sandwich, one event per device chunk:
#: exactly the stream PR 9's mini-bucket fallback emits, but
#: TIGHTENING over time until the gap closes to an optimality proof),
#: ``search.spill.drain`` (chunk, stash_rows — the counted host spill
#: fallback engaged: annex rows pulled to the host stash),
#: ``search.done`` (status, optimal, chunks, nodes, cost) — subscribe
#: with ``search.*`` (the UI server pushes them to ws/SSE clients
#: alongside ``dpop.*``).
SEARCH_TOPIC_PREFIX = "search."


def send_search(event: str, payload) -> None:
    """Publish an exact-search engine event on the global bus (no-op
    unless observability is enabled)."""
    event_bus.send(SEARCH_TOPIC_PREFIX + event, payload)


#: solve-harness topic prefix (algorithms/base).  Topics:
#: ``harness.run.done`` (algo, status, cycle + the HarnessCounters
#: scorecard: host_sync_count, dispatch_wait_s, donated_chunks,
#: masked_tail_cycles, ...) — subscribe with ``harness.*`` (the UI
#: server pushes them to ws/SSE clients like ``batch.*``).
HARNESS_TOPIC_PREFIX = "harness."


def send_harness(event: str, payload) -> None:
    """Publish a solve-harness lifecycle event on the global bus
    (no-op unless observability is enabled)."""
    event_bus.send(HARNESS_TOPIC_PREFIX + event, payload)
