"""Per-cycle computation tracing.

Equivalent capability to the reference's pydcop/infrastructure/stats.py
(:50-105): CSV step-tracing with operation counters, including the
non-concurrent operation count (`nc_op_count`, the literature's logical-time
metric).  For tensor solvers op counts come from kernel shapes: one cycle's
`op_count` is the total number of cost-table entries touched, and
`nc_op_count` is the critical-path share (one variable's worth), since all
per-variable updates of a cycle are concurrent on device.
"""
from __future__ import annotations

import csv
import dataclasses
from typing import List, Optional

#: matches the reference's column set (stats.py:50-66)
COLUMNS = ["timestamp", "computation", "cycle", "op_count", "nc_op_count",
           "msg_count", "cost"]


def cycle_op_counts(tensors) -> tuple:
    """(op_count, nc_op_count) per cycle from compiled kernel shapes."""
    ops = 0
    max_per_factor = 0
    for b in tensors.buckets:
        entries = b.n_factors
        for _ in range(b.arity):
            entries *= tensors.max_domain_size
        ops += entries * b.arity  # each position's reduction reads the table
        per_factor = 1
        for _ in range(b.arity):
            per_factor *= tensors.max_domain_size
        max_per_factor = max(max_per_factor, per_factor * b.arity)
    return ops, max_per_factor


#: counter names surfaced under metrics["resilience"] — one schema for
#: thread mode (VirtualOrchestrator) and process mode
#: (ProcessOrchestrator) so collectors need no mode-specific parsing
RESILIENCE_COUNTERS = (
    "faults_injected",      # fault-plan faults fired (any kind)
    "rank_crashes",         # ranks seen dead (injected kill or signal)
    "rank_stalls",          # ranks declared stalled by the watchdog
    "retries",              # full-mesh relaunches after a failure
    "resumes",              # runs warm-started from a checkpoint
    "repairs",              # agent-removal repair DCOPs solved
    "checkpoints_saved",
    "checkpoints_rejected",  # snapshots refused (checksum/version)
    "degraded_to_thread",   # process mode fell back to thread mode
)


class FaultCounters:
    """Fault + recovery counters collected by the orchestrators and
    merged into their end metrics (``metrics()['resilience']``)."""

    def __init__(self):
        self.counts = {k: 0 for k in RESILIENCE_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown resilience counter {name!r}; add it to "
                f"RESILIENCE_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        return dict(self.counts)

    @property
    def any_faults(self) -> bool:
        return any(self.counts.values())


#: counter names surfaced under ``metrics()['integrity']`` by the
#: elastic device-fault tier (parallel/elastic.ElasticRunner +
#: runtime/integrity) — the device-loss / silent-data-corruption
#: scorecard of a sharded solve
INTEGRITY_COUNTERS = (
    "chunks_run",               # sentinel-checked chunk dispatches
    "sentinel_trips",           # in-jit sentinel tripped (nonfinite /
                                # residual / operand-checksum drift)
    "scrub_runs",               # shadow re-executions performed
    "scrub_mismatches",         # shadow checksum disagreed w/ primary
    "sdc_detected",             # injected corruptions caught (trip or
                                # scrub — counted once per injection)
    "detection_latency_chunks",  # chunks from injection to detection
                                # (sum over detected corruptions)
    "snapshot_restores",        # state restored from a CRC'd chunk-
                                # boundary snapshot (ladder rung 1)
    "elastic_shrinks",          # exact-restore shrinks onto survivors
    "repartitions",             # partition/boundary/exchange re-plans
    "cold_repacks",             # full rebuild + replay (ladder floor)
    "devices_lost",             # mesh devices dropped by faults
    "snapshots_saved",          # chunk-boundary snapshots written
)


class IntegrityCounters:
    """Device-fault-tier counters collected by the elastic driver and
    merged into its end metrics (``metrics()['integrity']``)."""

    def __init__(self):
        self.counts = {k: 0 for k in INTEGRITY_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown integrity counter {name!r}; add it to "
                f"INTEGRITY_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        return dict(self.counts)

    @property
    def any_faults(self) -> bool:
        return any(self.counts[k] for k in (
            "sentinel_trips", "scrub_mismatches", "sdc_detected",
            "elastic_shrinks", "cold_repacks", "devices_lost",
        ))


#: counter names surfaced under metrics["batch"] by the batched solve
#: engine (pydcop_tpu.batch.engine.BatchEngine.counters) — one schema
#: for the library API, the in-process CLI runner and the bench
BATCH_COUNTERS = (
    "instances_enqueued",     # items handed to BatchEngine.solve
    "instances_solved",
    "instances_converged",    # converged before the cycle limit
    "buckets_formed",
    "compile_hits",           # in-memory runner-cache hits
    "compile_misses",         # runners traced+compiled this process
    "fallback_sequential",    # algos outside the vmap set, solved 1-by-1
    "padded_cells",           # stacked array cells holding padding
    "stacked_cells",          # total stacked array cells
    "lanes_nonfinite",        # lanes frozen ERROR on the device-side
                              # NaN/Inf check at a chunk boundary
)


class BatchCounters:
    """Batched-solve counters collected by the BatchEngine and merged
    into its run summary (``BatchEngine.metrics()``)."""

    def __init__(self):
        self.counts = {k: 0 for k in BATCH_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown batch counter {name!r}; add it to "
                f"BATCH_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        return dict(self.counts)

    @property
    def padding_waste(self) -> float:
        total = self.counts["stacked_cells"]
        return self.counts["padded_cells"] / total if total else 0.0


#: counter names surfaced under ``SolveResult.metrics()["repair"]`` by
#: the warm-repair layer (runtime/repair.WarmRepairController +
#: algorithms/warm) — the fixed-shape mutation scorecard of a live run
REPAIR_COUNTERS = (
    "mutations_applied",          # fixed-shape buffer-write mutations
    "headroom_claimed",           # slots claimed (add variable/factor)
    "headroom_released",          # slots released (remove)
    "headroom_exhausted_repacks",  # ONE counted repack per exhaustion
    "repair_retraces",            # chunk-runner traces caused by
                                  # repairs (0 while headroom holds)
    "time_to_recover_s",          # wall seconds from mutation to the
                                  # re-converged fixed point (float sum)
)


class RepairCounters:
    """Warm-repair counters collected by the repair controller and
    attached to every ``SolveResult`` of a warm engine
    (``metrics()['repair']``).  ``time_to_recover_s`` accumulates float
    seconds; everything else is an integer count."""

    def __init__(self):
        self.counts = {
            k: (0.0 if k == "time_to_recover_s" else 0)
            for k in REPAIR_COUNTERS
        }

    def inc(self, name: str, n=1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown repair counter {name!r}; add it to "
                f"REPAIR_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        out = dict(self.counts)
        out["time_to_recover_s"] = round(out["time_to_recover_s"], 6)
        return out


#: counter names surfaced under ``metrics()["serve"]`` by the
#: continuous-batching solve service (pydcop_tpu.serve.SolveService) —
#: the admission/slot-reuse scorecard of a serving session, alongside
#: the per-sweep BatchCounters
SERVE_COUNTERS = (
    "jobs_submitted",         # jobs accepted by SolveService.submit
    "jobs_admitted",          # jobs placed into a bucket lane
    "jobs_completed",
    "jobs_preempted",         # deadline-expired jobs evicted from lanes
    "jobs_resumed",           # jobs restored from a journal checkpoint
    "jobs_fallback",          # algos outside the vmap set, solved 1-by-1
    "lanes_reused",           # admissions into a lane a prior job freed
    "midflight_admissions",   # admissions into an already-running bucket
    "buckets_opened",
    "buckets_merged",         # under-filled same-signature buckets folded
    "buckets_closed",
    "deadline_shrunk_lanes",  # lane-chunks clamped for deadline pressure
    "prewarmed_runners",      # runners scheduled for ahead-of-arrival compile
    "prewarm_skipped_exact",  # predicted configs outside the vmap set
                              # (e.g. the frontier exact-search arm):
                              # nothing to prewarm, solved 1-by-1 later
    "checkpoints_saved",      # per-lane chunk-boundary snapshots written
    # -- fault isolation / overload (ISSUE 7): the alerting surface of
    # a production service — docs/serving.rst "Failure model"
    "scheduler_restarts",     # supervisor relaunches of the tick loop
    "buckets_failed",         # bucket workers torn down by a step exception
    "jobs_retried",           # quarantine retry re-admissions (with backoff)
    "jobs_quarantined",       # poison jobs escalated to sequential fallback
    "lanes_nan",              # non-finite lane detections (state or cost)
    "jobs_shed",              # overload rejections + displaced pending jobs
    "quota_rejections",       # submits rejected by the per-tenant quota
    "ticks_stalled",          # injected stall_tick faults absorbed
    "faults_injected",        # serve fault-plan faults fired (any kind)
    "events_dropped",         # per-job stream events dropped (slow consumer)
    "torn_journal_lines",     # journal lines skipped as torn on resume
    "journal_compactions",    # jobs.jsonl compaction rewrites
)


class ServeCounters:
    """Continuous-batching service counters collected by the
    SolveService scheduler and merged into its run summary
    (``SolveService.metrics()['serve']``).

    ``replica`` labels which fleet replica this service is (None for a
    standalone service); it rides the summary so failover paths are
    auditable post-hoc — every per-job ``metrics()["serve"]`` names the
    replica that actually served it."""

    def __init__(self, replica: Optional[str] = None):
        self.replica = replica
        self.counts = {k: 0 for k in SERVE_COUNTERS}
        #: per-tenant share of ``events_dropped`` (ISSUE 12 satellite):
        #: the flat counter says the SERVICE lost stream events, this
        #: says WHOSE — the twin's SLO scorecard charges a lossy gold
        #: stream against gold attainment, which needs the attribution
        self.events_dropped_by_tenant: dict = {}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown serve counter {name!r}; add it to "
                f"SERVE_COUNTERS"
            )
        self.counts[name] += n

    def drop_event(self, tenant: Optional[str], n: int = 1) -> None:
        """Count one dropped stream event against its tenant (and the
        flat ``events_dropped`` total)."""
        self.inc("events_dropped", n)
        t = tenant or "default"
        self.events_dropped_by_tenant[t] = (
            self.events_dropped_by_tenant.get(t, 0) + n
        )

    def as_dict(self) -> dict:
        out = dict(self.counts)
        out["replica"] = self.replica
        out["events_dropped_by_tenant"] = dict(
            self.events_dropped_by_tenant
        )
        return out


#: counter names surfaced under ``SolveFleet.metrics()['fleet']`` by
#: the replicated solve fleet (pydcop_tpu.serve.fleet) — the routing /
#: failover / recovery scorecard of a fleet session, alongside each
#: replica's own ServeCounters
FLEET_COUNTERS = (
    "jobs_routed",             # jobs placed on a replica by the router
    "jobs_routed_warm",        # placements onto an already-warm replica
    "jobs_reseated",           # failover re-seats onto a peer replica
    "reseat_checkpoint_hits",  # re-seats restored from a lane checkpoint
    "reseat_cold_restarts",    # re-seats replayed from cycle 0
    "replicas_up",             # replicas brought up (initial + later)
    "replicas_down",           # replicas declared dead (kill / crash)
    "replicas_stalled",        # replicas with a stale heartbeat
    "replicas_healed",         # stalled/partitioned replicas recovered
    "replicas_partitioned",    # replicas made unreachable for placement
    "jobs_shed",               # fleet-level admission rejections
    "quota_rejections",        # fleet-level per-tenant quota rejections
    "faults_injected",         # fleet fault-plan faults fired
    "journal_torn_lines",      # torn fleet-journal lines skipped on load
    "recoveries_completed",    # replica losses fully recovered (RTO set)
    "devices_lost",            # mesh devices lost by replicas
                               # (kill_device faults with a replica)
    "capacity_reduced",        # reduced-capacity advertisements pushed
                               # to the router after device loss
    "replicas_relaunched",     # dead replica PROCESSES respawned by the
                               # process fleet's backoff relauncher
    "socket_partitions",       # journal-socket partitions injected
                               # (partition_socket faults)
    "artifacts_corrupted",     # serialized runner artifacts corrupted
                               # in place (corrupt_artifact faults)
    "memo_shared",             # solution-cache entries broadcast to
                               # peer replicas via the journal stream
)


class FleetCounters:
    """Fleet-level counters collected by the SolveFleet supervisor and
    merged into its run summary (``SolveFleet.metrics()['fleet']``)."""

    def __init__(self):
        self.counts = {k: 0 for k in FLEET_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown fleet counter {name!r}; add it to "
                f"FLEET_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        return dict(self.counts)


#: counter names surfaced under ``metrics()["memo"]`` by the
#: cross-request solution cache (pydcop_tpu.serve.memo.MemoCache) —
#: the hit-taxonomy / invalidation / sharing scorecard of a serving
#: session (docs/serving.rst "Solution cache and warm-start serving")
MEMO_COUNTERS = (
    "hits_exact",              # content-hash exact-duplicate hits
    "hits_variant",            # embedding-matched warm-start hits
    "misses",                  # lookups that found nothing servable
    "inserts",                 # solved jobs added to the cache
    "evicted_lru",             # entries displaced at max_entries
    "expired_ttl",             # entries dropped past their TTL
    "invalidated_churn",       # entries dropped by a churn event
    "variant_rejected_gate",   # candidates refused by the feasibility
                               # gate (shape mismatch / diff too large)
    "variant_cold_fallbacks",  # warm repairs discarded for converging
                               # worse than their seed (never-worse
                               # guarantee: the cold result is served)
    "variant_repacks",         # headroom-exhausted repacks during replay
    "corrupt_skipped",         # CRC-failed npz entries skipped-and-
                               # counted on rehydrate/adopt, never served
    "rehydrated",              # entries restored from disk by resume()
    "adopted",                 # entries adopted from fleet peers via the
                               # journal stream (thread + socket wire)
)


class MemoCounters:
    """Solution-cache counters collected by the MemoCache and merged
    into the serve summary (``SolveService.metrics()['memo']``)."""

    def __init__(self):
        self.counts = {k: 0 for k in MEMO_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown memo counter {name!r}; add it to "
                f"MEMO_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        return dict(self.counts)


#: counter names surfaced under the twin scenario's SLO scorecard
#: (pydcop_tpu.scenario.slo.SloLadder / scenario.twin.TwinRunner) —
#: the degradation ladder's rung audit plus the deadline-attainment
#: tally, emitted as ``slo.*`` events and merged into the scorecard's
#: ``ladder`` section (docs/scenarios.rst "The SLO guardrail ladder")
SLO_COUNTERS = (
    "jobs_scored",            # completions tallied into a tier window
    "deadline_hits",          # FINISHED within the tier deadline
    "deadline_misses",        # TIMEOUT / late / ERROR completions
    "lossy_stream_misses",    # on-time jobs demoted to a miss because
                              # their progress stream dropped events
    "tier_breaches",          # rolling-attainment floor violations seen
    "ladder_escalations",     # rung steps up (breach while below max)
    "ladder_deescalations",   # rung steps down (hysteresis satisfied)
    "bronze_sheds",           # rung-1 admissions refused at the door
    "silver_clamps",          # rung-2 deadline-pressure engagements
    "gold_reroutes",          # rung-3 emptiest-healthy placements
)


class SloCounters:
    """SLO guardrail counters collected by the twin's degradation
    ladder and merged into its scorecard (``slo.*`` events on ws/SSE,
    docs/scenarios.rst)."""

    def __init__(self):
        self.counts = {k: 0 for k in SLO_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown slo counter {name!r}; add it to SLO_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        return dict(self.counts)


#: counter names surfaced under ``SolveResult.metrics()["search"]`` by
#: the frontier-batched exact search driver (search/solver) — the PR 4
#: discipline made auditable: ``scalar_reads`` must equal
#: ``2 * chunks`` in the steady state (one incumbent + one bound
#: scalar per chunk), and every departure from it is a counted spill
#: event, never silent extra traffic
SEARCH_COUNTERS = (
    "chunks",            # device chunk dispatches
    "scalar_reads",      # host-read scalars (2 per chunk steady-state)
    "spill_drains",      # annex drains (the counted host fallback)
    "spill_rows",        # rows pulled host-side across all drains
    "reinjected_rows",   # stashed rows returned to the device
)


class SearchCounters:
    """Host-traffic counters of the frontier search chunk loop,
    merged into ``SolveResult.metrics()['search']``."""

    def __init__(self):
        self.counts = {k: 0 for k in SEARCH_COUNTERS}

    def __getitem__(self, name: str) -> int:
        return self.counts[name]

    def __setitem__(self, name: str, value: int) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown search counter {name!r}; add it to "
                f"SEARCH_COUNTERS"
            )
        self.counts[name] = value

    def as_dict(self) -> dict:
        return dict(self.counts)


#: counter names surfaced under ``SolveResult.metrics()["harness"]`` by
#: the chunked solve harness (algorithms/base.SynchronousTensorSolver.run)
#: — the device-residency scorecard of a solve: how often the host
#: actually blocked on the device and what it paid per chunk
HARNESS_COUNTERS = (
    "chunks_dispatched",        # jitted chunk launches
    "host_sync_count",          # device→host materializations in the loop
    "dispatch_wait_s",          # wall seconds blocked on device results
    "donated_chunks",           # chunks run through a donating runner
    "masked_tail_cycles",       # frozen cycles in fixed-shape tail chunks
    "overshoot_cycles",         # cycles run past the stop (pipelined mode)
    "compile_cache_evictions",  # chunk-runner LRU evictions (cumulative)
)


class HarnessCounters:
    """Host↔device traffic counters collected by the solve harness and
    merged into its result (``SolveResult.metrics()['harness']``).
    ``dispatch_wait_s`` accumulates float seconds; everything else is an
    integer count."""

    def __init__(self):
        self.counts = {
            k: (0.0 if k == "dispatch_wait_s" else 0)
            for k in HARNESS_COUNTERS
        }

    def add(self, name: str, n=1) -> None:
        if name not in self.counts:
            raise KeyError(
                f"unknown harness counter {name!r}; add it to "
                f"HARNESS_COUNTERS"
            )
        self.counts[name] += n

    def as_dict(self) -> dict:
        out = dict(self.counts)
        out["dispatch_wait_s"] = round(out["dispatch_wait_s"], 6)
        return out


#: field names surfaced under ``SolveResult.metrics()["shard"]`` and the
#: ``shard.comm.selected`` event by the sharded engines (parallel/mesh
#: CommPlan.counters) — the partition-quality + collective-path
#: scorecard of a multi-device solve
SHARD_COMM_FIELDS = (
    "mode",                      # dense | compact-exact | compact-stale
    "collective",                # psum | ppermute | none
    "n_shards",
    "boundary_columns",          # compact slab width (real boundary)
    "total_columns",             # dense collective width
    "cut_fraction",              # boundary / factor-touched variables
    "boundary_fraction",         # boundary / all variables
    "bytes_per_cycle_dense",     # per-shard collective payload, dense
    "bytes_per_cycle_compact",   # per-shard payload on the chosen path
    "exchange_rounds",           # ppermute rounds (0 unless ppermute)
    "threshold",                 # auto-policy cut-fraction threshold
)


@dataclasses.dataclass
class ShardCommCounters:
    """Partition quality + per-cycle collective cost of a sharded
    engine (ISSUE 5): which collective path the boundary-compaction
    auto-policy chose and what it pays per cycle vs the dense psum.
    Built by parallel/mesh.CommPlan.counters; surfaced as
    ``SolveResult.metrics()['shard']`` and the ``shard.comm.selected``
    event.

    The separator-sharded DPOP sweep (ISSUE 9) reuses the same shape:
    ``mode="dpop_sep_tiled"``, ``collective="psum_wire"``, a "cycle" is
    one whole UTIL+VALUE sweep, ``boundary_columns``/``total_columns``
    are the pruned vs dense wire entries and ``exchange_rounds`` the
    tree levels (parallel/dpop_mesh.ShardedSepDpop.comm_stats)."""

    mode: str
    collective: str
    n_shards: int
    boundary_columns: int
    total_columns: int
    cut_fraction: float
    boundary_fraction: float
    bytes_per_cycle_dense: int
    bytes_per_cycle_compact: int
    exchange_rounds: int = 0
    threshold: float = 0.5

    def as_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["cut_fraction"] = round(out["cut_fraction"], 6)
        out["boundary_fraction"] = round(out["boundary_fraction"], 6)
        return out

    @property
    def compact_savings(self) -> float:
        """Fraction of dense collective bytes the chosen path avoids."""
        if not self.bytes_per_cycle_dense:
            return 0.0
        return 1.0 - (
            self.bytes_per_cycle_compact / self.bytes_per_cycle_dense
        )


#: the canonical executed-config schema surfaced as
#: ``SolveResult.metrics()["config"]`` — ONE stable label space for
#: the portfolio dataset harness, the ``--auto`` gap audit and log
#: collectors, replacing the per-engine scatter (shard/dpop/harness
#: sections) these knobs used to hide in.  Every solve path fills
#: every key; ``None``/0 mean "not applicable on this path" (e.g.
#: ``i_bound`` outside dpop) and ``"default"`` overlap means the PR 5
#: cut-fraction auto-policy stayed in charge
CONFIG_FIELDS = (
    "algo",                # algorithm name actually executed
    "engine",              # harness | sweep* | pernode | wholesweep |
                           # sharded | minibucket | sharded_mesh |
                           # frontier (anytime exact search)
    "chunk",               # harness chunk size (0 = single-shot path)
    "overlap",             # default | off | exact | stale
    "boundary_threshold",  # PR 5 auto-policy threshold in force
    "dpop_budget_mb",      # per-device util-table budget (0 = caps)
    "i_bound",             # mini-bucket width bound (0 = off)
    "precision",           # storage tier: f32 | bf16 | int8 (ISSUE 19)
)


def resolved_config(
    algo: str,
    engine: str,
    chunk: int = 0,
    overlap: str = "default",
    boundary_threshold: float = 0.5,
    dpop_budget_mb: float = 0.0,
    i_bound: int = 0,
    precision: str = "f32",
) -> dict:
    """Build the canonical config dict (all CONFIG_FIELDS, typed)."""
    return {
        "algo": str(algo),
        "engine": str(engine),
        "chunk": int(chunk),
        "overlap": str(overlap),
        "boundary_threshold": float(boundary_threshold),
        "dpop_budget_mb": float(dpop_budget_mb),
        "i_bound": int(i_bound),
        "precision": str(precision),
    }


#: field names surfaced under ``SolveResult.metrics()["portfolio"]``
#: by ``solve --auto`` (pydcop_tpu.portfolio.select.solve_auto) — the
#: chosen config plus the predicted-vs-actual honesty audit
PORTFOLIO_FIELDS = (
    "config",                       # chosen PortfolioConfig dict
    "fallback",                     # True = no model, hand heuristics
    "model",                        # model path / provenance, or None
    "predicted_norm_time",          # model's drift-normalized estimate
    "predicted_time_to_target_s",   # ... / calibration probe rate
    "actual_solve_s",               # measured wall of this solve
    "actual_norm_time",             # wall x calibration probe rate
    "gap_s",                        # actual - predicted (model only)
    "gap_ratio",                    # actual / predicted (model only)
    "n_feasible",                   # grid cells scored
    "n_masked",                     # grid cells feasibility-masked
    "masked",                       # first few (cell key, reason)
)


class StatsLogger:
    """Accumulate per-cycle rows and dump them as CSV (reference:
    trace_computation, stats.py:81)."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.rows: List[dict] = []

    def trace_cycle(self, computation: str, cycle: int, tensors,
                    cost: Optional[float] = None, msg_count: int = 0,
                    timestamp: float = 0.0) -> None:
        if not self.enabled:
            return
        op_count, nc_op_count = cycle_op_counts(tensors)
        self.rows.append(
            {
                "timestamp": timestamp,
                "computation": computation,
                "cycle": cycle,
                "op_count": op_count,
                "nc_op_count": nc_op_count,
                "msg_count": msg_count,
                "cost": cost,
            }
        )

    def dump(self, path: str) -> None:
        with open(path, "w", newline="", encoding="utf-8") as f:
            w = csv.DictWriter(f, fieldnames=COLUMNS)
            w.writeheader()
            for row in self.rows:
                w.writerow(row)
