from pydcop_tpu.dcop.objects import (
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableDomain,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    create_agents,
    create_variables,
)
from pydcop_tpu.dcop.relations import (
    AsNAryFunctionRelation,
    Constraint,
    NAryFunctionRelation,
    NAryMatrixRelation,
    RelationProtocol,
    UnaryBooleanRelation,
    UnaryFunctionRelation,
    ZeroAryRelation,
    assignment_cost,
    constraint_from_str,
    find_arg_optimal,
    find_optimum,
    join,
    projection,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.scenario import DcopEvent, EventAction, Scenario
from pydcop_tpu.dcop.yamldcop import (
    DistributionHints,
    dcop_yaml,
    load_dcop,
    load_dcop_from_file,
    load_scenario,
    load_scenario_from_file,
    yaml_agents,
    yaml_scenario,
)

__all__ = [
    "AgentDef", "BinaryVariable", "Domain", "ExternalVariable", "Variable",
    "VariableDomain", "VariableNoisyCostFunc", "VariableWithCostDict",
    "VariableWithCostFunc", "create_agents", "create_variables",
    "AsNAryFunctionRelation", "Constraint", "NAryFunctionRelation",
    "NAryMatrixRelation", "RelationProtocol", "UnaryBooleanRelation",
    "UnaryFunctionRelation", "ZeroAryRelation", "assignment_cost",
    "constraint_from_str", "find_arg_optimal", "find_optimum", "join",
    "projection", "DCOP", "DcopEvent", "EventAction", "Scenario",
    "DistributionHints", "dcop_yaml", "load_dcop", "load_dcop_from_file",
    "load_scenario", "load_scenario_from_file", "yaml_agents", "yaml_scenario",
]
