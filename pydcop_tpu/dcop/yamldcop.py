"""YAML problem format, compatible with the reference's format.

Equivalent capability to the reference's pydcop/dcop/yamldcop.py
(load_dcop_from_file :63, load_dcop :93, dcop_yaml :116, _build_constraints
:214, _build_agents :305, load_scenario_from_file :493).

Format summary (see reference docs for the full spec):

* ``domains``: name → {values, type?, initial_value?}; ``values`` may be a
  range string like ``[0 .. 9]``.
* ``variables``: name → {domain, initial_value?, cost_function?, noise_level?}.
* ``external_variables``: like variables, with an ``initial_value``.
* ``constraints``: name → {type: intention, function: <python expr>} or
  {type: extensional, variables: [..], default?, values: {cost: "tok tok |
  tok tok"}}.
* ``agents``: list of names or map name → {capacity?, ...extras}; top-level
  ``routes`` / ``hosting_costs`` sections with ``default`` entries.
* ``distribution_hints``: {must_host: {agent: [computations]}}.
"""
from __future__ import annotations

import os
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Union

import yaml

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostFunc,
)
from pydcop_tpu.dcop.relations import (
    Constraint,
    NAryMatrixRelation,
    assignment_matrix,
    constraint_from_str,
    generate_assignment_as_dict,
)
from pydcop_tpu.dcop.scenario import Scenario, DcopEvent, EventAction
from pydcop_tpu.utils.expressions import ExpressionFunction


class DcopInvalidFormatError(Exception):
    pass


class DistributionHints:
    """Placement hints from the problem file (reference:
    pydcop/distribution/objects.py DistributionHints)."""

    def __init__(self, must_host: Optional[Dict[str, List[str]]] = None,
                 host_with: Optional[Dict[str, List[str]]] = None):
        self._must_host = {k: list(v) for k, v in (must_host or {}).items()}
        self._host_with = {k: list(v) for k, v in (host_with or {}).items()}

    def must_host(self, agent_name: str) -> List[str]:
        return list(self._must_host.get(agent_name, []))

    def host_with(self, computation_name: str) -> List[str]:
        return list(self._host_with.get(computation_name, []))

    @property
    def must_host_map(self) -> Dict[str, List[str]]:
        return {k: list(v) for k, v in self._must_host.items()}


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def load_dcop_from_file(filenames: Union[str, Iterable[str]]) -> DCOP:
    """Load a DCOP from one or several YAML files (concatenated)."""
    if isinstance(filenames, str):
        filenames = [filenames]
    content = ""
    for fn in filenames:
        with open(os.path.expanduser(fn), encoding="utf-8") as f:
            content += f.read() + "\n"
    return load_dcop(content)


def load_dcop(dcop_str: str) -> DCOP:
    loaded = yaml.safe_load(dcop_str)
    if not loaded:
        raise DcopInvalidFormatError("Empty DCOP definition")
    if not isinstance(loaded, dict) or not loaded.get("variables"):
        raise DcopInvalidFormatError(
            "Invalid DCOP definition: no 'variables' section"
        )
    dcop = DCOP(
        name=loaded.get("name", "dcop"),
        objective=loaded.get("objective", "min"),
        description=loaded.get("description", ""),
    )
    domains = _build_domains(loaded)
    for d in domains.values():
        dcop.add_domain(d)
    for v in _build_variables(loaded, domains).values():
        dcop.add_variable(v)
    for ev in _build_external_variables(loaded, domains).values():
        dcop.add_variable(ev)
    for c in _build_constraints(loaded, dcop).values():
        dcop.add_constraint(c)
    dcop.add_agents(_build_agents(loaded))
    dcop.dist_hints = _build_dist_hints(loaded)
    return dcop


def str_2_domain_values(values_str: str) -> List:
    """Parse a range domain string like ``'0 .. 9'`` or ``'[0 .. 9]'``."""
    s = values_str.strip().strip("[]")
    lo, hi = (part.strip() for part in s.split(".."))
    return list(range(int(lo), int(hi) + 1))


def _build_domains(loaded) -> Dict[str, Domain]:
    domains = {}
    for name, d in (loaded.get("domains") or {}).items():
        values = d["values"]
        if len(values) == 1 and isinstance(values[0], str) and ".." in values[0]:
            values = str_2_domain_values(values[0])
        domains[name] = Domain(name, d.get("type", ""), values)
    return domains


def _variable_common(name, v, domains):
    try:
        domain = domains[v["domain"]]
    except KeyError:
        raise DcopInvalidFormatError(
            f"Unknown domain {v.get('domain')!r} for variable {name}"
        )
    initial_value = v.get("initial_value")
    if initial_value is not None and initial_value not in domain:
        raise DcopInvalidFormatError(
            f"initial value {initial_value!r} not in domain {domain.name} "
            f"for variable {name}"
        )
    return domain, initial_value


def _build_variables(loaded, domains) -> Dict[str, Variable]:
    variables = {}
    for name, v in (loaded.get("variables") or {}).items():
        domain, initial_value = _variable_common(name, v, domains)
        if "cost_function" in v:
            cost_func = ExpressionFunction(str(v["cost_function"]))
            if "noise_level" in v:
                variables[name] = VariableNoisyCostFunc(
                    name, domain, cost_func, initial_value,
                    noise_level=v["noise_level"],
                )
            else:
                variables[name] = VariableWithCostFunc(
                    name, domain, cost_func, initial_value
                )
        else:
            variables[name] = Variable(name, domain, initial_value)
    return variables


def _build_external_variables(loaded, domains) -> Dict[str, ExternalVariable]:
    ext = {}
    for name, v in (loaded.get("external_variables") or {}).items():
        domain, initial_value = _variable_common(name, v, domains)
        ext[name] = ExternalVariable(name, domain, initial_value)
    return ext


def _build_constraints(loaded, dcop: DCOP) -> Dict[str, Constraint]:
    constraints = {}
    all_vars = dcop.all_variables
    for name, c in (loaded.get("constraints") or {}).items():
        ctype = c.get("type")
        if ctype == "intention":
            constraints[name] = constraint_from_str(
                name, str(c["function"]), all_vars
            )
        elif ctype == "extensional":
            constraints[name] = _build_extensional(name, c, dcop)
        elif ctype == "structured":
            constraints[name] = _build_structured(name, c, dcop)
        else:
            raise DcopInvalidFormatError(
                f"Constraint {name}: unknown type {ctype!r} "
                "(must be 'intention', 'extensional' or 'structured')"
            )
    return constraints


def _lookup_var(dcop: DCOP, name: str) -> Variable:
    if name in dcop.variables:
        return dcop.variables[name]
    if name in dcop.external_variables:
        return dcop.external_variables[name]
    raise DcopInvalidFormatError(f"Unknown variable {name!r} in constraint")


def _build_extensional(name, c, dcop: DCOP) -> NAryMatrixRelation:
    var_names = c["variables"]
    if isinstance(var_names, str):
        var_names = [var_names]
    variables = [_lookup_var(dcop, vn) for vn in var_names]
    default = c.get("default", 0)
    matrix = assignment_matrix(variables, default)
    values_def = c.get("values") or {}
    for cost, assignments_def in values_def.items():
        cost = float(cost)
        if len(variables) == 1:
            dom = variables[0].domain
            tokens = (
                [t.strip() for t in assignments_def.split("|")]
                if isinstance(assignments_def, str)
                else [assignments_def]
            )
            for tok in tokens:
                matrix[dom.index(dom.to_domain_value(tok))] = cost
        else:
            for combo in str(assignments_def).split("|"):
                tokens = combo.split()
                if len(tokens) != len(variables):
                    raise DcopInvalidFormatError(
                        f"Constraint {name}: assignment {combo!r} does not "
                        f"match variables {var_names}"
                    )
                idx = tuple(
                    v.domain.index(v.domain.to_domain_value(t))
                    for v, t in zip(variables, tokens)
                )
                matrix[idx] = cost
    return NAryMatrixRelation(variables, matrix, name)


def _build_structured(name, c, dcop: DCOP):
    """``type: structured`` constraints round-trip by PARAMETERS — the
    closed-form classes of pydcop_tpu.dcop.structured — never through a
    densified table (a 100-arity resource rule has no D^100 table to
    write)."""
    from pydcop_tpu.dcop.structured import structured_from_params

    var_names = c["variables"]
    if isinstance(var_names, str):
        var_names = [var_names]
    variables = [_lookup_var(dcop, vn) for vn in var_names]
    try:
        return structured_from_params(name, variables, c.get("params") or {})
    except (KeyError, ValueError) as e:
        raise DcopInvalidFormatError(
            f"Constraint {name}: invalid structured parameters ({e})"
        ) from None


def _build_agents(loaded) -> Dict[str, AgentDef]:
    agents_attrs: Dict[str, Dict] = {}
    agents_loaded = loaded.get("agents") or {}
    if isinstance(agents_loaded, list):
        agents_attrs = {a: {} for a in agents_loaded}
    else:
        for a_name, kw in agents_loaded.items():
            agents_attrs[a_name] = dict(kw) if kw else {}

    default_route = 1
    routes: Dict[str, Dict[str, float]] = defaultdict(dict)
    for a1, a1_routes in (loaded.get("routes") or {}).items():
        if a1 == "default":
            default_route = a1_routes
            continue
        if a1 not in agents_attrs:
            raise DcopInvalidFormatError(f"Route for unknown agent {a1}")
        for a2, cost in a1_routes.items():
            if a2 not in agents_attrs:
                raise DcopInvalidFormatError(f"Route for unknown agent {a2}")
            existing = routes.get(a1, {}).get(a2, routes.get(a2, {}).get(a1))
            if existing is not None and existing != cost:
                raise DcopInvalidFormatError(
                    f"Conflicting route definitions for ({a1}, {a2})"
                )
            routes[a1][a2] = cost
            routes[a2][a1] = cost

    default_hosting = 0
    agent_default_hosting: Dict[str, float] = {}
    hosting: Dict[str, Dict[str, float]] = defaultdict(dict)
    for a, costs in (loaded.get("hosting_costs") or {}).items():
        if a == "default":
            default_hosting = costs
            continue
        if a not in agents_attrs:
            raise DcopInvalidFormatError(f"hosting_costs for unknown agent {a}")
        if "default" in costs:
            agent_default_hosting[a] = costs["default"]
        for comp, cost in (costs.get("computations") or {}).items():
            hosting[a][comp] = cost

    agents = {}
    for a, attrs in agents_attrs.items():
        agents[a] = AgentDef(
            a,
            default_hosting_cost=agent_default_hosting.get(a, default_hosting),
            hosting_costs=hosting.get(a, {}),
            default_route=default_route,
            routes=routes.get(a, {}),
            **attrs,
        )
    return agents


def _build_dist_hints(loaded) -> Optional[DistributionHints]:
    if "distribution_hints" not in loaded:
        return None
    hints = loaded["distribution_hints"] or {}
    return DistributionHints(
        must_host=hints.get("must_host"), host_with=hints.get("host_with")
    )


# ---------------------------------------------------------------------------
# Dumping
# ---------------------------------------------------------------------------


def dcop_yaml(dcop: DCOP) -> str:
    """Serialize a DCOP back to the YAML format."""
    out: Dict[str, Any] = {
        "name": dcop.name,
        "objective": dcop.objective,
    }
    if dcop.description:
        out["description"] = dcop.description
    out["domains"] = {
        d.name: {"values": list(d.values), "type": d.type}
        for d in dcop.domains.values()
    }
    variables = {}
    for v in dcop.variables.values():
        vd: Dict[str, Any] = {"domain": v.domain.name}
        if v.initial_value is not None:
            vd["initial_value"] = v.initial_value
        if isinstance(v, VariableWithCostFunc) and isinstance(
            v.cost_func, ExpressionFunction
        ):
            vd["cost_function"] = v.cost_func.expression
        if isinstance(v, VariableNoisyCostFunc):
            vd["noise_level"] = v.noise_level
        variables[v.name] = vd
    out["variables"] = variables
    if dcop.external_variables:
        out["external_variables"] = {
            v.name: {"domain": v.domain.name, "initial_value": v.value}
            for v in dcop.external_variables.values()
        }
    out["constraints"] = {
        c.name: _constraint_yaml(c) for c in dcop.constraints.values()
    }
    out["agents"] = {
        a.name: ({"capacity": a.capacity} if a.capacity is not None else {})
        for a in dcop.agents.values()
    }
    return yaml.dump(out, default_flow_style=False, sort_keys=False)


def _constraint_yaml(c: Constraint) -> Dict:
    from pydcop_tpu.dcop.structured import StructuredConstraint

    if isinstance(c, StructuredConstraint):
        # structure-preserving: parameters, never a densified table
        # (silent densification used to make structured instances
        # explode — or simply hang — at dump time)
        return {
            "type": "structured",
            "variables": c.scope_names,
            "params": c.params(),
        }
    expr = getattr(c, "expression", None)
    if expr is not None:
        return {"type": "intention", "function": expr}
    # dump as extensional table, grouping assignments by cost
    by_cost: Dict[float, List[str]] = defaultdict(list)
    for assignment in generate_assignment_as_dict(c.dimensions):
        val = c.get_value_for_assignment(assignment)
        tokens = " ".join(str(assignment[v.name]) for v in c.dimensions)
        by_cost[val].append(tokens)
    return {
        "type": "extensional",
        "variables": c.scope_names,
        "values": {cost: " | ".join(toks) for cost, toks in by_cost.items()},
    }


def yaml_agents(agents: Iterable[AgentDef]) -> str:
    """Serialize agents (+hosting costs & routes) to YAML."""
    agents = list(agents)
    out: Dict[str, Any] = {
        "agents": {
            a.name: {"capacity": a.capacity, **a.extra_attrs} for a in agents
        }
    }
    routes: Dict[str, Any] = {}
    hosting: Dict[str, Any] = {}
    for a in agents:
        if a.routes:
            routes[a.name] = a.routes
        hc: Dict[str, Any] = {}
        if a.default_hosting_cost:
            hc["default"] = a.default_hosting_cost
        if a.hosting_costs:
            hc["computations"] = a.hosting_costs
        if hc:
            hosting[a.name] = hc
    if routes:
        out["routes"] = routes
    if hosting:
        out["hosting_costs"] = hosting
    return yaml.dump(out, default_flow_style=False, sort_keys=False)


def load_agents_from_file(filename: str) -> Dict[str, AgentDef]:
    with open(os.path.expanduser(filename), encoding="utf-8") as f:
        return _build_agents(yaml.safe_load(f.read()))


# ---------------------------------------------------------------------------
# Scenario
# ---------------------------------------------------------------------------


def load_scenario_from_file(filename: str) -> Scenario:
    with open(os.path.expanduser(filename), encoding="utf-8") as f:
        return load_scenario(f.read())


def load_scenario(scenario_str: str) -> Scenario:
    loaded = yaml.safe_load(scenario_str)
    if loaded is None:  # empty file = empty scenario, not a crash
        loaded = {}
    events = []
    for e in loaded.get("events", []):
        if "delay" in e:
            events.append(DcopEvent(e.get("id", "delay"), delay=e["delay"]))
        else:
            actions = [
                EventAction(a["type"], **{k: v for k, v in a.items() if k != "type"})
                for a in e.get("actions", [])
            ]
            events.append(DcopEvent(e.get("id", ""), actions=actions))
    return Scenario(events)


def yaml_scenario(scenario: Scenario) -> str:
    events = []
    for e in scenario.events:
        if e.is_delay:
            events.append({"id": e.id, "delay": e.delay})
        else:
            events.append(
                {
                    "id": e.id,
                    "actions": [
                        {"type": a.type, **a.parameters} for a in e.actions
                    ],
                }
            )
    return yaml.dump({"events": events}, default_flow_style=False, sort_keys=False)
