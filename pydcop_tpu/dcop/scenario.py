"""Dynamic-DCOP scenarios: timed event streams.

Equivalent capability to the reference's pydcop/dcop/scenario.py
(EventAction :37, DcopEvent :55, Scenario :95).  Events either wait
(``delay``) or perform actions (``add_agent``, ``remove_agent``, external
variable changes) against the running system.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from pydcop_tpu.utils.serialization import SimpleRepr


class EventAction(SimpleRepr):
    """One action of a scenario event, e.g. remove_agent(agent='a1')."""

    def __init__(self, action_type: str, **parameters):
        self._action_type = action_type
        self._parameters = dict(parameters)

    @property
    def type(self) -> str:
        return self._action_type

    @property
    def parameters(self) -> Dict:
        return dict(self._parameters)

    def __repr__(self):
        return f"EventAction({self._action_type!r}, {self._parameters})"

    def _simple_repr(self):
        from pydcop_tpu.utils.serialization import REPR_MODULE, REPR_QUALNAME
        return {REPR_MODULE: type(self).__module__,
                REPR_QUALNAME: type(self).__qualname__,
                "action_type": self._action_type,
                **self._parameters}

    @classmethod
    def _from_repr(cls, r):
        from pydcop_tpu.utils.serialization import REPR_MODULE, REPR_QUALNAME
        kw = {k: v for k, v in r.items()
              if k not in (REPR_MODULE, REPR_QUALNAME, "action_type")}
        return cls(r["action_type"], **kw)


class DcopEvent(SimpleRepr):
    """A scenario event: either a delay or a list of actions."""

    def __init__(
        self,
        event_id: str,
        delay: Optional[float] = None,
        actions: Optional[List[EventAction]] = None,
    ):
        self._event_id = event_id
        self._delay = delay
        self._actions = list(actions) if actions else []

    @property
    def id(self) -> str:
        return self._event_id

    @property
    def is_delay(self) -> bool:
        return self._delay is not None

    @property
    def delay(self) -> Optional[float]:
        return self._delay

    @property
    def actions(self) -> List[EventAction]:
        return list(self._actions)

    def __repr__(self):
        if self.is_delay:
            return f"DcopEvent({self._event_id!r}, delay={self._delay})"
        return f"DcopEvent({self._event_id!r}, {self._actions})"


def churn_scenario(
    dcop,
    n_events: int,
    seed: int = 0,
    delay: float = 0.2,
    kinds: Optional[Iterable[str]] = None,
) -> "Scenario":
    """A seeded churn stream over a live DCOP (ISSUE 8): ``n_events``
    mutation events separated by ``delay`` solving phases, each a
    seeded choice among ``kinds`` (default: factor edits + agent
    remove/add — the sustained-mutation workload of the warm-repair
    bench leg and ``make churn-smoke``).  Same (dcop, seed) → same
    stream, so a killed run can replay it deterministically.

    ``change_factor`` events perturb a seeded constraint's table
    through the same :func:`pydcop_tpu.runtime.repair.
    perturbed_constraint` jitter the ``edit_factor`` fault kind uses
    (routed here as an expression-less action the orchestrator resolves
    at apply time via the ``seed`` parameter).
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    kinds = tuple(kinds) if kinds else (
        "change_factor", "change_factor", "remove_agent", "add_agent",
    )
    events: List[DcopEvent] = []
    alive = sorted(dcop.agents)
    added = 0
    constraints = sorted(dcop.constraints)
    for i in range(n_events):
        events.append(DcopEvent(f"churn_d{i}", delay=delay))
        kind = kinds[int(rng.integers(len(kinds)))]
        if kind == "remove_agent" and len(alive) > 1:
            a = alive.pop(int(rng.integers(len(alive))))
            act = EventAction("remove_agent", agent=a)
        elif kind == "add_agent":
            added += 1
            name = f"churn_agent_{added:03d}"
            alive.append(name)
            act = EventAction("add_agent", agent=name)
        else:
            c = constraints[int(rng.integers(len(constraints)))]
            act = EventAction(
                "change_factor", constraint=c, seed=int(seed) + i,
            )
        events.append(DcopEvent(f"churn_e{i}", actions=[act]))
    events.append(DcopEvent("churn_final", delay=delay))
    return Scenario(events)


class Scenario(SimpleRepr):
    """An ordered stream of events applied to a running dynamic DCOP."""

    def __init__(self, events: Optional[Iterable[DcopEvent]] = None):
        self._events = list(events) if events else []

    @property
    def events(self) -> List[DcopEvent]:
        return list(self._events)

    def add_event(self, event: DcopEvent) -> "Scenario":
        self._events.append(event)
        return self

    def __iter__(self):
        return iter(self._events)

    def __len__(self):
        return len(self._events)

    def __repr__(self):
        return f"Scenario({len(self._events)} events)"
