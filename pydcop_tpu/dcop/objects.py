"""Problem-model objects: domains, variables, agent definitions.

Equivalent capability to the reference's pydcop/dcop/objects.py
(Domain :46, Variable :175, BinaryVariable :335, VariableWithCostDict :410,
VariableWithCostFunc :464, VariableNoisyCostFunc :547, ExternalVariable :618,
AgentDef :669, create_variables :258, create_agents :879).

TPU-first design notes:

* A :class:`Domain` knows its integer index space — every variable value is
  ultimately an index into a padded value axis of a cost tensor; helpers
  ``index``/``to_value`` are the only bridge between python values and device
  arrays.
* Variable costs expose :meth:`Variable.cost_vector` returning a dense
  per-value numpy vector, ready to be stacked into the ``[V, D]`` unary-cost
  array consumed by the kernels (`pydcop_tpu.ops.compile`).
* Noise for ``VariableNoisyCostFunc`` is drawn from a per-variable-name
  deterministic PRNG so runs are reproducible on device and host
  (documented deviation: the reference seeds from the global RNG).
"""
from __future__ import annotations

import hashlib
from itertools import product
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np

from pydcop_tpu.utils.expressions import ExpressionFunction
from pydcop_tpu.utils.serialization import SimpleRepr


class Domain(SimpleRepr):
    """A named, ordered, finite set of values.

    >>> d = Domain('colors', 'color', ['R', 'G', 'B'])
    >>> len(d)
    3
    >>> d.index('G')
    1
    >>> d[2]
    'B'
    >>> 'R' in d
    True
    """

    def __init__(self, name: str, domain_type: str, values: Iterable):
        self._name = name
        self._domain_type = domain_type
        self._values = tuple(values)
        self._index = {v: i for i, v in enumerate(self._values)}

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._domain_type

    @property
    def values(self) -> Tuple:
        return self._values

    def index(self, value) -> int:
        try:
            return self._index[value]
        except KeyError:
            raise ValueError(f"{value!r} is not in domain {self._name}")

    def to_domain_value(self, token: str):
        """Map a string token (e.g. from YAML/CLI) to the domain value.

        Accepts the exact value, or its string form (so '1' matches int 1).
        """
        if token in self._index:
            return token
        for v in self._values:
            if str(v) == str(token):
                return v
        raise ValueError(f"{token!r} does not match any value of domain {self._name}")

    def __len__(self):
        return len(self._values)

    def __iter__(self):
        return iter(self._values)

    def __getitem__(self, i):
        return self._values[i]

    def __contains__(self, v):
        return v in self._index

    def __eq__(self, other):
        return (
            isinstance(other, Domain)
            and self._name == other._name
            and self._values == other._values
            and self._domain_type == other._domain_type
        )

    def __hash__(self):
        return hash((self._name, self._domain_type, self._values))

    def __repr__(self):
        return f"Domain({self._name!r}, {self._domain_type!r}, {list(self._values)!r})"


# Reference alias (pydcop/dcop/objects.py keeps VariableDomain as legacy name)
VariableDomain = Domain

binary_domain = Domain("binary", "binary", [0, 1])


class Variable(SimpleRepr):
    """A decision variable over a finite domain.

    >>> v = Variable('v1', Domain('d', 'd', [0, 1, 2]))
    >>> v.name
    'v1'
    >>> v.cost_for_val(2)
    0
    """

    has_cost = False

    def __init__(self, name: str, domain: Domain, initial_value=None):
        self._name = name
        self._domain = domain
        if initial_value is not None and initial_value not in domain:
            raise ValueError(
                f"initial value {initial_value!r} not in domain {domain.name}"
            )
        self._initial_value = initial_value

    @property
    def name(self) -> str:
        return self._name

    @property
    def domain(self) -> Domain:
        return self._domain

    @property
    def initial_value(self):
        return self._initial_value

    def cost_for_val(self, val) -> float:
        return 0

    def cost_vector(self) -> np.ndarray:
        """Dense per-value cost vector (aligned with domain order)."""
        return np.array([self.cost_for_val(v) for v in self._domain], dtype=np.float32)

    def clone(self, new_name: Optional[str] = None) -> "Variable":
        return Variable(new_name or self._name, self._domain, self._initial_value)

    def __eq__(self, other):
        return (
            type(other) is type(self)
            and self._name == other.name
            and self._domain == other.domain
        )

    def __hash__(self):
        return hash((type(self).__name__, self._name, self._domain))

    def __repr__(self):
        return f"{type(self).__name__}({self._name!r}, {self._domain.name})"


class BinaryVariable(Variable):
    """A 0/1 variable (used by the repair-DCOP builders, reference
    pydcop/dcop/objects.py:335)."""

    def __init__(self, name: str, initial_value=0):
        super().__init__(name, binary_domain, initial_value)

    def clone(self, new_name: Optional[str] = None) -> "BinaryVariable":
        return BinaryVariable(new_name or self._name, self._initial_value)


class VariableWithCostDict(Variable):
    """Variable with an explicit per-value cost table."""

    has_cost = True

    def __init__(
        self,
        name: str,
        domain: Domain,
        costs: Dict[Any, float],
        initial_value=None,
    ):
        super().__init__(name, domain, initial_value)
        self._costs = dict(costs)

    @property
    def costs(self) -> Dict[Any, float]:
        return dict(self._costs)

    def cost_for_val(self, val) -> float:
        return self._costs.get(val, 0)

    def clone(self, new_name=None):
        return VariableWithCostDict(
            new_name or self._name, self._domain, self._costs, self._initial_value
        )


class VariableWithCostFunc(Variable):
    """Variable whose per-value cost comes from a function of the value."""

    has_cost = True

    def __init__(
        self,
        name: str,
        domain: Domain,
        cost_func: Union[ExpressionFunction, Callable],
        initial_value=None,
    ):
        super().__init__(name, domain, initial_value)
        if isinstance(cost_func, ExpressionFunction):
            vnames = cost_func.variable_names
            if len(vnames) != 1 or name not in vnames:
                raise ValueError(
                    f"cost function for {name} must depend exactly on {name}, "
                    f"got {set(vnames)}"
                )
        self._cost_func = cost_func

    @property
    def cost_func(self):
        return self._cost_func

    def cost_for_val(self, val) -> float:
        if isinstance(self._cost_func, ExpressionFunction):
            return self._cost_func(**{self._name: val})
        return self._cost_func(val)

    def clone(self, new_name=None):
        if new_name and isinstance(self._cost_func, ExpressionFunction):
            raise ValueError(
                "Cannot rename a variable with an expression cost function: "
                "the expression refers to the old name"
            )
        return VariableWithCostFunc(
            new_name or self._name, self._domain, self._cost_func, self._initial_value
        )


def _stable_seed(*parts: str) -> int:
    h = hashlib.sha256("/".join(parts).encode()).digest()
    return int.from_bytes(h[:4], "little")


class VariableNoisyCostFunc(VariableWithCostFunc):
    """Cost function plus small per-value random noise.

    The reference adds uniform noise so MaxSum can break ties between
    symmetric solutions (pydcop/dcop/objects.py:547, used at maxsum.py:449).
    Noise here is deterministic per (variable name, value index), drawn once
    at construction from a name-seeded PRNG — reproducibility matters more
    than entropy for a solver, and it keeps the compiled cost tensors stable
    across processes/hosts.
    """

    has_cost = True

    def __init__(
        self,
        name: str,
        domain: Domain,
        cost_func,
        initial_value=None,
        noise_level: float = 0.02,
    ):
        super().__init__(name, domain, cost_func, initial_value)
        self._noise_level = noise_level
        rng = np.random.default_rng(_stable_seed("noise", name))
        self._noise = rng.uniform(0, noise_level, size=len(domain))

    @property
    def noise_level(self) -> float:
        return self._noise_level

    def cost_for_val(self, val) -> float:
        base = super().cost_for_val(val)
        return base + float(self._noise[self._domain.index(val)])

    def clone(self, new_name=None):
        if new_name and isinstance(self._cost_func, ExpressionFunction):
            raise ValueError("Cannot rename: expression refers to the old name")
        return VariableNoisyCostFunc(
            new_name or self._name,
            self._domain,
            self._cost_func,
            self._initial_value,
            self._noise_level,
        )


class ExternalVariable(Variable):
    """A read-only 'sensor' variable whose value is set from outside the
    optimization (reference: pydcop/dcop/objects.py:618).  Change callbacks
    let dynamic algorithms (maxsum_dynamic) react to new readings."""

    def __init__(self, name: str, domain: Domain, value=None):
        super().__init__(name, domain, value)
        self._value = value if value is not None else domain[0]
        self._callbacks: List[Callable] = []

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, val):
        if val == self._value:
            return
        if val not in self._domain:
            raise ValueError(f"{val!r} not in domain {self._domain.name}")
        self._value = val
        for cb in self._callbacks:
            cb(val)

    def subscribe(self, callback: Callable):
        self._callbacks.append(callback)

    def unsubscribe(self, callback: Callable):
        self._callbacks.remove(callback)

    def clone(self, new_name=None):
        return ExternalVariable(new_name or self._name, self._domain, self._value)


def create_variables(
    name_prefix: str,
    indexes: Union[str, Tuple, Iterable],
    domain: Domain,
    separator: str = "_",
) -> Dict[Union[str, Tuple[str, ...]], Variable]:
    """Batch-create variables over an index space.

    Mirrors the reference helper (pydcop/dcop/objects.py:258):

    * an iterable of names: ``create_variables('x_', ['a1', 'a2'], d)``
      → keys ``'x_a1', 'x_a2'``
    * a tuple of iterables: cartesian product, keys are tuples.

    >>> d = Domain('d', 'd', [0, 1])
    >>> vs = create_variables('v', ['1', '2'], d)
    >>> sorted(vs)
    ['v1', 'v2']
    >>> vs2 = create_variables('m', (['x', 'y'], ['1', '2']), d)
    >>> vs2[('x', '1')].name
    'mx_1'
    """
    variables: Dict = {}
    if isinstance(indexes, tuple):
        for combi in product(*indexes):
            name = name_prefix + separator.join(str(c) for c in combi)
            variables[tuple(str(c) for c in combi)] = Variable(name, domain)
    elif hasattr(indexes, "__iter__"):
        for i in indexes:
            name = name_prefix + str(i)
            variables[name] = Variable(name, domain)
    else:
        raise TypeError(f"indexes must be an iterable or tuple, got {indexes!r}")
    return variables


class AgentDef(SimpleRepr):
    """Agent metadata: capacity, hosting costs, route costs, extra attributes.

    Reference: pydcop/dcop/objects.py:669 (hosting_cost :739, route :788).

    >>> a = AgentDef('a1', capacity=100, default_hosting_cost=1,
    ...              hosting_costs={'v1': 5}, routes={'a2': 2})
    >>> a.hosting_cost('v1'), a.hosting_cost('v2')
    (5, 1)
    >>> a.route('a2'), a.route('a3'), a.route('a1')
    (2, 1, 0)
    """

    def __init__(
        self,
        name: str,
        capacity: float = 100,
        default_hosting_cost: float = 0,
        hosting_costs: Optional[Dict[str, float]] = None,
        default_route: float = 1,
        routes: Optional[Dict[str, float]] = None,
        **kwargs,
    ):
        self._name = name
        self._capacity = capacity
        self._default_hosting_cost = default_hosting_cost
        self._hosting_costs = dict(hosting_costs) if hosting_costs else {}
        self._default_route = default_route
        self._routes = dict(routes) if routes else {}
        self._extra_attrs = dict(kwargs)

    @property
    def name(self) -> str:
        return self._name

    @property
    def capacity(self) -> float:
        return self._capacity

    @property
    def default_hosting_cost(self) -> float:
        return self._default_hosting_cost

    @property
    def hosting_costs(self) -> Dict[str, float]:
        return dict(self._hosting_costs)

    @property
    def default_route(self) -> float:
        return self._default_route

    @property
    def routes(self) -> Dict[str, float]:
        return dict(self._routes)

    @property
    def extra_attrs(self) -> Dict[str, Any]:
        return dict(self._extra_attrs)

    def hosting_cost(self, computation_name: str) -> float:
        return self._hosting_costs.get(computation_name, self._default_hosting_cost)

    def route(self, other_agent: str) -> float:
        if other_agent == self._name:
            return 0
        return self._routes.get(other_agent, self._default_route)

    def __getattr__(self, item):
        # extra attributes (e.g. 'preferences') act like plain attributes,
        # as in the reference
        try:
            return self.__dict__["_extra_attrs"][item]
        except KeyError:
            raise AttributeError(f"AgentDef has no attribute {item!r}")

    def __eq__(self, other):
        return (
            isinstance(other, AgentDef)
            and self._name == other._name
            and self._capacity == other._capacity
            and self._hosting_costs == other._hosting_costs
            and self._routes == other._routes
        )

    def __hash__(self):
        return hash(("AgentDef", self._name, self._capacity))

    def __repr__(self):
        return f"AgentDef({self._name!r}, capacity={self._capacity})"

    def _simple_repr(self):
        from pydcop_tpu.utils.serialization import (
            REPR_MODULE,
            REPR_QUALNAME,
            simple_repr,
        )

        r = {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "name": self._name,
            "capacity": self._capacity,
            "default_hosting_cost": self._default_hosting_cost,
            "hosting_costs": simple_repr(self._hosting_costs),
            "default_route": self._default_route,
            "routes": simple_repr(self._routes),
        }
        r.update(simple_repr(self._extra_attrs))
        return r

    @classmethod
    def _from_repr(cls, r):
        from pydcop_tpu.utils.serialization import (
            REPR_MODULE,
            REPR_QUALNAME,
            from_repr,
        )

        kwargs = {
            k: from_repr(v)
            for k, v in r.items()
            if k not in (REPR_MODULE, REPR_QUALNAME)
        }
        name = kwargs.pop("name")
        return cls(name, **kwargs)


def create_agents(
    name_prefix: str,
    indexes: Union[Tuple, Iterable],
    default_hosting_cost: float = 0,
    hosting_costs: Optional[Dict] = None,
    default_route: float = 1,
    routes: Optional[Dict] = None,
    separator: str = "_",
    **kwargs,
) -> Dict[Union[str, Tuple[str, ...]], AgentDef]:
    """Batch-create agents (reference: pydcop/dcop/objects.py:879)."""
    agents: Dict = {}
    hosting_costs = hosting_costs or {}
    routes = routes or {}

    def _mk(key, name):
        agents[key] = AgentDef(
            name,
            default_hosting_cost=default_hosting_cost,
            hosting_costs=hosting_costs.get(name, None),
            default_route=default_route,
            routes=routes.get(name, None),
            **kwargs,
        )

    if isinstance(indexes, tuple):
        for combi in product(*indexes):
            name = name_prefix + separator.join(str(c) for c in combi)
            _mk(tuple(str(c) for c in combi), name)
    elif hasattr(indexes, "__iter__"):
        for i in indexes:
            name = name_prefix + str(i)
            _mk(name, name)
    else:
        raise TypeError(f"indexes must be an iterable or tuple, got {indexes!r}")
    return agents
