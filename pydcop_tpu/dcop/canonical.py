"""Canonical byte form + content addressing for DCOP instances.

ISSUE 18 tentpole support: the cross-request solution cache
(:mod:`pydcop_tpu.serve.memo`) keys entries on *content*, not on the
submitted object, so two requests carrying the same problem hit the
same cache line no matter how the instance was built, named, or
ordered.  Three layers of identity, from strict to loose:

* :func:`canonical_hash` — sha256 over a deterministic JSON form of
  the full instance (objective, sorted domain/variable/external/agent
  sections, per-constraint content digests).  Declaration order never
  leaks in (every section is name-sorted), the instance ``name`` /
  ``description`` metadata never leaks in, and no global RNG is
  consulted — the exact-duplicate key.
* :func:`shape_signature` — the same digest restricted to the
  variable/domain skeleton (objective + domains + variables +
  externals).  Two instances with equal shape signatures differ only
  in their factor set, which is precisely the precondition for the
  PR 8 warm-mutation replay — the variant-feasibility gate.
* :func:`factor_diff` — the factor-level delta between a cached
  instance (its stored name→digest map) and a fresh one: which
  constraints changed content, appeared, or vanished.  The memo layer
  replays this as an EditFactor/AddFactor/RemoveFactor mutation
  stream, so a k-edit variant costs k warm repairs.

Constraint digests prefer the cheapest exact content form available:
structured constraints hash their parameter dicts (never densified),
intentional constraints hash their expression string, and only plain
extensional tables hash the dense float64 tensor bytes.  Distinct
forms deliberately hash distinct — a semantically-equal table and
expression missing each other only costs a cache miss, never a wrong
hit.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP

__all__ = [
    "FactorDiff",
    "canonical_bytes",
    "canonical_hash",
    "constraint_digest",
    "constraint_digests",
    "constraint_fingerprint",
    "factor_diff",
    "params_key",
    "shape_signature",
]


def _jsonable(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, (tuple, set, frozenset)):
        return sorted(o) if isinstance(o, (set, frozenset)) else list(o)
    raise TypeError(f"not canonicalizable: {type(o).__name__}")


def _dumps(obj) -> str:
    """Deterministic JSON: sorted keys, no whitespace, numpy coerced."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=_jsonable)


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def params_key(algo_params) -> str:
    """Canonical string form of an algo-params dict (order-free)."""
    return _dumps(dict(algo_params or {}))


# ---------------------------------------------------------------------------
# constraint content
# ---------------------------------------------------------------------------


def constraint_fingerprint(c) -> bytes:
    """Canonical byte form of ONE constraint's content.

    Scope order is preserved (it defines the table's axis order — a
    transposed table is a different constraint); the containing
    instance's declaration order is not this function's concern.
    """
    from pydcop_tpu.dcop.structured import StructuredConstraint

    if isinstance(c, StructuredConstraint):
        body = {
            "form": "structured",
            "kind": c.kind,
            "scope": list(c.scope_names),
            "params": c.params(),
        }
    else:
        expr = getattr(c, "expression", None)
        if expr is not None:
            body = {
                "form": "intention",
                "scope": list(c.scope_names),
                "expr": str(expr),
            }
        else:
            t = np.ascontiguousarray(
                np.asarray(c.to_tensor(), dtype=np.float64))
            body = {
                "form": "table",
                "scope": list(c.scope_names),
                "shape": list(t.shape),
                "sha": _sha(t.tobytes()),
            }
    return _dumps(body).encode("utf-8")


def constraint_digest(c) -> str:
    """sha256 hex digest of :func:`constraint_fingerprint`."""
    return _sha(constraint_fingerprint(c))


def constraint_digests(dcop: DCOP) -> Dict[str, str]:
    """name → content digest for every constraint of ``dcop``."""
    return {name: constraint_digest(c)
            for name, c in dcop.constraints.items()}


# ---------------------------------------------------------------------------
# instance skeleton + full canonical form
# ---------------------------------------------------------------------------


def _skeleton(dcop: DCOP) -> Dict[str, Any]:
    """The variable/domain skeleton sections (sorted by name)."""
    from pydcop_tpu.dcop.objects import (
        VariableNoisyCostFunc,
        VariableWithCostFunc,
    )
    from pydcop_tpu.utils.expressions import ExpressionFunction

    variables: Dict[str, Any] = {}
    for v in dcop.variables.values():
        vd: Dict[str, Any] = {"domain": v.domain.name}
        if v.initial_value is not None:
            vd["initial_value"] = v.initial_value
        if isinstance(v, VariableWithCostFunc) and isinstance(
            v.cost_func, ExpressionFunction
        ):
            vd["cost_function"] = v.cost_func.expression
        if isinstance(v, VariableNoisyCostFunc):
            vd["noise_level"] = v.noise_level
        variables[v.name] = vd
    return {
        "objective": dcop.objective,
        "domains": {
            d.name: {"type": d.type, "values": list(d.values)}
            for d in dcop.domains.values()
        },
        "variables": variables,
        "external": {
            v.name: {"domain": v.domain.name, "value": v.value}
            for v in dcop.external_variables.values()
        },
    }


def shape_signature(dcop: DCOP) -> str:
    """Digest of the variable/domain skeleton — the warm-replay
    feasibility gate: equal signatures ⇒ the instances differ only in
    factors, so a cached assignment is a valid seed and the factor
    diff is expressible as fixed-shape mutations."""
    return _sha(_dumps(_skeleton(dcop)).encode("utf-8"))


def canonical_bytes(dcop: DCOP) -> bytes:
    """Deterministic byte form of the full instance content.

    Name-sorted sections (via ``sort_keys``) make declaration-order
    permutations byte-identical; ``name``/``description`` metadata is
    excluded — it does not change the problem being solved.
    """
    body = _skeleton(dcop)
    body["constraints"] = {
        name: {"scope": list(c.scope_names),
               "digest": constraint_digest(c)}
        for name, c in dcop.constraints.items()
    }
    body["agents"] = {
        a.name: ({"capacity": a.capacity}
                 if a.capacity is not None else {})
        for a in dcop.agents.values()
    }
    return _dumps(body).encode("utf-8")


def canonical_hash(dcop: DCOP) -> str:
    """sha256 hex of :func:`canonical_bytes` — the exact-duplicate key."""
    return _sha(canonical_bytes(dcop))


# ---------------------------------------------------------------------------
# factor-level diff
# ---------------------------------------------------------------------------


@dataclass
class FactorDiff:
    """Factor delta between a cached instance and a fresh submission.

    ``changed``/``added``/``removed`` are constraint names relative to
    the NEW instance (``changed`` = same name, different content
    digest; ``added`` = only in new; ``removed`` = only in cached).
    """

    changed: List[str] = field(default_factory=list)
    added: List[str] = field(default_factory=list)
    removed: List[str] = field(default_factory=list)

    @property
    def edits(self) -> int:
        return len(self.changed) + len(self.added) + len(self.removed)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "edits": self.edits,
            "changed": len(self.changed),
            "added": len(self.added),
            "removed": len(self.removed),
        }


def factor_diff(old_digests: Dict[str, str], new_dcop: DCOP,
                new_digests: Dict[str, str] = None) -> FactorDiff:
    """Diff a cached instance's name→digest map against ``new_dcop``."""
    if new_digests is None:
        new_digests = constraint_digests(new_dcop)
    diff = FactorDiff()
    for name in sorted(new_digests):
        if name not in old_digests:
            diff.added.append(name)
        elif old_digests[name] != new_digests[name]:
            diff.changed.append(name)
    diff.removed = sorted(n for n in old_digests if n not in new_digests)
    return diff
