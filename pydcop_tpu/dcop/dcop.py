"""The DCOP container object.

Equivalent capability to the reference's pydcop/dcop/dcop.py:41 (`DCOP`),
including `solution_cost` (:308,319) and DCOP merging (`__add__`, :154).
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from pydcop_tpu.dcop.objects import AgentDef, Domain, ExternalVariable, Variable
from pydcop_tpu.dcop.relations import Constraint


class DCOP:
    """A Distributed Constraint Optimization Problem.

    Holds domains, variables, constraints, agents and external variables,
    with an objective ('min' or 'max').

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> dcop = DCOP('test')
    >>> d = Domain('d', 'd', [0, 1, 2])
    >>> v1, v2 = Variable('v1', d), Variable('v2', d)
    >>> _ = dcop.add_constraint(constraint_from_str('c1', 'abs(v1 - v2)', [v1, v2]))
    >>> dcop.solution_cost({'v1': 0, 'v2': 2}, 10000)
    (0, 2.0)
    """

    def __init__(
        self,
        name: str = "dcop",
        objective: str = "min",
        description: str = "",
        domains: Optional[Dict[str, Domain]] = None,
        variables: Optional[Dict[str, Variable]] = None,
        constraints: Optional[Dict[str, Constraint]] = None,
        agents: Optional[Dict[str, AgentDef]] = None,
    ):
        if objective not in ("min", "max"):
            raise ValueError(f"objective must be 'min' or 'max', got {objective!r}")
        self.name = name
        self.objective = objective
        self.description = description
        self.domains: Dict[str, Domain] = dict(domains or {})
        self.variables: Dict[str, Variable] = dict(variables or {})
        self.external_variables: Dict[str, ExternalVariable] = {}
        self.constraints: Dict[str, Constraint] = {}
        self.agents: Dict[str, AgentDef] = dict(agents or {})
        self.dist_hints = None  # DistributionHints, set by the yaml loader
        for c in (constraints or {}).values():
            self.add_constraint(c)

    # -- building -----------------------------------------------------------

    def add_domain(self, domain: Domain) -> "DCOP":
        self.domains[domain.name] = domain
        return self

    def add_variable(self, variable: Variable) -> "DCOP":
        if isinstance(variable, ExternalVariable):
            self.external_variables[variable.name] = variable
        else:
            self.variables[variable.name] = variable
        self.domains.setdefault(variable.domain.name, variable.domain)
        return self

    def add_constraint(self, constraint: Constraint) -> "DCOP":
        """Add a constraint; its variables (and their domains) are
        registered automatically."""
        self.constraints[constraint.name] = constraint
        for v in constraint.dimensions:
            if v.name not in self.variables and v.name not in self.external_variables:
                self.add_variable(v)
        return self

    def add_agents(
        self, agents: Union[Iterable[AgentDef], Dict[Any, AgentDef]]
    ) -> "DCOP":
        if isinstance(agents, dict):
            agents = agents.values()
        for a in agents:
            self.agents[a.name] = a
        return self

    # reference-parity conveniences
    def variable(self, name: str) -> Variable:
        return self.variables[name]

    def constraint(self, name: str) -> Constraint:
        return self.constraints[name]

    def agent(self, name: str) -> AgentDef:
        return self.agents[name]

    def get_external_variable(self, name: str) -> ExternalVariable:
        return self.external_variables[name]

    @property
    def all_variables(self) -> List[Variable]:
        return list(self.variables.values()) + list(self.external_variables.values())

    # -- queries ------------------------------------------------------------

    def constraints_for_variable(self, variable: Union[str, Variable]
                                 ) -> List[Constraint]:
        name = variable if isinstance(variable, str) else variable.name
        return [c for c in self.constraints.values() if name in c.scope_names]

    def solution_cost(
        self, assignment: Dict[str, Any], infinity: float = float("inf")
    ) -> Tuple[int, float]:
        """(hard-violation count, total cost) of a full assignment.

        A constraint whose cost reaches `infinity` counts as violated and is
        excluded from the cost sum; variable costs are included
        (reference: dcop.py:308-360).
        """
        full = dict(assignment)
        for ev in self.external_variables.values():
            full.setdefault(ev.name, ev.value)
        violations, cost = 0, 0.0
        for c in self.constraints.values():
            try:
                val = c.get_value_for_assignment(
                    {n: full[n] for n in c.scope_names}
                )
            except KeyError as ke:
                raise ValueError(
                    f"Incomplete assignment: missing {ke} for constraint {c.name}"
                )
            if val >= infinity:
                violations += 1
            else:
                cost += val
        for v in self.variables.values():
            if v.has_cost and v.name in full:
                cost += v.cost_for_val(full[v.name])
        return violations, cost

    # -- merge (dynamic DCOPs build on this, reference dcop.py:154) ---------

    def __add__(self, other: "DCOP") -> "DCOP":
        merged = DCOP(
            f"{self.name}+{other.name}",
            self.objective,
            self.description,
        )
        if self.objective != other.objective:
            raise ValueError("Cannot merge DCOPs with different objectives")
        for d in {**self.domains, **other.domains}.values():
            merged.add_domain(d)
        for v in {**self.variables, **other.variables}.values():
            merged.add_variable(v)
        for ev in {**self.external_variables, **other.external_variables}.values():
            merged.add_variable(ev)
        for c in {**self.constraints, **other.constraints}.values():
            merged.add_constraint(c)
        merged.add_agents({**self.agents, **other.agents})
        return merged

    def __repr__(self):
        return (
            f"DCOP({self.name!r}, {len(self.variables)} vars, "
            f"{len(self.constraints)} constraints, {len(self.agents)} agents)"
        )
