"""Constraints and the relational algebra kernel.

Equivalent capability to the reference's pydcop/dcop/relations.py
(RelationProtocol :48, ZeroAryRelation :218, UnaryFunctionRelation :270,
UnaryBooleanRelation :380, NAryFunctionRelation :456, AsNAryFunctionRelation
:639, NAryMatrixRelation :672, NeutralRelation :909, ConditionalRelation :948,
constraint_from_str :1275, find_optimum :1348, generate_assignment :1405,
assignment_cost :1460, find_arg_optimal :1535, join :1622, projection :1667).

TPU-first redesign: where the reference's ``join``/``projection`` iterate in
python over the full cross-product of assignments (its hottest loop, driving
DPOP's UTIL phase), here every constraint can materialize to a dense numpy
cost tensor over domain-index space (:meth:`Constraint.to_tensor`), and the
algebra is **broadcast arithmetic + axis reductions**:

* ``join(u, v)``  = ``u[..., None] + v`` aligned over the union of dimensions,
* ``projection(r, var)`` = ``min``/``max`` over that variable's axis.

The same formulation runs unchanged under numpy (host, small problems) and
jax.numpy (device, batched DPOP sweeps — see pydcop_tpu.ops.dpop_kernels).
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.utils.expressions import ExpressionFunction
from pydcop_tpu.utils.serialization import SimpleRepr, simple_repr, from_repr, \
    REPR_MODULE, REPR_QUALNAME

DEFAULT_TYPE = np.float32


class Constraint(SimpleRepr):
    """Abstract constraint: a cost function over a tuple of variables.

    Immutable; all mutating-looking operations return new objects.
    """

    def __init__(self, name: str, variables: Sequence[Variable]):
        self._name = name
        self._variables = list(variables)

    @property
    def name(self) -> str:
        return self._name

    @property
    def dimensions(self) -> List[Variable]:
        return list(self._variables)

    @property
    def scope_names(self) -> List[str]:
        return [v.name for v in self._variables]

    @property
    def arity(self) -> int:
        return len(self._variables)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(len(v.domain) for v in self._variables)

    # -- evaluation ---------------------------------------------------------

    def __call__(self, *args, **kwargs):
        if args and kwargs:
            raise ValueError("Use either positional or keyword arguments")
        if args:
            if len(args) != self.arity:
                raise ValueError(
                    f"{self._name} expects {self.arity} values, got {len(args)}"
                )
            kwargs = {v.name: a for v, a in zip(self._variables, args)}
        return self.get_value_for_assignment(kwargs)

    def get_value_for_assignment(self, assignment: Union[Dict, List]) -> float:
        if isinstance(assignment, list):
            assignment = {v.name: a for v, a in zip(self._variables, assignment)}
        return self._value(assignment)

    def _value(self, assignment: Dict) -> float:
        raise NotImplementedError

    # -- algebra ------------------------------------------------------------

    def slice(self, partial_assignment: Dict[str, Any]) -> "Constraint":
        """Fix some variables, producing a constraint over the rest."""
        fixed = {
            k: v for k, v in partial_assignment.items() if k in self.scope_names
        }
        remaining = [v for v in self._variables if v.name not in fixed]
        if not fixed:
            return self
        return SlicedRelation(self, fixed, remaining)

    def to_tensor(self) -> np.ndarray:
        """Materialize as a dense cost tensor over domain-index space.

        Axis *k* corresponds to ``self.dimensions[k]``, indexed in its
        domain's order.  This is the compilation step that turns arbitrary
        python cost functions into XLA-ready arrays (reference twin:
        NAryMatrixRelation.from_func_relation, relations.py:861).
        """
        shape = self.shape
        t = np.empty(shape, dtype=DEFAULT_TYPE)
        domains = [v.domain for v in self._variables]
        names = self.scope_names
        for idx in np.ndindex(*shape) if shape else [()]:
            assignment = {n: d[i] for n, d, i in zip(names, domains, idx)}
            t[idx] = self._value(assignment)
        return t

    def set_value_for_assignment(
        self, assignment: Dict[str, Any], value: float
    ) -> "NAryMatrixRelation":
        rel = NAryMatrixRelation.from_constraint(self)
        return rel.set_value_for_assignment(assignment, value)

    def __eq__(self, other):
        if type(other) is not type(self):
            return NotImplemented
        return self._name == other._name and self.scope_names == other.scope_names

    def __hash__(self):
        return hash((type(self).__name__, self._name, tuple(self.scope_names)))

    def __repr__(self):
        return f"{type(self).__name__}({self._name!r}, {self.scope_names})"


# Reference exposes `RelationProtocol` and `Constraint` as the same thing
# (relations.py:186)
RelationProtocol = Constraint


class ZeroAryRelation(Constraint):
    """A constant-cost relation over no variables (relations.py:218)."""

    def __init__(self, name: str, value: float):
        super().__init__(name, [])
        self._rel_value = value

    def _value(self, assignment: Dict) -> float:
        return self._rel_value

    def _simple_repr(self):
        return {REPR_MODULE: type(self).__module__,
                REPR_QUALNAME: type(self).__qualname__,
                "name": self._name, "value": self._rel_value}

    @classmethod
    def _from_repr(cls, r):
        return cls(r["name"], r["value"])


class UnaryFunctionRelation(Constraint):
    """Cost from a single-argument function of one variable (relations.py:270)."""

    def __init__(self, name: str, variable: Variable, rel_function: Callable):
        super().__init__(name, [variable])
        self._rel_function = rel_function

    @property
    def expression(self):
        if isinstance(self._rel_function, ExpressionFunction):
            return self._rel_function.expression
        return None

    def _value(self, assignment: Dict) -> float:
        val = assignment[self._variables[0].name]
        if isinstance(self._rel_function, ExpressionFunction):
            return self._rel_function(**{self._variables[0].name: val})
        return self._rel_function(val)


class UnaryBooleanRelation(Constraint):
    """Hard unary relation: cost 0 if value is truthy, else infinity
    (relations.py:380)."""

    def __init__(self, name: str, variable: Variable):
        super().__init__(name, [variable])

    def _value(self, assignment: Dict) -> float:
        return 0 if assignment[self._variables[0].name] else np.inf


class NAryFunctionRelation(Constraint):
    """Cost from an arbitrary function over n variables (relations.py:456).

    ``f`` may be an :class:`ExpressionFunction` (called with variable names as
    keywords) or a plain callable (called positionally in dimension order,
    unless ``takes_kwargs``).
    """

    def __init__(
        self,
        f: Callable,
        variables: Sequence[Variable],
        name: Optional[str] = None,
        takes_kwargs: Optional[bool] = None,
    ):
        super().__init__(name or getattr(f, "__name__", "relation"), variables)
        self._f = f
        if takes_kwargs is None:
            takes_kwargs = isinstance(f, ExpressionFunction)
        self._takes_kwargs = takes_kwargs

    @property
    def function(self):
        return self._f

    @property
    def expression(self):
        if isinstance(self._f, ExpressionFunction):
            return self._f.expression
        return None

    def _value(self, assignment: Dict) -> float:
        if self._takes_kwargs:
            return self._f(**{n: assignment[n] for n in self.scope_names})
        return self._f(*[assignment[n] for n in self.scope_names])

    def _simple_repr(self):
        if not isinstance(self._f, ExpressionFunction):
            raise ValueError(
                "Only expression-based NAryFunctionRelation are serializable"
            )
        return {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "name": self._name,
            "f": simple_repr(self._f),
            "variables": simple_repr(self._variables),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(from_repr(r["f"]), from_repr(r["variables"]), r["name"])


def AsNAryFunctionRelation(*variables: Variable):
    """Decorator building an NAryFunctionRelation from a python function
    (relations.py:639).

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'd', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> @AsNAryFunctionRelation(x, y)
    ... def my_rel(x, y):
    ...     return x + y
    >>> my_rel(1, 1)
    2
    """

    def decorate(f):
        return NAryFunctionRelation(f, list(variables), f.__name__)

    return decorate


class NAryMatrixRelation(Constraint):
    """Cost tensor over the cartesian product of variable domains —
    the canonical compiled form of any constraint (relations.py:672).

    The backing array is a numpy tensor whose axis *k* is indexed by
    ``dimensions[k]``'s domain order.

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'd', ['a', 'b'])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> r = NAryMatrixRelation([x, y], [[1, 2], [3, 4]], name='r')
    >>> r(x='b', y='a')
    3.0
    >>> r.slice({'x': 'a'})(y='b')
    2.0
    """

    def __init__(
        self,
        variables: Sequence[Variable],
        matrix: Optional[np.ndarray] = None,
        name: str = "",
    ):
        super().__init__(name, variables)
        shape = self.shape
        if matrix is None:
            self._m = np.zeros(shape, dtype=DEFAULT_TYPE)
        else:
            self._m = np.asarray(matrix, dtype=DEFAULT_TYPE).reshape(shape)

    @property
    def matrix(self) -> np.ndarray:
        return self._m

    @classmethod
    def from_constraint(cls, c: Constraint) -> "NAryMatrixRelation":
        if isinstance(c, NAryMatrixRelation):
            return c
        return cls(c.dimensions, c.to_tensor(), c.name)

    # reference-parity alias (relations.py:861)
    from_func_relation = from_constraint

    def to_tensor(self) -> np.ndarray:
        return self._m

    def _index(self, assignment: Dict) -> Tuple[int, ...]:
        return tuple(
            v.domain.index(assignment[v.name]) for v in self._variables
        )

    def _value(self, assignment: Dict) -> float:
        return float(self._m[self._index(assignment)])

    def slice(self, partial_assignment: Dict[str, Any]) -> "NAryMatrixRelation":
        fixed = {
            k: v for k, v in partial_assignment.items() if k in self.scope_names
        }
        if not fixed:
            return self
        indexer: List[Any] = []
        remaining: List[Variable] = []
        for v in self._variables:
            if v.name in fixed:
                indexer.append(v.domain.index(fixed[v.name]))
            else:
                indexer.append(slice(None))
                remaining.append(v)
        return NAryMatrixRelation(remaining, self._m[tuple(indexer)], self._name)

    def set_value_for_assignment(
        self, assignment: Dict[str, Any], value: float
    ) -> "NAryMatrixRelation":
        m = self._m.copy()
        m[self._index(assignment)] = value
        return NAryMatrixRelation(self._variables, m, self._name)

    def __eq__(self, other):
        if not isinstance(other, NAryMatrixRelation):
            return NotImplemented
        return (
            self._name == other._name
            and self.scope_names == other.scope_names
            and np.array_equal(self._m, other._m)
        )

    def __hash__(self):
        return hash((self._name, tuple(self.scope_names)))

    def _simple_repr(self):
        return {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "name": self._name,
            "variables": simple_repr(self._variables),
            "matrix": self._m.tolist(),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(from_repr(r["variables"]), np.array(r["matrix"]), r["name"])


class SlicedRelation(Constraint):
    """Generic lazy slice of any constraint (used when the base is not a
    matrix; matrix relations slice natively)."""

    def __init__(self, base: Constraint, fixed: Dict[str, Any],
                 remaining: Sequence[Variable]):
        super().__init__(base.name, remaining)
        self._base = base
        self._fixed = dict(fixed)

    def _value(self, assignment: Dict) -> float:
        return self._base.get_value_for_assignment({**self._fixed, **assignment})


class NeutralRelation(Constraint):
    """Always-zero relation over given variables (relations.py:909)."""

    def __init__(self, variables: Sequence[Variable], name: str = "neutral"):
        super().__init__(name, variables)

    def _value(self, assignment: Dict) -> float:
        return 0


class ConditionalRelation(Constraint):
    """Cost of ``relation_if_true`` when the (boolean) condition relation is
    truthy, else 0 (relations.py:948)."""

    def __init__(
        self,
        condition: Constraint,
        relation_if_true: Constraint,
        name: str = "conditional",
        return_value_if_false: float = 0,
    ):
        cond_vars = condition.dimensions
        rel_vars = [
            v for v in relation_if_true.dimensions if v not in cond_vars
        ]
        super().__init__(name, cond_vars + rel_vars)
        self._condition = condition
        self._relation = relation_if_true
        self._if_false = return_value_if_false

    def _value(self, assignment: Dict) -> float:
        cond = self._condition.get_value_for_assignment(
            {n: assignment[n] for n in self._condition.scope_names}
        )
        if cond:
            return self._relation.get_value_for_assignment(
                {n: assignment[n] for n in self._relation.scope_names}
            )
        return self._if_false


# ---------------------------------------------------------------------------
# Constructors & helpers
# ---------------------------------------------------------------------------


def constraint_from_str(
    name: str, expression: str, all_variables: Iterable[Variable]
) -> Constraint:
    """Build a constraint from a python expression string, binding the
    expression's free names to the given variables (relations.py:1275).

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'vals', [0, 1, 2])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> c = constraint_from_str('c', '10 if x == y else abs(x - y)', [x, y])
    >>> c(x=1, y=1)
    10
    >>> c(x=0, y=2)
    2
    >>> sorted(c.scope_names)
    ['x', 'y']
    """
    f = ExpressionFunction(expression)
    var_map = {v.name: v for v in all_variables}
    scope = []
    for vname in sorted(f.variable_names):
        if vname not in var_map:
            raise ValueError(
                f"Unknown variable {vname!r} in constraint {name}: {expression!r}"
            )
        scope.append(var_map[vname])
    if len(scope) == 1:
        return UnaryFunctionRelation(name, scope[0], f)
    return NAryFunctionRelation(f, scope, name)


def relation_from_str(name, expression, all_variables):
    return constraint_from_str(name, expression, all_variables)


def assignment_matrix(variables: Sequence[Variable], default_value: float = 0
                      ) -> np.ndarray:
    """Dense tensor over the variables' domain product, filled with default."""
    shape = tuple(len(v.domain) for v in variables)
    return np.full(shape, default_value, dtype=DEFAULT_TYPE)


def generate_assignment(variables: Sequence[Variable]):
    """Yield all assignments as value lists, last variable fastest
    (relations.py:1405)."""
    domains = [list(v.domain) for v in variables]
    for combo in itertools.product(*domains):
        yield list(combo)


def generate_assignment_as_dict(variables: Sequence[Variable]):
    """Yield all assignments as dicts (relations.py:1433)."""
    names = [v.name for v in variables]
    domains = [list(v.domain) for v in variables]
    for combo in itertools.product(*domains):
        yield dict(zip(names, combo))


def assignment_cost(
    assignment: Dict[str, Any],
    constraints: Iterable[Constraint],
    consider_variable_cost: bool = False,
    variables: Iterable[Variable] = (),
) -> float:
    """Total cost of an assignment over the given constraints
    (relations.py:1460).

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'vals', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> c1 = constraint_from_str('c1', 'x + y', [x, y])
    >>> c2 = constraint_from_str('c2', '5 * x', [x])
    >>> assignment_cost({'x': 1, 'y': 0}, [c1, c2])
    6.0
    """
    cost = 0.0
    for c in constraints:
        cost += c.get_value_for_assignment(
            {n: assignment[n] for n in c.scope_names}
        )
    if consider_variable_cost:
        for v in variables:
            if v.name in assignment and v.has_cost:
                cost += v.cost_for_val(assignment[v.name])
    return cost


def filter_assignment_dict(assignment: Dict, target_vars: Iterable[Variable]
                           ) -> Dict:
    """Keep only entries whose key names one of target_vars
    (reference: pydcop/dcop/relations.py filter_assignment_dict)."""
    names = {v.name for v in target_vars}
    return {k: v for k, v in assignment.items() if k in names}


def find_optimum(constraint: Constraint, mode: str) -> float:
    """Best achievable cost of a constraint: min or max over its tensor
    (relations.py:1348)."""
    if mode not in ("min", "max"):
        raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
    t = constraint.to_tensor() if not isinstance(constraint, NAryMatrixRelation) \
        else constraint.matrix
    return float(t.min() if mode == "min" else t.max())


def optimal_cost_value(variable: Variable, mode: str = "min"):
    """Best (value, cost) for a variable's own cost function."""
    costs = variable.cost_vector()
    idx = int(np.argmin(costs) if mode == "min" else np.argmax(costs))
    return variable.domain[idx], float(costs[idx])


def find_arg_optimal(
    variable: Variable, relation: Constraint, mode: str = "min"
) -> Tuple[List[Any], float]:
    """All optimal values of `variable` for a unary relation over it
    (relations.py:1535).  Returns (list_of_values, optimal_cost).

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'vals', ['a', 'b', 'c'])
    >>> v = Variable('v', d)
    >>> r = constraint_from_str('r', "{'a': 3, 'b': 1, 'c': 1}[v]", [v])
    >>> find_arg_optimal(v, r, mode='min')
    (['b', 'c'], 1.0)
    """
    if relation.arity != 1 or relation.dimensions[0].name != variable.name:
        raise ValueError(
            f"find_arg_optimal needs a unary relation on {variable.name}, "
            f"got {relation.scope_names}"
        )
    t = relation.to_tensor() if not isinstance(relation, NAryMatrixRelation) \
        else relation.matrix
    opt = t.min() if mode == "min" else t.max()
    values = [variable.domain[i] for i in np.flatnonzero(t == opt)]
    return values, float(opt)


def find_optimal(
    variable: Variable, assignment: Dict, constraints: Iterable[Constraint],
    mode: str = "min",
) -> Tuple[List[Any], float]:
    """Optimal values for one variable given fixed neighbors
    (relations.py:1575)."""
    costs = np.zeros(len(variable.domain), dtype=np.float64)
    for i, val in enumerate(variable.domain):
        full = {**assignment, variable.name: val}
        costs[i] = sum(
            c.get_value_for_assignment({n: full[n] for n in c.scope_names})
            for c in constraints
        )
    opt = costs.min() if mode == "min" else costs.max()
    values = [variable.domain[i] for i in np.flatnonzero(costs == opt)]
    return values, float(opt)


# ---------------------------------------------------------------------------
# The algebra: join & projection (broadcast formulation)
# ---------------------------------------------------------------------------


def _align_tensor(
    t: np.ndarray, dims: List[Variable], out_dims: List[Variable]
) -> np.ndarray:
    """Transpose/expand t (over `dims`) to broadcast over `out_dims`."""
    pos = {v.name: i for i, v in enumerate(dims)}
    # axes of out_dims present in dims, in out order
    perm = [pos[v.name] for v in out_dims if v.name in pos]
    t = np.transpose(t, perm) if perm else t
    shape = [len(v.domain) if v.name in pos else 1 for v in out_dims]
    return t.reshape(shape)


def join(u: Constraint, v: Constraint) -> NAryMatrixRelation:
    """Sum-combine two relations over the union of their dimensions
    (relations.py:1622).

    Broadcast formulation: align both cost tensors on the union axis order
    and add — one XLA-fusable op instead of the reference's python loop over
    every assignment.

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'd', [0, 1])
    >>> x, y, z = (Variable(n, d) for n in 'xyz')
    >>> r1 = NAryMatrixRelation([x, y], [[0, 1], [2, 3]], 'r1')
    >>> r2 = NAryMatrixRelation([y, z], [[10, 20], [30, 40]], 'r2')
    >>> j = join(r1, r2)
    >>> [v.name for v in j.dimensions]
    ['x', 'y', 'z']
    >>> j(x=1, y=0, z=1)
    22.0
    """
    u_dims = u.dimensions
    u_names = {d.name for d in u_dims}
    out_dims = u_dims + [d for d in v.dimensions if d.name not in u_names]
    ut = u.matrix if isinstance(u, NAryMatrixRelation) else u.to_tensor()
    vt = v.matrix if isinstance(v, NAryMatrixRelation) else v.to_tensor()
    m = _align_tensor(ut, u_dims, out_dims) + _align_tensor(
        vt, v.dimensions, out_dims
    )
    return NAryMatrixRelation(out_dims, m, f"joined_{u.name}_{v.name}")


def projection(
    rel: Constraint, variable: Variable, mode: str = "min"
) -> NAryMatrixRelation:
    """Eliminate one variable by optimizing it out (relations.py:1667).

    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> d = Domain('d', 'd', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> r = NAryMatrixRelation([x, y], [[5, 1], [2, 8]], 'r')
    >>> p = projection(r, y, 'min')
    >>> p(x=0), p(x=1)
    (1.0, 2.0)
    """
    names = rel.scope_names
    if variable.name not in names:
        raise ValueError(
            f"Cannot project {variable.name} out of {rel.name}({names})"
        )
    axis = names.index(variable.name)
    t = rel.matrix if isinstance(rel, NAryMatrixRelation) else rel.to_tensor()
    m = t.min(axis=axis) if mode == "min" else t.max(axis=axis)
    out_dims = [v for v in rel.dimensions if v.name != variable.name]
    return NAryMatrixRelation(out_dims, m, rel.name)


def find_dependent_relations(
    variable: Variable, relations: Iterable[Constraint]
) -> List[Constraint]:
    return [r for r in relations if variable.name in r.scope_names]


def add_var_to_rel(
    name: str, rel: Constraint, variable: Variable, f: Callable
) -> Constraint:
    """Extend a relation with one more variable, combining costs with
    ``f(old_cost, var_value)`` (reference: relations.py add_var_to_rel)."""

    def extended(**kwargs):
        val = kwargs.pop(variable.name)
        base = rel.get_value_for_assignment(
            {n: kwargs[n] for n in rel.scope_names}
        )
        return f(base, val)

    return NAryFunctionRelation(
        extended, rel.dimensions + [variable], name, takes_kwargs=True
    )
