"""Structured (table-free) constraints: compile *structure*, not D^arity.

Every engine in this repo historically consumed dense cost tables
(:meth:`Constraint.to_tensor`), so device memory and collective bytes scale
as ``D^arity`` and high-arity families (routing window/resource rules,
AllDiff, meeting scheduling) were capped at small arity.  This module is the
constraint IR that removes that cap: each structured class carries a few
small parameter arrays and compiles to closed-form batched kernels
(:mod:`pydcop_tpu.ops.structured_kernels`) — cost-at-assignment,
per-variable min-marginal / message updates, and per-depth increment/bound
forms for the frontier engine — with peak memory *independent of arity*.

The IR is deliberately tiny.  Two **primitive** classes cover everything the
generators emit, and richer classes :meth:`~StructuredConstraint.lower` onto
them exactly (no approximation):

* :class:`LinearConstraint` — separable cost
  ``bias + sum_p tables[p][x_p]``.  Fully factorizes: maxsum messages are
  O(k·D), DPOP projection is symbolic (per-variable unaries), frontier
  increments fold into the plan's unary slabs.
* :class:`CardinalityConstraint` — cost is a function of *how many* scope
  variables take a designated value: ``count_cost[#{p : x_p == value}]``.
  Covers capacity caps, mutual exclusion and AllDiff (via one primitive per
  value).  Messages use an exact O(k log k + k·D) sorted-delta update.
* :class:`ResourceConstraint` — the PR 12 routing family: per-position
  preference rows plus per-value capacity curves.  Lowers to one
  LinearConstraint + one CardinalityConstraint per counted value.

Exactness tiers (PR 5 style): cost-at-assignment and frontier increments
are **exact** vs the densified table (same float32 adds in a fixed order);
message/min-marginal kernels are **ulp-tier** (identical math, different
float32 summation order than the table reduction — parity pinned to rtol in
``tests/unit/test_structured.py``).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import (
    Constraint,
    NAryMatrixRelation,
    DEFAULT_TYPE,
)
from pydcop_tpu.utils.serialization import REPR_MODULE, REPR_QUALNAME, simple_repr, from_repr

#: Refuse to densify a structured constraint above this many table entries
#: (2**22 entries = 16 MiB float32).  Anything larger must stay table-free;
#: hitting this limit on a hot path is a bug, not a fallback.
MAX_DENSIFY_ENTRIES = 1 << 22


class DensifyError(ValueError):
    """A structured constraint was asked to materialize an over-budget table."""


class StructuredConstraint(Constraint):
    """Base for table-free constraints.

    Subclasses declare ``kind`` and implement :meth:`params`,
    :meth:`lower`, and :meth:`_value`.  ``to_tensor`` stays available for
    parity tests and small-arity fallbacks but is guarded by
    :data:`MAX_DENSIFY_ENTRIES` so no engine can silently densify a
    100-arity factor.
    """

    kind: str = "structured"

    def dense_entries(self) -> int:
        """Number of entries a densified table would hold (may be huge)."""
        n = 1
        for v in self._variables:
            n *= len(v.domain)
        return n

    def dense_bytes(self) -> float:
        """Bytes the densified float32 table would take (as a float — the
        whole point is that this can exceed 2**63)."""
        b = 4.0
        for v in self._variables:
            b *= len(v.domain)
        return b

    def params(self) -> Dict[str, Any]:
        """JSON/YAML-safe parameter dict (plain python lists/floats)."""
        raise NotImplementedError

    def lower(self) -> List["StructuredConstraint"]:
        """Exact decomposition into primitives (Linear / Cardinality).

        ``sum(p(x) for p in c.lower()) == c(x)`` for every assignment.
        """
        raise NotImplementedError

    def to_tensor(self) -> np.ndarray:
        if self.dense_entries() > MAX_DENSIFY_ENTRIES:
            raise DensifyError(
                f"constraint {self.name!r} (kind={self.kind}, arity="
                f"{self.arity}) would densify to {self.dense_entries()} "
                f"entries > MAX_DENSIFY_ENTRIES={MAX_DENSIFY_ENTRIES}; "
                "use the structured kernels instead"
            )
        return super().to_tensor()

    def densified(self) -> NAryMatrixRelation:
        """Guarded dense twin, for parity tests and small-arity fallbacks."""
        return NAryMatrixRelation(self.dimensions, self.to_tensor(), self.name)


class LinearConstraint(StructuredConstraint):
    """Separable cost: ``bias + sum_p tables[p][index(x_p)]``.

    ``tables[p]`` is a 1-D cost row over ``variables[p]``'s domain (indexed
    in domain order).  Parameters are stored float64 so YAML round-trips are
    value-exact; kernels cast to float32 at compile time.
    """

    kind = "linear"

    def __init__(
        self,
        name: str,
        variables: Sequence[Variable],
        tables: Sequence[Sequence[float]],
        bias: float = 0.0,
    ):
        super().__init__(name, variables)
        if len(tables) != len(self._variables):
            raise ValueError(
                f"{name}: {len(tables)} cost rows for {len(self._variables)} variables"
            )
        self._tables = [np.asarray(t, dtype=np.float64) for t in tables]
        for v, t in zip(self._variables, self._tables):
            if t.shape != (len(v.domain),):
                raise ValueError(
                    f"{name}: cost row for {v.name} has shape {t.shape}, "
                    f"domain size {len(v.domain)}"
                )
        self._bias = float(bias)

    @property
    def tables(self) -> List[np.ndarray]:
        return list(self._tables)

    @property
    def bias(self) -> float:
        return self._bias

    def _value(self, assignment: Dict) -> float:
        total = self._bias
        for v, t in zip(self._variables, self._tables):
            total += float(
                np.float32(t[v.domain.index(assignment[v.name])])
            )
        return total

    def lower(self) -> List["StructuredConstraint"]:
        return [self]

    def slice(self, partial_assignment: Dict[str, Any]) -> Constraint:
        fixed = {k: v for k, v in partial_assignment.items()
                 if k in self.scope_names}
        if not fixed:
            return self
        bias = self._bias
        keep_vars: List[Variable] = []
        keep_tables: List[np.ndarray] = []
        for v, t in zip(self._variables, self._tables):
            if v.name in fixed:
                bias += float(t[v.domain.index(fixed[v.name])])
            else:
                keep_vars.append(v)
                keep_tables.append(t)
        return LinearConstraint(self._name, keep_vars, keep_tables, bias)

    def params(self) -> Dict[str, Any]:
        return {
            "class": "linear",
            "tables": [[float(x) for x in t] for t in self._tables],
            "bias": float(self._bias),
        }

    def _simple_repr(self):
        return {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "name": self._name,
            "variables": simple_repr(self._variables),
            "tables": [[float(x) for x in t] for t in self._tables],
            "bias": float(self._bias),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["name"], from_repr(r["variables"]), r["tables"], r["bias"])


class CardinalityConstraint(StructuredConstraint):
    """Cost depends only on how many scope variables equal ``value``:
    ``count_cost[#{p : x_p == value}]`` with ``len(count_cost) == arity+1``.

    Covers capacity caps (``penalty * max(0, c - cap)``), mutual exclusion
    (``0, 0, BIG, BIG, ...``) and, summed over values, AllDiff.
    """

    kind = "cardinality"

    def __init__(
        self,
        name: str,
        variables: Sequence[Variable],
        value: Any,
        count_cost: Sequence[float],
    ):
        super().__init__(name, variables)
        self._counted = value
        self._count_cost = np.asarray(count_cost, dtype=np.float64)
        k = len(self._variables)
        if self._count_cost.shape != (k + 1,):
            raise ValueError(
                f"{name}: count_cost must have arity+1={k + 1} entries, "
                f"got shape {self._count_cost.shape}"
            )

    @property
    def counted_value(self) -> Any:
        return self._counted

    @property
    def count_cost(self) -> np.ndarray:
        return self._count_cost

    def counted_indices(self) -> np.ndarray:
        """Per-position domain index of the counted value (-1 if absent)."""
        out = np.empty(len(self._variables), dtype=np.int32)
        for p, v in enumerate(self._variables):
            vals = list(v.domain)
            out[p] = vals.index(self._counted) if self._counted in vals else -1
        return out

    def _count(self, assignment: Dict) -> int:
        return sum(
            1 for v in self._variables if assignment[v.name] == self._counted
        )

    def _value(self, assignment: Dict) -> float:
        return float(np.float32(self._count_cost[self._count(assignment)]))

    def lower(self) -> List["StructuredConstraint"]:
        return [self]

    def slice(self, partial_assignment: Dict[str, Any]) -> Constraint:
        fixed = {k: v for k, v in partial_assignment.items()
                 if k in self.scope_names}
        if not fixed:
            return self
        base = sum(1 for n, val in fixed.items() if val == self._counted)
        remaining = [v for v in self._variables if v.name not in fixed]
        cc = self._count_cost[base:base + len(remaining) + 1]
        return CardinalityConstraint(self._name, remaining, self._counted, cc)

    def min_remaining_delta(self) -> float:
        """``min_{c' >= c} count_cost[c'] - count_cost[c]`` over all c.

        An admissible per-factor lower bound on the cost still to come once
        some prefix of the scope is assigned; 0 for monotone
        (nondecreasing) curves, possibly negative otherwise (max mode).
        """
        cc = self._count_cost.astype(np.float64)
        suffix_min = np.minimum.accumulate(cc[::-1])[::-1]
        return float(np.min(suffix_min - cc))

    def params(self) -> Dict[str, Any]:
        return {
            "class": "cardinality",
            "value": self._counted,
            "count_cost": [float(x) for x in self._count_cost],
        }

    def _simple_repr(self):
        return {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "name": self._name,
            "variables": simple_repr(self._variables),
            "value": self._counted,
            "count_cost": [float(x) for x in self._count_cost],
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["name"], from_repr(r["variables"]), r["value"],
                   r["count_cost"])


class ResourceConstraint(StructuredConstraint):
    """Window/resource rule (the PR 12 routing family):

    ``cost(x) = sum_p pref[p][x_p] + sum_v count_cost[v][#{p : x_p == values[v]}]``

    i.e. per-task slot preferences plus a per-slot capacity curve.  Lowers
    exactly to one :class:`LinearConstraint` (the preference part) plus one
    :class:`CardinalityConstraint` per counted value with a non-trivial
    curve.
    """

    kind = "resource"

    def __init__(
        self,
        name: str,
        variables: Sequence[Variable],
        pref: Sequence[Sequence[float]],
        values: Sequence[Any],
        count_cost: Sequence[Sequence[float]],
    ):
        super().__init__(name, variables)
        k = len(self._variables)
        self._pref = [np.asarray(t, dtype=np.float64) for t in pref]
        if len(self._pref) != k:
            raise ValueError(f"{name}: {len(self._pref)} pref rows for {k} variables")
        for v, t in zip(self._variables, self._pref):
            if t.shape != (len(v.domain),):
                raise ValueError(
                    f"{name}: pref row for {v.name} has shape {t.shape}, "
                    f"domain size {len(v.domain)}"
                )
        self._values = list(values)
        self._count_cost = np.asarray(count_cost, dtype=np.float64)
        if self._count_cost.shape != (len(self._values), k + 1):
            raise ValueError(
                f"{name}: count_cost must be [n_values={len(self._values)}, "
                f"arity+1={k + 1}], got {self._count_cost.shape}"
            )

    @property
    def pref(self) -> List[np.ndarray]:
        return list(self._pref)

    @property
    def values(self) -> List[Any]:
        return list(self._values)

    @property
    def count_cost(self) -> np.ndarray:
        return self._count_cost

    @classmethod
    def all_different(
        cls, name: str, variables: Sequence[Variable], penalty: float = 1.0
    ) -> "ResourceConstraint":
        """Soft AllDiff: ``penalty`` per clashing pair.  The count curve
        ``penalty * c*(c-1)/2`` per value sums to exactly the number of
        equal pairs, so this matches the pairwise formulation bit-for-bit
        in float64 parameter space."""
        vals: List[Any] = []
        for v in variables:
            for d in v.domain:
                if d not in vals:
                    vals.append(d)
        k = len(variables)
        counts = np.arange(k + 1, dtype=np.float64)
        curve = penalty * counts * (counts - 1.0) / 2.0
        pref = [np.zeros(len(v.domain)) for v in variables]
        cc = np.tile(curve, (len(vals), 1))
        return cls(name, variables, pref, vals, cc)

    def _value(self, assignment: Dict) -> float:
        total = 0.0
        for v, t in zip(self._variables, self._pref):
            total += float(np.float32(t[v.domain.index(assignment[v.name])]))
        for vi, val in enumerate(self._values):
            c = sum(1 for v in self._variables if assignment[v.name] == val)
            total += float(np.float32(self._count_cost[vi][c]))
        return total

    def lower(self) -> List[StructuredConstraint]:
        out: List[StructuredConstraint] = []
        if any(np.any(t != 0.0) for t in self._pref):
            out.append(
                LinearConstraint(
                    f"{self._name}__lin", self._variables, self._pref
                )
            )
        for vi, val in enumerate(self._values):
            row = self._count_cost[vi]
            if np.all(row == row[0]):
                # Constant curve contributes row[0] regardless of count;
                # nonzero constants are kept so total cost stays exact.
                if row[0] == 0.0:
                    continue
            out.append(
                CardinalityConstraint(
                    f"{self._name}__c{vi}", self._variables, val, row
                )
            )
        if not out:
            out.append(
                LinearConstraint(f"{self._name}__lin", self._variables,
                                 self._pref)
            )
        return out

    def slice(self, partial_assignment: Dict[str, Any]) -> Constraint:
        fixed = {k: v for k, v in partial_assignment.items()
                 if k in self.scope_names}
        if not fixed:
            return self
        keep_vars: List[Variable] = []
        keep_pref: List[np.ndarray] = []
        bias = 0.0
        for v, t in zip(self._variables, self._pref):
            if v.name in fixed:
                bias += float(t[v.domain.index(fixed[v.name])])
            else:
                keep_vars.append(v)
                keep_pref.append(t)
        n_keep = len(keep_vars)
        cc = np.empty((len(self._values), n_keep + 1), dtype=np.float64)
        for vi, val in enumerate(self._values):
            base = sum(1 for n, fv in fixed.items() if fv == val)
            cc[vi] = self._count_cost[vi][base:base + n_keep + 1]
        sliced = ResourceConstraint(self._name, keep_vars, keep_pref,
                                    self._values, cc)
        if bias:
            # Fold the fixed positions' preference cost into the first
            # remaining pref row (exact: added once per assignment).
            if keep_pref:
                sliced._pref[0] = sliced._pref[0] + bias
            else:
                sliced = LinearConstraint(self._name, [], [], bias)  # type: ignore
        return sliced

    def params(self) -> Dict[str, Any]:
        return {
            "class": "resource",
            "pref": [[float(x) for x in t] for t in self._pref],
            "values": list(self._values),
            "count_cost": [[float(x) for x in row] for row in self._count_cost],
        }

    def _simple_repr(self):
        return {
            REPR_MODULE: type(self).__module__,
            REPR_QUALNAME: type(self).__qualname__,
            "name": self._name,
            "variables": simple_repr(self._variables),
            "pref": [[float(x) for x in t] for t in self._pref],
            "values": list(self._values),
            "count_cost": [[float(x) for x in row] for row in self._count_cost],
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["name"], from_repr(r["variables"]), r["pref"],
                   r["values"], r["count_cost"])


#: name → class, for YAML loading (`type: structured` blocks).
STRUCTURED_CLASSES: Dict[str, type] = {
    "linear": LinearConstraint,
    "cardinality": CardinalityConstraint,
    "resource": ResourceConstraint,
}


def structured_from_params(
    name: str, variables: Sequence[Variable], params: Dict[str, Any]
) -> StructuredConstraint:
    """Rebuild a structured constraint from its :meth:`params` dict."""
    cls_name = params.get("class")
    if cls_name == "linear":
        return LinearConstraint(name, variables, params["tables"],
                                params.get("bias", 0.0))
    if cls_name == "cardinality":
        return CardinalityConstraint(name, variables, params["value"],
                                     params["count_cost"])
    if cls_name == "resource":
        return ResourceConstraint(name, variables, params["pref"],
                                  params["values"], params["count_cost"])
    raise ValueError(f"unknown structured constraint class {cls_name!r}")


def detect_structure(
    c: Constraint, max_entries: int = 4096
) -> Optional[StructuredConstraint]:
    """Try to recover structure from an opaque constraint.

    Currently detects exact separability (→ :class:`LinearConstraint`) by
    densifying small constraints and checking the rank-1-in-cost-space
    decomposition reconstructs the table exactly.  Covers the seed model's
    ``ExpressionFunction`` sums like ``"x1 + 2*x2 - x3"``.  Returns None if
    no structure is found or the constraint is too large to check.
    """
    if isinstance(c, StructuredConstraint):
        return c
    shape = c.shape
    if not shape or int(np.prod(shape)) > max_entries:
        return None
    t = np.asarray(c.to_tensor(), dtype=np.float64)
    if not np.all(np.isfinite(t)):
        return None
    origin = (0,) * len(shape)
    ref = t[origin]
    rows: List[np.ndarray] = []
    for p, n in enumerate(shape):
        idx = list(origin)
        row = np.empty(n, dtype=np.float64)
        for d in range(n):
            idx[p] = d
            row[d] = t[tuple(idx)] - ref
        rows.append(row)
        idx[p] = 0
    recon = np.full(shape, ref, dtype=np.float64)
    for p, row in enumerate(rows):
        bshape = [1] * len(shape)
        bshape[p] = shape[p]
        recon = recon + row.reshape(bshape)
    if not np.array_equal(recon.astype(DEFAULT_TYPE), t.astype(DEFAULT_TYPE)):
        return None
    return LinearConstraint(c.name, c.dimensions, rows, float(ref))


def has_structured(dcop) -> bool:
    return any(
        isinstance(c, StructuredConstraint) for c in dcop.constraints.values()
    )


def lower_structured_for_inference(dcop, max_table_entries: int = MAX_DENSIFY_ENTRIES):
    """DPOP-facing lowering: rewrite a DCOP so exact-inference engines see
    only constraints they can process without materializing D^arity.

    * Linear primitives project symbolically: each becomes ``arity`` unary
      matrix relations (one per scope position, bias folded into the first)
      — DPOP's UTIL join then never sees the high-arity scope at all.
    * Cardinality primitives stay structured (the frontier rung handles
      them table-free); callers that must densify go through the
      :data:`MAX_DENSIFY_ENTRIES` guard.

    Returns a new DCOP sharing Variable/Domain objects with the input.
    """
    from pydcop_tpu.dcop.dcop import DCOP

    out = DCOP(
        dcop.name,
        objective=dcop.objective,
        domains=dict(dcop.domains),
        variables=dict(dcop.variables),
        agents=dict(dcop.agents),
    )
    out.external_variables = dict(dcop.external_variables)
    out.dist_hints = dcop.dist_hints
    for c in dcop.constraints.values():
        if not isinstance(c, StructuredConstraint):
            out.add_constraint(c)
            continue
        for prim in c.lower():
            if isinstance(prim, LinearConstraint):
                for p, (v, row) in enumerate(zip(prim.dimensions, prim.tables)):
                    m = np.asarray(row, dtype=np.float64)
                    if p == 0:
                        m = m + prim.bias
                    out.add_constraint(
                        NAryMatrixRelation([v], m.astype(DEFAULT_TYPE),
                                           f"{prim.name}__u{p}")
                    )
            else:
                out.add_constraint(prim)
    return out
