"""`pydcop_tpu` CLI entry point.

Equivalent capability to the reference's pydcop/dcop_cli.py (:62-207):
global options (-v verbosity, --timeout with a forced-exit slack timer,
--output, --version, --log) and the subcommand tree (solve, run,
orchestrator, agent, distribute, graph, generate, batch, replica_dist,
consolidate) — plus ``serve``, the continuous-batching solve service
(no reference twin; docs/serving.rst), and ``analyze``, the program
auditor + source lint (docs/analysis.rst).
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

#: extra seconds after --timeout before the process force-exits
#: (reference: dcop_cli.py TIMEOUT_SLACK = 40)
TIMEOUT_SLACK = 40


def make_parser() -> argparse.ArgumentParser:
    from pydcop_tpu.version import __version__

    parser = argparse.ArgumentParser(
        prog="pydcop_tpu",
        description="TPU-native DCOP solving (pyDCOP capability set)",
    )
    parser.add_argument("-v", "--verbosity", type=int, default=0,
                        choices=[0, 1, 2, 3])
    parser.add_argument("--version", action="version",
                        version=f"pydcop_tpu {__version__}")
    parser.add_argument("-t", "--timeout", type=float, default=None,
                        help="global timeout in seconds")
    parser.add_argument("--strict_timeout", type=float, default=None,
                        help="hard wall-clock limit (forced exit)")
    parser.add_argument("-o", "--output", default=None,
                        help="result output file")
    parser.add_argument("--log", default=None,
                        help="logging fileConfig (accepted for "
                             "compatibility)")

    subparsers = parser.add_subparsers(dest="command", required=True)
    from pydcop_tpu.commands import (
        agent,
        analyze,
        batch,
        checkpoint_cmd,
        consolidate,
        distribute,
        generate,
        graph,
        orchestrator,
        portfolio,
        replica_dist,
        run,
        serve,
        serve_replica,
        solve,
        twin,
    )

    for module in (solve, run, orchestrator, agent, distribute, graph,
                   generate, batch, replica_dist, consolidate, serve,
                   serve_replica, portfolio, twin, analyze,
                   checkpoint_cmd):
        module.set_parser(subparsers)
    return parser


def _setup_logging(verbosity: int, log_conf) -> None:
    if log_conf:
        from logging import config as logging_config

        logging_config.fileConfig(log_conf)
        return
    levels = {0: logging.ERROR, 1: logging.WARNING, 2: logging.INFO,
              3: logging.DEBUG}
    logging.basicConfig(
        level=levels.get(verbosity, logging.ERROR),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )


def main(argv=None) -> int:
    parser = make_parser()
    args = parser.parse_args(argv)
    _setup_logging(args.verbosity, args.log)

    # forced-exit watchdog: even if a solver wedges, the CLI returns
    # (reference: dcop_cli.py:162-207)
    hard_limit = args.strict_timeout or (
        args.timeout + TIMEOUT_SLACK if args.timeout else None
    )
    if hard_limit:
        def force_exit():
            print('{"status": "STOPPED", "reason": "forced timeout"}',
                  file=sys.stderr)
            os._exit(42)

        watchdog = threading.Timer(hard_limit, force_exit)
        watchdog.daemon = True
        watchdog.start()

    try:
        return args.func(args) or 0
    except KeyboardInterrupt:
        print('{"status": "STOPPED"}', file=sys.stderr)
        return 130


if __name__ == "__main__":
    sys.exit(main())
