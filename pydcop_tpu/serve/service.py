"""SolveService — the streaming front door over the batch engine.

A persistent in-process solve service: callers :meth:`submit` jobs
(instance + tenant + priority + optional deadline) from any thread, a
single scheduler thread routes them by shape signature into
continuously-batched :class:`~pydcop_tpu.serve.scheduler.BucketWorker`
buckets, and results stream back three ways — :meth:`result` (blocking
future), :meth:`stream` (per-job anytime-assignment iterator) and the
``serve.*`` topics on the process event bus (forwarded to ws/SSE GUI
clients by runtime/ui.py).

Scheduling policy, in the order the tick applies it:

1. **admission** — pending jobs (highest priority first, FIFO within a
   priority) fold into the free lanes of a running bucket whose target
   shape fits them; what remains opens new buckets, preferring
   prewarmed signatures so admission never pays a cold XLA compile on
   the hot path;
2. **stepping** — every occupied bucket advances one chunk; lanes that
   converge (or expire their deadline — counted as preempted) complete
   their jobs and free their slots at that same boundary;
3. **maintenance** — empty buckets close, and two under-filled buckets
   of the same signature merge (lane states copy verbatim, streams
   continue bit-identically).

Crash safety rides the PR 1 checkpoint/JID layer: with a
``journal_dir`` every submission is journaled (``jobs.jsonl``), every
completion registers a ``JID:`` line (the batch command's resume
protocol), and every occupied lane snapshots its state at periodic
chunk boundaries (atomic + CRC, runtime/checkpoint).  A restarted
service :meth:`resume`-s: completed jobs are skipped, in-flight jobs
re-seat at their last checkpointed chunk boundary and continue the
SAME stream — their results stay bit-identical to an uninterrupted
solve.  Journal lines cut short by a crash mid-append are skipped and
counted, never fatal, and done-job records are compacted away (atomic
rewrite) so a long-running service's journal stays bounded.

Fault isolation (ISSUE 7) is layered the way an OS supervises
processes:

* a **bucket step** that throws (XLA error, injected fault) tears down
  only that bucket: its jobs are bisected into isolated suspect groups
  and re-run from cycle 0, so the poison job is cornered while its
  healthy bucket-mates complete bit-identically (a fresh lane IS the
  standalone stream);
* a cornered **poison job** climbs a bounded ladder — retry with
  exponential backoff, then a sequential-fallback solve, then a
  terminal ``ERROR`` — and a lane whose float state goes NaN/Inf
  (device-side check at every chunk boundary) enters the same ladder;
* the **scheduler loop** itself is supervised: a tick that throws is
  relaunched with exponential backoff (the PR 1 watchdog's policy); if
  the restart budget is exhausted every pending job fails with
  :class:`~pydcop_tpu.serve.errors.ServiceStopped` — ``result()``
  raises, it never hangs;
* **admission control** keeps overload a designed-for state: a bounded
  pending queue with priority-aware shedding, per-tenant quotas and
  deadline-infeasibility rejection, all surfaced as structured
  :class:`~pydcop_tpu.serve.errors.ServiceOverloaded` errors with a
  retry-after hint.

All of it is observable (``serve.fault.*`` events +
:class:`~pydcop_tpu.runtime.stats.ServeCounters`) and deterministically
testable through the seedable serve faults in runtime/faults.py.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import queue
import tempfile
import threading
from collections import deque
from time import monotonic, sleep
from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.batch.bucketing import InstanceDims, bucket_signature
from pydcop_tpu.batch.cache import CompileCache, global_compile_cache
from pydcop_tpu.batch.engine import (
    DEFAULT_MAX_CYCLES,
    SUPPORTED_ALGOS,
    BatchItem,
    BucketMeta,
    _params_key,
    adapter_for,
    runner_cache_key,
)
from pydcop_tpu.runtime.events import event_bus, send_serve
from pydcop_tpu.runtime.faults import (
    FaultPlan,
    HeartbeatWriter,
    InjectedFault,
    ServeFaultInjector,
)
from pydcop_tpu.runtime.stats import ServeCounters
from pydcop_tpu.serve.errors import (
    DeadlineInfeasible,
    ServiceOverloaded,
    ServiceStopped,
)
from pydcop_tpu.serve.scheduler import (
    BucketWorker,
    fits,
    restore_lane_state,
    serve_target,
    warm_bucket_runner,
)

#: journal file names inside ``journal_dir``
JOBS_JOURNAL = "jobs.jsonl"
PROGRESS_FILE = "progress_serve"
CKPT_SUBDIR = "ckpt"


def restore_target(meta: Dict[str, Any]) -> InstanceDims:
    """The exact padded target a checkpointed lane must re-seat at —
    its state leaves are target-shaped, so re-seating anywhere else
    would fail loudly in restore_lane_state.  Shared by the service's
    resume path and the fleet's failover re-seat (serve/fleet.py)."""
    t = dict(meta["target"])
    t["arities"] = tuple(t["arities"])
    t["F"] = tuple(t["F"])
    return InstanceDims(**t)


@dataclasses.dataclass
class ServeJob:
    """One submitted job and its runtime bookkeeping."""

    jid: str
    dcop: Any
    algo: str
    algo_params: Dict[str, Any]
    seed: int
    tenant: str
    priority: int
    deadline_s: Optional[float]
    deadline_at: Optional[float]  # monotonic absolute deadline
    label: Optional[str]
    source_file: Optional[str]
    stream: bool
    submitted_at: float
    seq: int
    # scheduler-side state
    spec: Any = None
    spec_future: Any = None  # in-flight background spec build
    restore: Optional[Tuple] = None  # checkpointed lane restore tuple
    resumed: bool = False
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    result: Optional[SolveResult] = None
    events: "queue.Queue" = dataclasses.field(
        default_factory=lambda: queue.Queue(maxsize=1024)
    )
    # fault-isolation / admission bookkeeping
    counters: Optional[ServeCounters] = None
    in_backlog: bool = False  # counted against the bounded queue
    retries: int = 0  # quarantine re-admissions consumed
    not_before: float = 0.0  # monotonic backoff gate on re-admission
    isolate_key: Optional[str] = None  # quarantine group tag
    lossy_notified: bool = False  # one serve.stream.lossy per job
    service_stopped: bool = False  # failed by a dead scheduler
    # solution-cache bookkeeping (serve/memo.py): one probe per job,
    # kept so completion can insert without re-canonicalizing
    memo_checked: bool = False   # probe ran (exactly once per job)
    memo_probe: Any = None       # the MemoProbe (hit artifacts)
    memo_served: bool = False    # answered from cache, skip insert

    def restore_target(self) -> InstanceDims:
        """The exact padded target a checkpointed job must re-seat at
        (its state leaves are target-shaped)."""
        assert self.restore is not None
        return restore_target(self.restore[0])

    def emit(self, event: str, payload: Dict[str, Any]) -> None:
        send_serve(event, payload)
        if self.stream:
            try:
                self.events.put_nowait({"event": event, **payload})
            except queue.Full:
                # slow consumer: drop, never block solve — but COUNT
                # the drop against the TENANT (the twin's SLO report
                # charges a lossy gold stream against attainment) and
                # tell the stream once that it is lossy, so a starved
                # consumer is an alert, not a mystery
                if self.counters is not None:
                    self.counters.drop_event(self.tenant)
                if not self.lossy_notified:
                    self.lossy_notified = True
                    send_serve("stream.lossy", {"jid": self.jid})


class SolveService:
    """Continuous-batching solve service over the batch engine.

    >>> # sketch:
    >>> # svc = SolveService(lanes=8)
    >>> # svc.start()
    >>> # jid = svc.submit(dcop, "mgm", tenant="t1", priority=1)
    >>> # res = svc.result(jid, timeout=30)
    >>> # svc.stop()

    ``lanes`` is the slot count of each bucket the service opens.
    ``cache=None`` shares the process-wide compile cache (so a restart
    in the same process reuses every compiled runner); pass a fresh
    :class:`CompileCache` to isolate (the tests do).  With
    ``journal_dir`` the service is crash-safe — see the module
    docstring.  ``start()`` spawns the scheduler thread; tests may
    instead drive :meth:`tick` synchronously for deterministic
    schedules.

    Overload knobs: ``max_pending`` bounds the not-yet-admitted queue
    (a submit beyond it sheds — the lowest-priority queued job if the
    arrival outranks it, else the arrival itself, as
    :class:`ServiceOverloaded`); ``tenant_quota`` caps one tenant's
    open (submitted-but-unfinished) jobs.  Fault knobs:
    ``max_job_retries`` bounds the quarantine retry ladder before the
    sequential-fallback escalation, ``max_scheduler_restarts`` bounds
    the supervisor's tick-loop relaunches, and
    ``backoff_base``/``backoff_max`` shape both exponential backoffs
    (the PR 1 watchdog's policy, runtime/process.py).  ``fault_plan``
    arms the seedable serve-fault injector (runtime/faults.py) for
    deterministic chaos testing.
    """

    def __init__(
        self,
        lanes: int = 4,
        cache: Optional[CompileCache] = None,
        counters: Optional[ServeCounters] = None,
        max_cycles: int = DEFAULT_MAX_CYCLES,
        journal_dir: Optional[str] = None,
        checkpoint_every: int = 4,
        merge_below: float = 0.5,
        tick_interval: float = 0.02,
        max_buckets: Optional[int] = None,
        max_pending: Optional[int] = None,
        tenant_quota: Optional[int] = None,
        max_job_retries: int = 1,
        max_scheduler_restarts: int = 5,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        journal_compact_bytes: int = 1 << 20,
        fault_plan: Optional[FaultPlan] = None,
        replica: Optional[str] = None,
        heartbeat_path: Optional[str] = None,
        on_complete: Optional[Callable[["ServeJob", SolveResult],
                                       None]] = None,
        memo=None,
    ):
        self.lanes = int(lanes)
        self.max_buckets = max_buckets
        self.cache = cache if cache is not None else global_compile_cache()
        #: fleet identity: stamped on every completed job's
        #: ``metrics()["serve"]`` and the counters summary, so failover
        #: paths are auditable post-hoc; None for a standalone service
        self.replica = replica
        self.counters = (
            counters if counters is not None
            else ServeCounters(replica=replica)
        )
        if replica is not None and self.counters.replica is None:
            self.counters.replica = replica
        #: completion hook (the fleet's journal-streaming tap): called
        #: after a job turns terminal, on whichever thread completed it
        self.on_complete = on_complete
        self.max_cycles = int(max_cycles)
        self.journal_dir = journal_dir
        self.checkpoint_every = int(checkpoint_every)
        self.merge_below = float(merge_below)
        self.tick_interval = float(tick_interval)
        self.max_pending = max_pending
        self.tenant_quota = tenant_quota
        self.max_job_retries = int(max_job_retries)
        self.max_scheduler_restarts = int(max_scheduler_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.journal_compact_bytes = int(journal_compact_bytes)

        self._jobs: Dict[str, ServeJob] = {}
        self._pending: "deque[ServeJob]" = deque()
        self._workers: List[BucketWorker] = []
        self._prewarmed: Dict[Tuple[str, Tuple], List[InstanceDims]] = {}
        self._lock = threading.RLock()
        self._journal_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._thread_started = False
        self._failure: Optional[BaseException] = None
        self._prep_pool = None  # spec-build executor (started threads)
        self._seq = 0
        self._qseq = 0  # quarantine isolation-group counter
        self._ticks = 0  # scheduler passes (the serve faults' clock)
        self._backlog = 0  # submitted-but-unadmitted jobs
        self._tenant_open: Dict[str, int] = {}
        self._done_rate: Optional[float] = None  # completions/sec EMA
        self._last_done_t: Optional[float] = None
        self._injector = (
            ServeFaultInjector(fault_plan) if fault_plan is not None
            and fault_plan.serve_faults() else None
        )
        self._done_jids: set = set()
        #: liveness channel to a fleet supervisor (PR 1's heartbeat
        #: file protocol): the TICK loop touches it, not a side thread,
        #: so staleness faithfully reflects a wedged/killed scheduler
        self._hb = (
            HeartbeatWriter(heartbeat_path)
            if heartbeat_path is not None else None
        )
        self._stall_until = 0.0  # injected stall gate (stall_replica)
        #: (factor, exempt_priority) applied to every bucket's
        #: deadline-chunk clamp — the SLO ladder's rung-2 lever
        self._deadline_pressure: Tuple[float, Optional[int]] = (1.0, None)
        #: cross-request solution cache (serve/memo.py, ISSUE 18):
        #: ``memo`` is None/False (disabled), True / a MemoConfig
        #: (build one, persisted beside the journal when there is
        #: one), or a ready MemoCache (the fleet passes per-replica
        #: caches wired with its sharing tap)
        self.memo = None
        if memo is not None and memo is not False:
            from pydcop_tpu.serve.memo import (
                MEMO_SUBDIR, MemoCache, MemoConfig,
            )

            if isinstance(memo, MemoCache):
                self.memo = memo
            else:
                cfg = memo if isinstance(memo, MemoConfig) else None
                mdir = (os.path.join(journal_dir, MEMO_SUBDIR)
                        if journal_dir else None)
                self.memo = MemoCache(cfg, directory=mdir)
        if journal_dir:
            os.makedirs(os.path.join(journal_dir, CKPT_SUBDIR),
                        exist_ok=True)
            self._done_jids = self._load_done_jids()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        from concurrent.futures import ThreadPoolExecutor

        self._stop = False
        self._thread_started = True
        # instance compilation (spec building) runs OFF the scheduler
        # thread so admission prep overlaps bucket stepping; manual
        # tick() driving (tests) stays synchronous — no pool, specs
        # build inline, schedules are deterministic
        self._prep_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="serve-prep"
        )
        self._thread = threading.Thread(
            target=self._loop, name="solve-service", daemon=True
        )
        self._thread.start()

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop the scheduler thread.  ``drain=True`` waits until every
        submitted job completed (bounded by ``timeout``);
        ``drain=False`` abandons in-flight work where it stands — with
        a journal this is the crash-with-checkpoints path a later
        :meth:`resume` recovers from."""
        if drain:
            try:
                self.wait_all(timeout=timeout)
            except ServiceStopped:
                pass  # nothing left to drain: the scheduler is dead
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._prep_pool is not None:
            self._prep_pool.shutdown(wait=False)
            self._prep_pool = None

    def halt(self) -> None:
        """Hard-stop: the thread-hosted twin of ``kill -9``.  The
        scheduler exits at the next tick boundary WITHOUT draining,
        completing or journaling anything further; in-flight lanes are
        abandoned where they stand and only the journal — submissions,
        ``JID:`` completion lines, lane checkpoints — survives for a
        peer replica or a restarted service to recover from.  Blocked
        :meth:`result` callers raise :class:`ServiceStopped` through
        the liveness gate instead of hanging."""
        with self._lock:
            self._failure = RuntimeError(
                "replica halted (injected kill)"
            )
        self._stop = True
        self._wake.set()

    def set_deadline_pressure(self, factor: float,
                              exempt_priority: Optional[int] = None
                              ) -> None:
        """Tighten (or relax) the deadline-driven chunk shrinking of
        every bucket: lanes whose job has a deadline see only
        ``factor`` of their remaining budget when
        :func:`~pydcop_tpu.algorithms.base.clamp_chunk_to_deadline`
        sizes their next chunk, so they reach chunk boundaries — the
        service's only admission/completion points — sooner.  Jobs at
        priority >= ``exempt_priority`` are exempt (the SLO ladder
        clamps silver/bronze lanes while gold runs full chunks;
        docs/scenarios.rst "The SLO guardrail ladder").  ``factor=1``
        restores normal behavior.  Applies to current buckets and
        every bucket opened later."""
        with self._lock:
            self._deadline_pressure = (float(factor), exempt_priority)
            for w in self._workers:
                w.deadline_pressure = float(factor)
                w.pressure_exempt_priority = exempt_priority

    def stall_for(self, duration: float) -> None:
        """Wedge the NEXT scheduler tick for ``duration`` seconds (the
        fleet's ``stall_replica`` injection): the sleep happens inside
        :meth:`tick`, on the scheduler thread itself, so the heartbeat
        file goes genuinely stale — from the supervisor's viewpoint
        this is indistinguishable from a real wedged collective."""
        with self._lock:
            self._stall_until = monotonic() + float(duration)

    def __enter__(self) -> "SolveService":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    def _raise_if_dead(self) -> None:
        """The liveness gate behind every blocking wait: a scheduler
        that died (supervisor exhausted, thread killed) or was stopped
        with work in flight will never complete anything again —
        callers get :class:`ServiceStopped`, not a silent hang."""
        with self._lock:
            failure = self._failure
        if failure is not None:
            raise ServiceStopped(
                f"scheduler thread died: {failure!r}"
            )
        if not self._thread_started:
            return  # synchronous tick() driving: no thread to die
        t = self._thread
        if t is not None and not t.is_alive() and not self._stop:
            raise ServiceStopped(
                "scheduler thread is dead (exited without recording a "
                "failure)"
            )
        if t is None and self._stop:
            raise ServiceStopped("service was stopped")

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job is done; False on timeout.
        Raises :class:`ServiceStopped` instead of blocking forever when
        the scheduler thread is dead."""
        deadline = None if timeout is None else monotonic() + timeout
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            while not job.done.is_set():
                self._raise_if_dead()
                remain = (
                    None if deadline is None else deadline - monotonic()
                )
                if remain is not None and remain <= 0:
                    return False
                job.done.wait(
                    0.1 if remain is None else min(0.1, remain)
                )
        return True

    # -- front door ---------------------------------------------------------

    def submit(
        self,
        dcop,
        algo: str,
        algo_params: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        tenant: str = "default",
        priority: int = 0,
        deadline_s: Optional[float] = None,
        label: Optional[str] = None,
        source_file: Optional[str] = None,
        stream: bool = False,
        spec: Any = None,
        _jid: Optional[str] = None,
        _journal: bool = True,
        _restore: Optional[Tuple] = None,
    ) -> str:
        """Enqueue one solve job; returns its job id immediately.

        ``priority`` orders admission (higher first, FIFO within a
        level); ``deadline_s`` is a per-tenant latency budget in
        seconds from now — the scheduler shrinks the job's chunks as
        the budget tightens and completes it as ``TIMEOUT`` (counted
        preempted) when it expires.  ``source_file`` makes the job
        crash-resumable when the service has a journal.  ``spec``
        optionally hands over an already-compiled instance (the batch
        engine's adapter spec) — callers that prepare instances
        themselves skip the service's prep stage entirely.

        Admission control (raises instead of queueing unboundedly):
        :class:`DeadlineInfeasible` for a deadline that is already
        unmeetable, :class:`ServiceOverloaded` when the tenant is over
        quota or the bounded pending queue is full and the arrival
        does not outrank any queued job (a lower-priority queued job
        is shed in its favor otherwise, completed ``ERROR`` and
        counted ``jobs_shed``).  :class:`ServiceStopped` if the
        scheduler thread is already dead.  Resumed jobs bypass the
        checks — they were admitted before the crash."""
        self._raise_if_dead()
        victim: Optional[ServeJob] = None
        if _jid is None:
            if deadline_s is not None and deadline_s <= 0:
                self.counters.inc("jobs_shed")
                send_serve("job.rejected", {
                    "tenant": tenant, "reason": "deadline infeasible",
                    "deadline_s": deadline_s,
                })
                raise DeadlineInfeasible(
                    f"deadline_s={deadline_s} is already expired at "
                    f"submit time"
                )
            with self._lock:
                if (
                    self.tenant_quota is not None
                    and self._tenant_open.get(tenant, 0)
                    >= self.tenant_quota
                ):
                    self.counters.inc("quota_rejections")
                    send_serve("job.rejected", {
                        "tenant": tenant, "reason": "tenant quota",
                        "quota": self.tenant_quota,
                    })
                    raise ServiceOverloaded(
                        f"tenant {tenant!r} at quota "
                        f"({self.tenant_quota} open jobs)",
                        retry_after=self._retry_after(),
                        tenant=tenant,
                    )
                if (
                    self.max_pending is not None
                    and self._backlog >= self.max_pending
                ):
                    victim = self._shed_candidate(int(priority))
                    if victim is None:
                        self.counters.inc("jobs_shed")
                        send_serve("job.rejected", {
                            "tenant": tenant, "reason": "queue full",
                            "max_pending": self.max_pending,
                        })
                        raise ServiceOverloaded(
                            f"pending queue full "
                            f"({self.max_pending} jobs)",
                            retry_after=self._retry_after(),
                            tenant=tenant,
                        )
                    self._pending.remove(victim)
        if victim is not None:
            # priority-aware shedding: the lowest-priority queued job
            # makes room for the higher-priority arrival — completed
            # as a structured ERROR, never dropped silently
            self.counters.inc("jobs_shed")
            victim.emit("job.shed", {
                "jid": victim.jid, "tenant": victim.tenant,
                "priority": victim.priority,
                "displaced_by_priority": int(priority),
            })
            self._complete(victim, SolveResult(
                status="ERROR", assignment={}, cost=None,
                violation=None, cycle=0, msg_count=0, msg_size=0.0,
                time=monotonic() - victim.submitted_at,
            ), error="shed: displaced by a higher-priority arrival "
                     "while the pending queue was full")
        with self._lock:
            self._seq += 1
            if _jid is not None:
                # a resumed job keeps its journaled id; advance the
                # sequence past it so fresh submissions cannot collide
                tail = _jid.rsplit("-", 1)[-1]
                if tail.isdigit():
                    self._seq = max(self._seq, int(tail))
            jid = _jid or f"job-{self._seq:06d}"
            job = ServeJob(
                jid=jid,
                dcop=dcop,
                algo=algo,
                algo_params=dict(algo_params or {}),
                seed=int(seed),
                tenant=tenant,
                priority=int(priority),
                deadline_s=deadline_s,
                deadline_at=(
                    monotonic() + deadline_s
                    if deadline_s is not None else None
                ),
                label=label,
                source_file=source_file,
                stream=stream,
                submitted_at=monotonic(),
                seq=self._seq,
                counters=self.counters,
            )
            job.spec = spec
            # the checkpoint restore tuple rides the SAME lock as the
            # pending append: a running scheduler can never observe the
            # job restore-less and re-run it from cycle 0 by accident
            job.restore = _restore
            self._jobs[jid] = job
            self._pending.append(job)
            job.in_backlog = True
            self._backlog += 1
            self._tenant_open[tenant] = (
                self._tenant_open.get(tenant, 0) + 1
            )
        if (
            job.spec is None
            and self._prep_pool is not None
            and algo in SUPPORTED_ALGOS
        ):
            job.spec_future = self._prep_pool.submit(
                self._build_spec, job
            )
        self.counters.inc("jobs_submitted")
        if _journal:
            self._journal_submit(job)
        job.emit("job.submitted", {
            "jid": jid, "tenant": tenant, "priority": job.priority,
            "algo": algo,
        })
        self._wake.set()
        return jid

    def _shed_candidate(self, priority: int) -> Optional[ServeJob]:
        """The queued job a higher-priority arrival may displace: the
        lowest-priority pending job strictly below ``priority`` (the
        newest among equals — FIFO fairness for the older ones).
        Caller holds the lock."""
        victim = None
        for j in self._pending:
            if j.priority >= priority:
                continue
            if victim is None or (j.priority, -j.seq) < (
                victim.priority, -victim.seq
            ):
                victim = j
        return victim

    def _retry_after(self) -> float:
        """Back-off hint for rejected submits: the backlog drained at
        the observed completion rate, clamped to [tick, 30s]."""
        rate = self._done_rate
        if not rate or rate <= 0:
            return 1.0
        est = self._backlog / rate
        return round(min(30.0, max(self.tick_interval, est)), 3)

    def result(self, jid: str, timeout: Optional[float] = None
               ) -> SolveResult:
        """Block until job ``jid`` completes and return its result.
        Raises :class:`ServiceStopped` — instead of blocking forever —
        when the scheduler thread died or the service was stopped with
        the job still in flight."""
        with self._lock:
            job = self._jobs[jid]
        deadline = None if timeout is None else monotonic() + timeout
        while not job.done.is_set():
            self._raise_if_dead()
            remain = None if deadline is None else deadline - monotonic()
            if remain is not None and remain <= 0:
                raise TimeoutError(
                    f"job {jid} not done within {timeout}s"
                )
            job.done.wait(0.1 if remain is None else min(0.1, remain))
        with self._lock:
            stopped, failure = job.service_stopped, self._failure
            res = job.result
        if stopped:
            raise ServiceStopped(
                f"job {jid} failed: scheduler thread died "
                f"({failure!r})"
            )
        assert res is not None
        return res

    def stream(self, jid: str, timeout: float = 60.0
               ) -> Iterator[Dict[str, Any]]:
        """Iterate job ``jid``'s lifecycle events — admission, anytime
        assignments at chunk boundaries (``job.progress``: cycle +
        current cost), completion — until the job is done.  The job
        must have been submitted with ``stream=True``.  ``timeout``
        bounds the gap between consecutive events; a dead scheduler
        raises :class:`ServiceStopped` instead of a silent stall."""
        with self._lock:
            job = self._jobs[jid]
        deadline = monotonic() + timeout
        while True:
            remain = deadline - monotonic()
            if remain <= 0:
                return
            try:
                evt = job.events.get(timeout=min(0.1, remain))
            except queue.Empty:
                if job.events.empty():
                    self._raise_if_dead()
                continue
            yield evt
            if evt.get("event") == "job.done":
                return
            deadline = monotonic() + timeout

    def churn_event(self, tenant: Optional[str] = None) -> int:
        """A churn event (live mutation burst, scenario epoch, tenant
        redeploy) makes cached RESULTS stale even though the service
        itself is fine: drop the tenant's solution-cache namespace
        (every tenant when None).  No-op without a memo cache; returns
        the number of entries invalidated."""
        if self.memo is None:
            return 0
        return self.memo.churn_event(tenant)

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            workers = [
                {"algo": w.algo, "signature": list(map(str, w.signature)),
                 "occupied": w.occupied, "lanes": w.B, "steps": w.steps}
                for w in self._workers
            ]
            pending = len(self._pending)
        out = {
            "serve": self.counters.as_dict(),
            "cache": self.cache.stats(),
            "workers": workers,
            "pending": pending,
        }
        if self.memo is not None:
            out["memo"] = self.memo.stats()
        return out

    # -- prewarm ------------------------------------------------------------

    def prewarm(
        self,
        items: Sequence[Tuple],
        lanes: Optional[int] = None,
        block: bool = False,
    ) -> None:
        """Compile bucket runners for expected traffic ahead of
        arrival.  ``items`` is a sequence of ``(dcop, algo)`` or
        ``(dcop, algo, algo_params)`` tuples describing the shapes the
        service expects; one runner compiles per (algo, params, shape
        family) at the pooled serve target, on the compile cache's
        background thread (``block=True`` joins — tests and
        warm-before-open services).  Buckets opened later for fitting
        traffic resolve to the SAME cache key, so their admission is a
        hit, not a cold compile."""
        lanes = int(lanes or self.lanes)
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for it in items:
            dcop, algo = it[0], it[1]
            params = dict(it[2]) if len(it) > 2 and it[2] else {}
            if algo not in SUPPORTED_ALGOS:
                # e.g. a predicted frontier exact-search config: it
                # has no bucket runner to warm (it solves 1-by-1 on
                # the fallback path) — count it so the prewarm keyset
                # stays auditable instead of silently shrinking
                self.counters.inc("prewarm_skipped_exact")
                continue
            adapter = adapter_for(algo)
            spec = adapter.build_spec(
                BatchItem(dcop, algo, algo_params=params)
            )
            g = groups.setdefault(
                (algo, _params_key(params), spec.dims.family_key),
                {"adapter": adapter, "params": params, "dims": []},
            )
            g["dims"].append(spec.dims)
        from pydcop_tpu.algorithms.base import default_chunk

        entries = []
        for (algo, pkey, _fam), g in sorted(
            groups.items(), key=lambda kv: str(kv[0])
        ):
            target = serve_target(g["dims"])
            self._prewarmed.setdefault((algo, pkey), []).append(target)
            # the worker's own chunk policy (the PRNG stream depends on
            # it, so the prewarmed key must use the same)
            chunk = default_chunk(None, False, False, None,
                                  self.max_cycles)
            key = runner_cache_key(
                algo, pkey, bucket_signature(target, lanes), chunk
            )
            adapter, params = g["adapter"], g["params"]
            entries.append((
                key,
                lambda a=adapter, t=target, p=params, b=lanes, c=chunk:
                warm_bucket_runner(
                    a, t, p, b, c,
                    aot=getattr(self.cache, "exports_artifacts", False),
                ),
            ))
        self.counters.inc("prewarmed_runners", len(entries))
        send_serve("prewarm.scheduled", {"runners": len(entries)})
        if entries:
            self.cache.prewarm(entries, block=block)

    def prewarm_predicted(
        self,
        dcops: Sequence[Any],
        model=None,
        grid=None,
        block: bool = False,
    ):
        """Portfolio-informed prewarm: let the learned cost model (or
        its heuristic fallback) pick the expected config for each
        anticipated instance, then compile bucket runners for the
        batch-eligible picks ahead of arrival — the predicted configs
        decide WHICH (algo, params, shape-family) signatures are worth
        paying for, instead of the caller hand-listing them
        (docs/portfolio.rst).  ``model`` is a CostModel, a path, or
        None (fallback policy).  Returns the chosen configs, one per
        dcop."""
        from pydcop_tpu.portfolio.select import prewarm_predicted

        return prewarm_predicted(self, dcops, model=model, grid=grid,
                                 block=block)

    def prewarm_targets(
        self,
        items: Sequence[Tuple[str, Optional[Dict[str, Any]],
                              InstanceDims]],
        block: bool = False,
    ) -> int:
        """Compile bucket runners at EXACT padded targets — the
        re-seat twin of :meth:`prewarm`.  A journal-checkpointed job
        must re-seat at the very target its state leaves were padded
        to (:func:`restore_target`), so admission-time warmth means
        warming THAT signature, not a freshly pooled one.  ``items``
        is a sequence of ``(algo, algo_params, target_dims)``;
        :meth:`resume` and the fleet's failover re-seat
        (serve/fleet.py) call this so a resumed-on-peer job pays zero
        new cache misses (pinned in tests/unit/test_fleet.py).
        Returns the number of runners scheduled."""
        from pydcop_tpu.algorithms.base import default_chunk

        entries = []
        for algo, params, target in items:
            if algo not in SUPPORTED_ALGOS:
                continue
            params = dict(params or {})
            pkey = _params_key(params)
            chunk = default_chunk(None, False, False, None,
                                  self.max_cycles)
            key = runner_cache_key(
                algo, pkey, bucket_signature(target, self.lanes), chunk
            )
            adapter = adapter_for(algo)
            self._prewarmed.setdefault((algo, pkey), []).append(target)
            entries.append((
                key,
                lambda a=adapter, t=target, p=params, b=self.lanes,
                c=chunk: warm_bucket_runner(
                    a, t, p, b, c,
                    aot=getattr(self.cache, "exports_artifacts", False),
                ),
            ))
        if not entries:
            return 0
        self.counters.inc("prewarmed_runners", len(entries))
        send_serve("prewarm.scheduled", {
            "runners": len(entries), "exact": True,
        })
        self.cache.prewarm(entries, block=block)
        return len(entries)

    # -- scheduler ----------------------------------------------------------

    def _loop(self) -> None:
        """The supervised scheduler loop.  A tick that throws — an
        exception the per-bucket isolation inside :meth:`tick` could
        not contain (admission logic, journal I/O, a backend falling
        over) — is relaunched with exponential backoff, reusing the
        PR 1 watchdog's policy (runtime/process.py).  When the restart
        budget is exhausted the scheduler is declared dead: every
        unfinished job fails with a ``ServiceStopped``-marked ERROR so
        blocked ``result()`` calls raise instead of hanging."""
        failures = 0
        while not self._stop:
            try:
                busy = self.tick()
            except Exception as e:
                failures += 1
                if failures > self.max_scheduler_restarts:
                    self._scheduler_died(e)
                    return
                delay = min(self.backoff_max,
                            self.backoff_base * (2 ** (failures - 1)))
                self.counters.inc("scheduler_restarts")
                send_serve("fault.scheduler_restart", {
                    "attempt": failures, "backoff": round(delay, 4),
                    "error": str(e),
                })
                if delay > 0:
                    self._wake.wait(delay)
                    self._wake.clear()
                continue
            failures = 0  # a clean tick refills the restart budget
            if not busy:
                self._wake.wait(self.tick_interval)
                self._wake.clear()

    def _scheduler_died(self, exc: BaseException) -> None:
        with self._lock:
            self._failure = exc
        send_serve("fault.scheduler_dead", {
            "error": str(exc),
            "restarts": self.max_scheduler_restarts,
        })
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            if job.done.is_set():
                continue
            with self._lock:
                job.service_stopped = True
            try:
                self._complete(job, SolveResult(
                    status="ERROR", assignment={}, cost=None,
                    violation=None, cycle=0, msg_count=0, msg_size=0.0,
                    time=monotonic() - job.submitted_at,
                ), error=f"scheduler died: {exc}")
            except Exception:  # the done flag must be set, no matter what
                job.done.set()

    def tick(self) -> bool:
        """One synchronous scheduler pass: admissions, one chunk step
        per occupied bucket (completions + slot reuse at each
        boundary), then maintenance.  Returns True while work remains.
        The background thread just calls this in a loop; tests call it
        directly for deterministic schedules.

        A bucket whose step throws is quarantined on the spot
        (:meth:`_quarantine_worker`) — the failure never escapes to
        the other buckets or, in thread mode, past the supervisor."""
        self._ticks += 1
        with self._lock:
            stall = self._stall_until
            self._stall_until = 0.0
        if stall:
            remain = stall - monotonic()
            if remain > 0:
                sleep(remain)  # wedged: the heartbeat goes stale too
        if self._hb is not None:
            try:
                self._hb.beat()
            except OSError:  # heartbeat dir vanished: stay alive
                pass
        inj = self._injector
        if inj is not None:
            f = inj.due("stall_tick", self._ticks)
            if f is not None:
                self.counters.inc("faults_injected")
                self.counters.inc("ticks_stalled")
                send_serve("fault.injected", {
                    "kind": "stall_tick", "tick": self._ticks,
                    "duration": f.duration,
                })
                sleep(f.duration)
        self._admit_pending()
        with self._lock:
            workers = list(self._workers)
        for w in workers:
            if w.occupied == 0:
                continue
            try:
                self._step_worker(w)
            except Exception as e:
                self._quarantine_worker(w, e)
        # boundary admissions into lanes just freed — this is the
        # continuous part of the batching
        self._admit_pending()
        self._maintain_workers()
        with self._lock:
            now = monotonic()
            return any(w.occupied for w in self._workers) or any(
                j.not_before <= now for j in self._pending
            )

    def _step_worker(self, w: BucketWorker) -> None:
        """Advance one bucket a chunk and settle its boundary:
        non-finite lanes are quarantined, finished lanes complete
        (with a host-side finiteness check on the final cost — the
        int-state families have no float leaf for the device check),
        progress streams and checkpoints follow."""
        inj = self._injector
        if inj is not None:
            jids = {ln.job.jid for ln in w.lanes if ln is not None}
            f = inj.due("raise_in_step", self._ticks, jids=jids)
            if f is not None:
                self.counters.inc("faults_injected")
                send_serve("fault.injected", {
                    "kind": "raise_in_step", "tick": self._ticks,
                    "jid": f.jid,
                })
                raise InjectedFault(
                    f"raise_in_step (fault plan, tick {self._ticks})"
                )
        forced: List[int] = []
        if inj is not None:
            for i, ln in enumerate(w.lanes):
                if ln is None or ln.converged:
                    continue
                f = inj.due("nan_lane", self._ticks, jid=ln.job.jid)
                if f is None:
                    continue
                self.counters.inc("faults_injected")
                send_serve("fault.injected", {
                    "kind": "nan_lane", "tick": self._ticks,
                    "jid": ln.job.jid, "lane": i,
                })
                if not w.poison_lane(i):
                    forced.append(i)  # int-state family: no float leaf
        finished = w.step()
        bad = set(w.nonfinite) | set(forced)
        for i in sorted(bad):
            lane = w.lanes[i]
            if lane is None:
                continue
            self.counters.inc("lanes_nan")
            send_serve("fault.nan_lane", {
                "jid": lane.job.jid, "lane": i,
                "cycle": int(lane.age),
            })
            w.release(i)
            self._requeue_or_escalate(
                lane.job,
                f"non-finite lane state at cycle {lane.age}",
            )
        for i, lane, status in finished:
            if i in bad or w.lanes[i] is None:
                continue  # already quarantined this boundary
            res = w.lane_result(i, lane, status)
            w.release(i)
            if res.cost is not None and not math.isfinite(float(res.cost)):
                self.counters.inc("lanes_nan")
                send_serve("fault.nan_lane", {
                    "jid": lane.job.jid, "lane": i, "cycle": res.cycle,
                })
                self._requeue_or_escalate(
                    lane.job, "non-finite final cost"
                )
                continue
            self._complete(lane.job, res)
        self._progress_events(w)
        self._checkpoint_worker(w)

    # -- quarantine ---------------------------------------------------------

    def _quarantine_worker(self, w: BucketWorker,
                           exc: BaseException) -> None:
        """A bucket step threw.  The failing step cannot identify the
        poison lane, so the bucket is torn down and its jobs bisected
        into two ISOLATED suspect groups, each re-run from cycle 0 in
        its own bucket: the group holding the poison fails again and
        splits further until the poison job is cornered as a
        singleton (and climbs the retry → sequential-fallback →
        ERROR ladder), while every healthy group completes — a fresh
        lane replays the standalone stream, so healthy results stay
        bit-identical to a fault-free run."""
        jobs = [ln.job for ln in w.lanes if ln is not None]
        with self._lock:
            if w in self._workers:
                self._workers.remove(w)
        self.counters.inc("buckets_failed")
        send_serve("fault.bucket_failed", {
            "algo": w.algo, "error": str(exc),
            "jobs": [j.jid for j in jobs],
            "signature": [str(s) for s in w.signature],
        })
        if len(jobs) <= 1:
            for job in jobs:
                self._requeue_or_escalate(
                    job, f"bucket step failed: {exc}"
                )
            return
        mid = (len(jobs) + 1) // 2
        for group in (jobs[:mid], jobs[mid:]):
            if not group:
                continue
            self._qseq += 1
            key = f"quarantine-{self._qseq}"
            for job in group:
                job.isolate_key = key
                job.restore = None
                self._requeue(job)
        send_serve("fault.bisect", {
            "jobs": len(jobs), "groups": 2,
        })

    def _requeue(self, job: ServeJob) -> None:
        with self._lock:
            if not job.in_backlog:
                job.in_backlog = True
                self._backlog += 1
            self._pending.append(job)
        self._wake.set()

    def _requeue_or_escalate(self, job: ServeJob, reason: str) -> None:
        """The poison-candidate ladder: bounded retry with exponential
        backoff in an isolated bucket, then the sequential-fallback
        escalation, then a terminal ERROR — a bad job always ends in a
        terminal status, never a hang, and never takes anyone down
        with it."""
        job.restore = None
        if job.isolate_key is None:
            self._qseq += 1
            job.isolate_key = f"quarantine-{self._qseq}"
        job.retries += 1
        if job.retries <= self.max_job_retries:
            delay = min(self.backoff_max,
                        self.backoff_base * (2 ** (job.retries - 1)))
            with self._lock:
                job.not_before = monotonic() + delay
            self.counters.inc("jobs_retried")
            send_serve("fault.retry", {
                "jid": job.jid, "attempt": job.retries,
                "backoff": round(delay, 4), "reason": reason,
            })
            self._requeue(job)
            return
        self._escalate_sequential(job, reason)

    def _escalate_sequential(self, job: ServeJob, reason: str) -> None:
        """Last rung before ERROR: solve the cornered job alone on the
        scheduler thread, outside every bucket (an XLA/vmap problem
        cannot follow it there).  A still-poisoned job — the fallback
        throws, its cost is non-finite, or a persistent injected fault
        targets it — completes as a terminal ERROR."""
        from pydcop_tpu.runtime.run import solve_result

        self.counters.inc("jobs_quarantined")
        send_serve("fault.quarantined", {
            "jid": job.jid, "reason": reason,
            "retries": job.retries,
        })
        inj = self._injector
        err: Optional[str] = None
        res: Optional[SolveResult] = None
        if inj is not None and inj.poisoned(job.jid):
            err = "injected poison persists (fault plan)"
        else:
            try:
                res = solve_result(
                    job.dcop, job.algo, algo_params=job.algo_params,
                    seed=job.seed,
                )
            except Exception as e:
                err = str(e)
            else:
                if res.cost is not None and not math.isfinite(
                    float(res.cost)
                ):
                    err = "non-finite cost from sequential fallback"
        if err is not None:
            self._complete(job, SolveResult(
                status="ERROR", assignment={}, cost=None,
                violation=None, cycle=0, msg_count=0, msg_size=0.0,
                time=monotonic() - job.submitted_at,
            ), error=f"quarantined: {reason}; {err}")
            return
        res.time = monotonic() - job.submitted_at
        self._complete(job, res)

    def _admit_pending(self) -> None:
        now = monotonic()
        with self._lock:
            pending = sorted(
                self._pending, key=lambda j: (-j.priority, j.seq)
            )
            self._pending.clear()
        leftover: List[ServeJob] = []
        not_ready: List[ServeJob] = []
        for job in pending:
            with self._lock:
                gated = job.not_before > now
            if gated:  # quarantine backoff gate
                not_ready.append(job)
                continue
            if self.memo is not None and not job.memo_checked:
                job.memo_checked = True
                if self._serve_from_memo(job):
                    continue
            ready = self._prepare(job)
            if ready is False:
                continue
            if ready is None:  # spec still building in the background
                not_ready.append(job)
                continue
            if job.algo not in SUPPORTED_ALGOS:
                self._solve_fallback(job)
                continue
            if not self._try_admit(job):
                leftover.append(job)
        if not_ready:
            with self._lock:
                self._pending.extend(not_ready)
        # open new buckets for whatever could not fold in — bounded by
        # ``max_buckets``: beyond it jobs queue for the next freed lane
        # instead of growing the working set without limit
        while leftover:
            with self._lock:
                full = (
                    self.max_buckets is not None
                    and len(self._workers) >= self.max_buckets
                )
            if full:
                with self._lock:
                    self._pending.extend(leftover)
                break
            leftover = self._open_worker_for(leftover)
        return

    @staticmethod
    def _build_spec(job: ServeJob):
        return adapter_for(job.algo).build_spec(BatchItem(
            job.dcop, job.algo, algo_params=job.algo_params,
            seed=job.seed, label=job.label,
        ))

    def _prepare(self, job: ServeJob) -> Optional[bool]:
        """Resolve the job's compiled spec.  True → ready; None → a
        background build is still in flight (the job stays pending,
        nothing blocks); False → the build failed and the job completed
        as ERROR instead of poisoning the scheduler."""
        if job.spec is not None or job.algo not in SUPPORTED_ALGOS:
            return True
        try:
            if job.spec_future is not None:
                if not job.spec_future.done():
                    return None
                job.spec = job.spec_future.result()
                job.spec_future = None
            else:
                job.spec = self._build_spec(job)
            return True
        except Exception as e:
            self._complete(job, SolveResult(
                status="ERROR", assignment={}, cost=None, violation=None,
                cycle=0, msg_count=0, msg_size=0.0,
                time=monotonic() - job.submitted_at,
            ), error=str(e))
            return False

    def _try_admit(self, job: ServeJob) -> bool:
        pkey = _params_key(job.algo_params)
        with self._lock:
            workers = list(self._workers)
        for w in workers:
            if w.isolate_key != job.isolate_key:
                continue  # quarantine groups never mix
            if not (w.matches(job.algo, pkey) and w.free > 0):
                continue
            if job.restore is not None:
                # a checkpointed job must re-seat at the exact target
                # it was padded at — state shapes are target-shaped
                if w.target != job.restore_target():
                    continue
            elif not fits(job.spec.dims, w.target):
                continue
            self._admit_into(w, job)
            return True
        return False

    def _admit_into(self, w: BucketWorker, job: ServeJob) -> None:
        with self._lock:
            if job.in_backlog:
                job.in_backlog = False
                self._backlog -= 1
        midflight = w.steps > 0
        restore = None
        if job.restore is not None:
            restore = restore_lane_state(
                w.adapter, job.spec, w.target,
                job.restore[1], job.restore[0],
            )
            job.restore = None
            job.resumed = True
            self.counters.inc("jobs_resumed")
        lane = w.admit(job, job.spec, restore=restore)
        job.emit("job.admitted", {
            "jid": job.jid, "lane": lane, "midflight": midflight,
            "resumed": job.resumed,
            "signature": [str(s) for s in w.signature],
        })

    def _open_worker_for(self, jobs: List[ServeJob]) -> List[ServeJob]:
        """Open ONE bucket for the head job's group; admit every
        group-mate that fits; return the jobs still waiting (the
        caller loops)."""
        head = jobs[0]
        pkey = _params_key(head.algo_params)
        if head.restore is not None:
            target = head.restore_target()
        else:
            group_dims = [
                j.spec.dims for j in jobs
                if j.algo == head.algo
                and _params_key(j.algo_params) == pkey
                and j.restore is None
                and j.isolate_key == head.isolate_key
                and j.spec.dims.family_key == head.spec.dims.family_key
            ]
            target = self._pick_target(head.algo, pkey, group_dims)
        try:
            w = BucketWorker(
                head.algo, head.algo_params, target, self.lanes,
                self.cache, counters=self.counters,
                limit=self.max_cycles,
            )
        except Exception as e:
            # a bucket that cannot even build (compile failure) must
            # not wedge admission: the head job climbs the quarantine
            # ladder, the rest re-group behind the next head
            self._requeue_or_escalate(
                head, f"bucket worker build failed: {e}"
            )
            return jobs[1:]
        w.isolate_key = head.isolate_key
        with self._lock:
            w.deadline_pressure, w.pressure_exempt_priority = (
                self._deadline_pressure
            )
            self._workers.append(w)
        self.counters.inc("buckets_opened")
        send_serve("bucket.opened", {
            "algo": w.algo, "lanes": w.B, "warm": w.runner_was_warm,
            "signature": [str(s) for s in w.signature],
        })
        leftover = []
        for job in jobs:
            if (
                w.free > 0
                and w.matches(job.algo, _params_key(job.algo_params))
                and job.isolate_key == w.isolate_key
                and (
                    (job.restore is not None
                     and w.target == job.restore_target())
                    or (job.restore is None
                        and fits(job.spec.dims, w.target))
                )
            ):
                self._admit_into(w, job)
            else:
                leftover.append(job)
        return leftover

    def _pick_target(self, algo: str, pkey: Tuple,
                     dims: List[InstanceDims]) -> InstanceDims:
        """Prefer a prewarmed or already-compiled signature that fits
        the whole group — admission then hits the warm runner — else
        the group's own pooled target."""
        candidates = list(self._prewarmed.get((algo, pkey), []))
        with self._lock:
            candidates += [
                w.target for w in self._workers
                if w.matches(algo, pkey)
            ]
        for t in candidates:
            if all(fits(d, t) for d in dims):
                return t
        return serve_target(dims)

    def _maintain_workers(self) -> None:
        # merge under-filled same-signature buckets (smaller → larger)
        with self._lock:
            workers = list(self._workers)
        by_sig: Dict[Tuple, List[BucketWorker]] = {}
        for w in workers:
            if 0 < w.occupied <= max(1, int(w.B * self.merge_below)):
                by_sig.setdefault(
                    (w.algo, w.pkey, w.isolate_key) + w.signature, []
                ).append(w)
        for _sig, ws in by_sig.items():
            if len(ws) < 2:
                continue
            ws.sort(key=lambda w: -w.occupied)
            dst = ws[0]
            for src in ws[1:]:
                if dst.free < src.occupied:
                    continue
                moved = dst.migrate_from(src)
                if moved:
                    self.counters.inc("buckets_merged")
                    send_serve("bucket.merged", {
                        "algo": dst.algo, "moved": moved,
                        "signature": [str(s) for s in dst.signature],
                    })
        # close drained buckets (their compiled runner stays cached)
        for w in workers:
            if w.occupied == 0 and w.steps > 0:
                with self._lock:
                    if w not in self._workers:
                        continue
                    self._workers.remove(w)
                self.counters.inc("buckets_closed")
                send_serve("bucket.closed", {
                    "algo": w.algo,
                    "signature": [str(s) for s in w.signature],
                })

    def _progress_events(self, w: BucketWorker) -> None:
        """Anytime assignments at the chunk boundary, for jobs that
        asked to stream (or any bus subscriber).  Gated so a service
        with nobody listening pays zero extra host pulls."""
        for i, lane in enumerate(w.lanes):
            if lane is None:
                continue
            if not (lane.job.stream or event_bus.enabled):
                continue
            cost, cycle = w.lane_cost(i, lane)
            lane.job.emit("job.progress", {
                "jid": lane.job.jid, "cycle": cycle, "cost": cost,
            })

    def _serve_from_memo(self, job: ServeJob) -> bool:
        """Consult the cross-request solution cache (serve/memo.py)
        before paying for admission.  Returns True when the job was
        answered from cache (exact replay or warm-started variant
        repair) — it never reaches a bucket; False routes it onward
        with its probe attached so completion inserts the solve.

        Runs on the scheduler thread like ``_solve_fallback``: the
        exact path is O(canonicalize), the variant path does k warm
        repairs — both far below a cold solve.
        """
        probe = self.memo.probe(
            job.dcop, job.algo, algo_params=job.algo_params,
            seed=job.seed, tenant=job.tenant,
        )
        job.memo_probe = probe
        if probe.kind == "exact":
            res = self.memo.result_from_entry(probe.entry, probe)
            res.time = monotonic() - job.submitted_at
            job.memo_served = True
            self._complete(job, res)
            return True
        if probe.kind == "variant":
            res = self.memo.serve_variant(
                probe, job.dcop, algo_params=job.algo_params,
            )
            if res is not None:
                res.time = monotonic() - job.submitted_at
                job.memo_served = True
                self._complete(job, res)
                return True
            # warm repair could not uphold the never-worse guarantee:
            # mark the provenance and solve cold through the normal
            # path (fallback counted by the cache)
            probe.kind = "miss"
            probe.cold_fallback = True
            probe.entry = probe.diff = probe.distance = None
        return False

    def _solve_fallback(self, job: ServeJob) -> None:
        """Algorithms outside the batched set solve sequentially on
        the scheduler thread — counted, never silently dropped."""
        from pydcop_tpu.runtime.run import solve_result

        self.counters.inc("jobs_fallback")
        try:
            res = solve_result(
                job.dcop, job.algo, algo_params=job.algo_params,
                seed=job.seed,
            )
        except Exception as e:
            self._complete(job, SolveResult(
                status="ERROR", assignment={}, cost=None, violation=None,
                cycle=0, msg_count=0, msg_size=0.0,
                time=monotonic() - job.submitted_at,
            ), error=str(e))
            return
        res.time = monotonic() - job.submitted_at
        self._complete(job, res)

    def _complete(self, job: ServeJob, res: SolveResult,
                  error: Optional[str] = None) -> None:
        if job.done.is_set():
            return  # already terminal (defensive: double release)
        with self._lock:
            job.result = res
        now = monotonic()
        with self._lock:
            if job.in_backlog:
                job.in_backlog = False
                self._backlog -= 1
            n = self._tenant_open.get(job.tenant, 0)
            if n > 0:
                self._tenant_open[job.tenant] = n - 1
            # completion-rate EMA → the retry-after hint on rejects
            if self._last_done_t is not None:
                dt = now - self._last_done_t
                if dt > 0:
                    inst = 1.0 / dt
                    self._done_rate = (
                        inst if self._done_rate is None
                        else 0.5 * self._done_rate + 0.5 * inst
                    )
            self._last_done_t = now
        self.counters.inc("jobs_completed")
        if res.status == "TIMEOUT" and job.deadline_at is not None:
            self.counters.inc("jobs_preempted")
        self._journal_done(job.jid)
        self._drop_checkpoint(job.jid)
        # serving provenance: which replica/JID actually served the
        # job — the post-hoc audit trail of every failover re-seat
        res.serve = {
            "replica": self.replica,
            "jid": job.jid,
            "resumed": job.resumed,
        }
        if self.memo is not None and job.memo_probe is not None:
            job.memo_probe.decorate(res)
            if (not job.memo_served and error is None
                    and res.status == "FINISHED"):
                entry = self.memo.memoize(job.memo_probe, job.dcop,
                                          res)
                inj = self._injector
                if entry is not None and entry.path and inj is not None:
                    # analyze: waive[unlocked-shared-attr] advisory tick stamp for the fault injector; a torn int read is impossible under the GIL
                    due = inj.due("corrupt_cache_entry", self._ticks,
                                  jid=job.jid)
                    if due is not None:
                        self.counters.inc("faults_injected")
                        send_serve("fault.injected", {
                            "kind": "corrupt_cache_entry",
                            "jid": job.jid,
                        })
                        self.memo.corrupt_entry(entry.key)
        payload = {
            "jid": job.jid, "status": res.status, "cycle": res.cycle,
            "cost": res.cost, "latency": round(res.time, 4),
        }
        if error:
            payload["error"] = error
        job.emit("job.done", payload)
        job.done.set()
        if self.on_complete is not None:
            try:
                self.on_complete(job, res)
            except Exception:  # a fleet tap must never wedge a lane
                pass
        self._maybe_compact_journal()

    # -- journal / crash resume --------------------------------------------

    def _journal_submit(self, job: ServeJob) -> None:
        if not self.journal_dir:
            return
        rec = {
            "jid": job.jid, "file": job.source_file, "algo": job.algo,
            "algo_params": job.algo_params, "seed": job.seed,
            "tenant": job.tenant, "priority": job.priority,
            "deadline_s": job.deadline_s, "label": job.label,
        }
        line = json.dumps(rec, sort_keys=True) + "\n"
        inj = self._injector
        if inj is not None:
            # analyze: waive[unlocked-shared-attr] advisory tick stamp for the fault injector; a torn int read is impossible under the GIL
            f_t = inj.due("torn_journal_write", self._ticks,
                          jid=job.jid)
            if f_t is not None:
                self.counters.inc("faults_injected")
                send_serve("fault.injected", {
                    "kind": "torn_journal_write", "jid": job.jid,
                })
                # a crash mid-append: a prefix of the record, no
                # newline — exactly what resume must skip and count
                line = line[: max(1, len(line) // 2)]
        path = os.path.join(self.journal_dir, JOBS_JOURNAL)
        with self._journal_lock:
            with open(path, "a", encoding="utf-8") as f:
                f.write(line)
                f.flush()
                os.fsync(f.fileno())

    def _journal_done(self, jid: str) -> None:
        with self._lock:
            self._done_jids.add(jid)
        if not self.journal_dir:
            return
        # the batch command's JID resume protocol: append + fsync per
        # job, so a kill -9 loses at most the in-flight work
        path = os.path.join(self.journal_dir, PROGRESS_FILE)
        with self._journal_lock:
            with open(path, "a", encoding="utf-8") as f:
                f.write(f"JID: {jid}\n")
                f.flush()
                os.fsync(f.fileno())

    @staticmethod
    def _complete_lines(path: str) -> Tuple[List[str], int]:
        """(complete lines, torn count).  Every journal append is
        newline-terminated, so a final fragment without a newline is a
        write cut short by a crash (or the injected
        ``torn_journal_write``) — skipped and counted, never fatal."""
        with open(path, encoding="utf-8") as f:
            raw = f.read()
        if not raw:
            return [], 0
        lines = raw.split("\n")
        if lines[-1] == "":
            lines.pop()
            return lines, 0
        lines.pop()  # unterminated tail: torn
        return lines, 1

    def _load_done_jids(self) -> set:
        path = os.path.join(self.journal_dir, PROGRESS_FILE)
        if not os.path.exists(path):
            return set()
        lines, torn = self._complete_lines(path)
        out = set()
        for line in lines:
            if line.startswith("JID: ") and line[5:].strip():
                out.add(line[5:].strip())
            elif line.strip():
                torn += 1  # half-written completion line: not trusted
        if torn:
            self.counters.inc("torn_journal_lines", torn)
            send_serve("journal.torn", {
                "file": PROGRESS_FILE, "lines": torn,
            })
        return out

    def _maybe_compact_journal(self) -> None:
        if not self.journal_dir:
            return
        path = os.path.join(self.journal_dir, JOBS_JOURNAL)
        try:
            if os.path.getsize(path) < self.journal_compact_bytes:
                return
        except OSError:
            return
        self.compact_journal()

    def compact_journal(self) -> int:
        """Drop done-job records from ``jobs.jsonl`` — in a
        long-running service the journal otherwise grows without
        bound.  Both files rewrite through the checkpoint writer's
        discipline (same-directory temp file + fsync + atomic rename),
        and the rewrite order is crash-safe: ``jobs.jsonl`` first, so
        a crash between the two renames leaves only harmless stale
        ``JID:`` lines.  Runs on :meth:`resume` and automatically at
        the ``journal_compact_bytes`` size threshold.  Returns the
        number of records kept."""
        if not self.journal_dir:
            return 0
        path = os.path.join(self.journal_dir, JOBS_JOURNAL)
        if not os.path.exists(path):
            return 0
        with self._journal_lock:
            lines, _torn = self._complete_lines(path)
            keep: List[Dict[str, Any]] = []
            for line in lines:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn interleave: already counted on read
                if rec.get("jid") not in self._done_jids:
                    keep.append(rec)
            d = self.journal_dir
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".jobs_tmp_",
                                       suffix=".jsonl")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    for rec in keep:
                        f.write(json.dumps(rec, sort_keys=True) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            # every record left is NOT done, so the completion file's
            # done-lines are all redundant now — truncate it the same
            # atomic way
            ppath = os.path.join(self.journal_dir, PROGRESS_FILE)
            keep_jids = {rec["jid"] for rec in keep}
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".prog_tmp_")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    for jid in sorted(self._done_jids & keep_jids):
                        f.write(f"JID: {jid}\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, ppath)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        self.counters.inc("journal_compactions")
        send_serve("journal.compacted", {
            "kept": len(keep), "dropped": len(lines) - len(keep),
        })
        return len(keep)

    def _ckpt_path(self, jid: str) -> str:
        return os.path.join(self.journal_dir, CKPT_SUBDIR, f"{jid}.npz")

    def _checkpoint_worker(self, w: BucketWorker) -> None:
        if not self.journal_dir or self.checkpoint_every <= 0:
            return
        if w.steps % self.checkpoint_every != 0:
            return
        from pydcop_tpu.runtime.checkpoint import write_state_npz

        for i, lane in enumerate(w.lanes):
            if lane is None:
                continue
            # a standalone service can only resume jobs it can reload
            # from a source file; a fleet REPLICA checkpoints every
            # lane — the fleet holds the dcop in memory, so failover
            # re-seats need no file to restore from
            if lane.job.source_file is None and self.replica is None:
                continue
            arrays, meta = w.lane_checkpoint(i, lane)
            write_state_npz(self._ckpt_path(lane.job.jid), arrays, meta)
            self.counters.inc("checkpoints_saved")

    def _drop_checkpoint(self, jid: str) -> None:
        if not self.journal_dir:
            return
        try:
            os.unlink(self._ckpt_path(jid))
        except OSError:
            pass

    def resume(self, prewarm: bool = True) -> int:
        """Re-submit every journaled job that never registered its
        ``JID:`` completion line.  Jobs with a valid per-lane
        checkpoint re-seat at their last chunk boundary (their PRNG
        key, age and stability counters restored — the continuation is
        bit-identical to an uninterrupted run); jobs without one
        restart from cycle 0.  Torn journal lines (an append cut short
        by the crash) are skipped and counted, never fatal, and the
        journal is compacted afterwards.  Returns the number of jobs
        re-queued.

        With ``prewarm`` (the default) the re-seat signatures are
        compiled BEFORE the jobs enter the pending queue, through the
        same cache keys the portfolio prewarm hook
        (:meth:`prewarm_predicted`, docs/portfolio.rst) resolves:
        checkpointed jobs warm their exact padded re-seat target
        (:meth:`prewarm_targets`), restart-from-0 jobs warm their
        pooled signature (:meth:`prewarm`) — so a resumed job never
        pays a cold XLA compile at admission time (zero new cache
        misses, pinned in tests/unit/test_fleet.py)."""
        if not self.journal_dir:
            return 0
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.checkpoint import read_state_npz

        if self.memo is not None:
            # rehydrate the solution cache from its CRC'd npz entries
            # beside the journal — a duplicate of an already-served
            # job hits again right after the crash; corrupt entries
            # are skipped-and-counted, never served
            self.memo.rehydrate()

        path = os.path.join(self.journal_dir, JOBS_JOURNAL)
        if not os.path.exists(path):
            return 0
        lines, torn = self._complete_lines(path)
        todo: List[Tuple[Dict[str, Any], str, Any, Optional[Tuple]]] = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                jid = rec["jid"]
            except (ValueError, KeyError, TypeError):
                # a torn fragment glued to the next append: the merged
                # line parses as neither record — skip it, count it,
                # keep resuming
                torn += 1
                continue
            with self._lock:
                seen = jid in self._done_jids or jid in self._jobs
            if seen:
                continue
            if not rec.get("file"):
                continue  # not resumable without a source
            try:
                dcop = load_dcop_from_file([rec["file"]])
            except Exception:
                continue
            restore = None
            ck = self._ckpt_path(jid)
            if os.path.exists(ck):
                try:
                    meta, arrays = read_state_npz(ck)
                    restore = (meta, arrays)
                except ValueError:
                    restore = None  # corrupt: restart from 0
            todo.append((rec, jid, dcop, restore))
        if prewarm and todo:
            self.prewarm_targets(
                [(rec["algo"], rec.get("algo_params") or {},
                  restore_target(restore[0]))
                 for rec, _jid, _dcop, restore in todo
                 if restore is not None],
                block=True,
            )
            fresh = [
                (dcop, rec["algo"], rec.get("algo_params") or {})
                for rec, _jid, dcop, restore in todo if restore is None
                and rec["algo"] in SUPPORTED_ALGOS
            ]
            if fresh:
                self.prewarm(fresh, block=True)
        for rec, jid, dcop, restore in todo:
            self.submit(
                dcop, rec["algo"],
                algo_params=rec.get("algo_params") or {},
                seed=int(rec.get("seed", 0)),
                tenant=rec.get("tenant", "default"),
                priority=int(rec.get("priority", 0)),
                deadline_s=rec.get("deadline_s"),
                label=rec.get("label"),
                source_file=rec["file"],
                _jid=jid, _journal=False, _restore=restore,
            )
        if torn:
            self.counters.inc("torn_journal_lines", torn)
            send_serve("journal.torn", {
                "file": JOBS_JOURNAL, "lines": torn,
            })
        self.compact_journal()
        send_serve("resume.done", {"jobs": len(todo), "torn": torn})
        self._wake.set()
        return len(todo)
