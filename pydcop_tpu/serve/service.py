"""SolveService — the streaming front door over the batch engine.

A persistent in-process solve service: callers :meth:`submit` jobs
(instance + tenant + priority + optional deadline) from any thread, a
single scheduler thread routes them by shape signature into
continuously-batched :class:`~pydcop_tpu.serve.scheduler.BucketWorker`
buckets, and results stream back three ways — :meth:`result` (blocking
future), :meth:`stream` (per-job anytime-assignment iterator) and the
``serve.*`` topics on the process event bus (forwarded to ws/SSE GUI
clients by runtime/ui.py).

Scheduling policy, in the order the tick applies it:

1. **admission** — pending jobs (highest priority first, FIFO within a
   priority) fold into the free lanes of a running bucket whose target
   shape fits them; what remains opens new buckets, preferring
   prewarmed signatures so admission never pays a cold XLA compile on
   the hot path;
2. **stepping** — every occupied bucket advances one chunk; lanes that
   converge (or expire their deadline — counted as preempted) complete
   their jobs and free their slots at that same boundary;
3. **maintenance** — empty buckets close, and two under-filled buckets
   of the same signature merge (lane states copy verbatim, streams
   continue bit-identically).

Crash safety rides the PR 1 checkpoint/JID layer: with a
``journal_dir`` every submission is journaled (``jobs.jsonl``), every
completion registers a ``JID:`` line (the batch command's resume
protocol), and every occupied lane snapshots its state at periodic
chunk boundaries (atomic + CRC, runtime/checkpoint).  A restarted
service :meth:`resume`-s: completed jobs are skipped, in-flight jobs
re-seat at their last checkpointed chunk boundary and continue the
SAME stream — their results stay bit-identical to an uninterrupted
solve.
"""
from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
from collections import deque
from time import monotonic
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.batch.bucketing import InstanceDims, bucket_signature
from pydcop_tpu.batch.cache import CompileCache, global_compile_cache
from pydcop_tpu.batch.engine import (
    DEFAULT_MAX_CYCLES,
    SUPPORTED_ALGOS,
    BatchItem,
    BucketMeta,
    _params_key,
    adapter_for,
    runner_cache_key,
)
from pydcop_tpu.runtime.events import event_bus, send_serve
from pydcop_tpu.runtime.stats import ServeCounters
from pydcop_tpu.serve.scheduler import (
    BucketWorker,
    fits,
    restore_lane_state,
    serve_target,
    warm_bucket_runner,
)

#: journal file names inside ``journal_dir``
JOBS_JOURNAL = "jobs.jsonl"
PROGRESS_FILE = "progress_serve"
CKPT_SUBDIR = "ckpt"


@dataclasses.dataclass
class ServeJob:
    """One submitted job and its runtime bookkeeping."""

    jid: str
    dcop: Any
    algo: str
    algo_params: Dict[str, Any]
    seed: int
    tenant: str
    priority: int
    deadline_s: Optional[float]
    deadline_at: Optional[float]  # monotonic absolute deadline
    label: Optional[str]
    source_file: Optional[str]
    stream: bool
    submitted_at: float
    seq: int
    # scheduler-side state
    spec: Any = None
    spec_future: Any = None  # in-flight background spec build
    restore: Optional[Tuple] = None  # checkpointed lane restore tuple
    resumed: bool = False
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    result: Optional[SolveResult] = None
    events: "queue.Queue" = dataclasses.field(
        default_factory=lambda: queue.Queue(maxsize=1024)
    )

    def restore_target(self) -> InstanceDims:
        """The exact padded target a checkpointed job must re-seat at
        (its state leaves are target-shaped)."""
        assert self.restore is not None
        t = dict(self.restore[0]["target"])
        t["arities"] = tuple(t["arities"])
        t["F"] = tuple(t["F"])
        return InstanceDims(**t)

    def emit(self, event: str, payload: Dict[str, Any]) -> None:
        send_serve(event, payload)
        if self.stream:
            try:
                self.events.put_nowait({"event": event, **payload})
            except queue.Full:  # slow consumer: drop, never block solve
                pass


class SolveService:
    """Continuous-batching solve service over the batch engine.

    >>> # sketch:
    >>> # svc = SolveService(lanes=8)
    >>> # svc.start()
    >>> # jid = svc.submit(dcop, "mgm", tenant="t1", priority=1)
    >>> # res = svc.result(jid, timeout=30)
    >>> # svc.stop()

    ``lanes`` is the slot count of each bucket the service opens.
    ``cache=None`` shares the process-wide compile cache (so a restart
    in the same process reuses every compiled runner); pass a fresh
    :class:`CompileCache` to isolate (the tests do).  With
    ``journal_dir`` the service is crash-safe — see the module
    docstring.  ``start()`` spawns the scheduler thread; tests may
    instead drive :meth:`tick` synchronously for deterministic
    schedules.
    """

    def __init__(
        self,
        lanes: int = 4,
        cache: Optional[CompileCache] = None,
        counters: Optional[ServeCounters] = None,
        max_cycles: int = DEFAULT_MAX_CYCLES,
        journal_dir: Optional[str] = None,
        checkpoint_every: int = 4,
        merge_below: float = 0.5,
        tick_interval: float = 0.02,
        max_buckets: Optional[int] = None,
    ):
        self.lanes = int(lanes)
        self.max_buckets = max_buckets
        self.cache = cache if cache is not None else global_compile_cache()
        self.counters = counters if counters is not None else ServeCounters()
        self.max_cycles = int(max_cycles)
        self.journal_dir = journal_dir
        self.checkpoint_every = int(checkpoint_every)
        self.merge_below = float(merge_below)
        self.tick_interval = float(tick_interval)

        self._jobs: Dict[str, ServeJob] = {}
        self._pending: "deque[ServeJob]" = deque()
        self._workers: List[BucketWorker] = []
        self._prewarmed: Dict[Tuple[str, Tuple], List[InstanceDims]] = {}
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._prep_pool = None  # spec-build executor (started threads)
        self._seq = 0
        self._done_jids: set = set()
        if journal_dir:
            os.makedirs(os.path.join(journal_dir, CKPT_SUBDIR),
                        exist_ok=True)
            self._done_jids = self._load_done_jids()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        from concurrent.futures import ThreadPoolExecutor

        self._stop = False
        # instance compilation (spec building) runs OFF the scheduler
        # thread so admission prep overlaps bucket stepping; manual
        # tick() driving (tests) stays synchronous — no pool, specs
        # build inline, schedules are deterministic
        self._prep_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="serve-prep"
        )
        self._thread = threading.Thread(
            target=self._loop, name="solve-service", daemon=True
        )
        self._thread.start()

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop the scheduler thread.  ``drain=True`` waits until every
        submitted job completed (bounded by ``timeout``);
        ``drain=False`` abandons in-flight work where it stands — with
        a journal this is the crash-with-checkpoints path a later
        :meth:`resume` recovers from."""
        if drain:
            self.wait_all(timeout=timeout)
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._prep_pool is not None:
            self._prep_pool.shutdown(wait=False)
            self._prep_pool = None

    def __enter__(self) -> "SolveService":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job is done; False on timeout."""
        deadline = None if timeout is None else monotonic() + timeout
        for job in list(self._jobs.values()):
            remain = (
                None if deadline is None else max(0.0, deadline - monotonic())
            )
            if not job.done.wait(remain):
                return False
        return True

    # -- front door ---------------------------------------------------------

    def submit(
        self,
        dcop,
        algo: str,
        algo_params: Optional[Dict[str, Any]] = None,
        seed: int = 0,
        tenant: str = "default",
        priority: int = 0,
        deadline_s: Optional[float] = None,
        label: Optional[str] = None,
        source_file: Optional[str] = None,
        stream: bool = False,
        spec: Any = None,
        _jid: Optional[str] = None,
        _journal: bool = True,
    ) -> str:
        """Enqueue one solve job; returns its job id immediately.

        ``priority`` orders admission (higher first, FIFO within a
        level); ``deadline_s`` is a per-tenant latency budget in
        seconds from now — the scheduler shrinks the job's chunks as
        the budget tightens and completes it as ``TIMEOUT`` (counted
        preempted) when it expires.  ``source_file`` makes the job
        crash-resumable when the service has a journal.  ``spec``
        optionally hands over an already-compiled instance (the batch
        engine's adapter spec) — callers that prepare instances
        themselves skip the service's prep stage entirely."""
        with self._lock:
            self._seq += 1
            if _jid is not None:
                # a resumed job keeps its journaled id; advance the
                # sequence past it so fresh submissions cannot collide
                tail = _jid.rsplit("-", 1)[-1]
                if tail.isdigit():
                    self._seq = max(self._seq, int(tail))
            jid = _jid or f"job-{self._seq:06d}"
            job = ServeJob(
                jid=jid,
                dcop=dcop,
                algo=algo,
                algo_params=dict(algo_params or {}),
                seed=int(seed),
                tenant=tenant,
                priority=int(priority),
                deadline_s=deadline_s,
                deadline_at=(
                    monotonic() + deadline_s
                    if deadline_s is not None else None
                ),
                label=label,
                source_file=source_file,
                stream=stream,
                submitted_at=monotonic(),
                seq=self._seq,
            )
            job.spec = spec
            self._jobs[jid] = job
            self._pending.append(job)
        if (
            job.spec is None
            and self._prep_pool is not None
            and algo in SUPPORTED_ALGOS
        ):
            job.spec_future = self._prep_pool.submit(
                self._build_spec, job
            )
        self.counters.inc("jobs_submitted")
        if _journal:
            self._journal_submit(job)
        job.emit("job.submitted", {
            "jid": jid, "tenant": tenant, "priority": job.priority,
            "algo": algo,
        })
        self._wake.set()
        return jid

    def result(self, jid: str, timeout: Optional[float] = None
               ) -> SolveResult:
        """Block until job ``jid`` completes and return its result."""
        job = self._jobs[jid]
        if not job.done.wait(timeout):
            raise TimeoutError(f"job {jid} not done within {timeout}s")
        assert job.result is not None
        return job.result

    def stream(self, jid: str, timeout: float = 60.0
               ) -> Iterator[Dict[str, Any]]:
        """Iterate job ``jid``'s lifecycle events — admission, anytime
        assignments at chunk boundaries (``job.progress``: cycle +
        current cost), completion — until the job is done.  The job
        must have been submitted with ``stream=True``."""
        job = self._jobs[jid]
        while True:
            try:
                evt = job.events.get(timeout=timeout)
            except queue.Empty:
                return
            yield evt
            if evt.get("event") == "job.done":
                return

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            workers = [
                {"algo": w.algo, "signature": list(map(str, w.signature)),
                 "occupied": w.occupied, "lanes": w.B, "steps": w.steps}
                for w in self._workers
            ]
            pending = len(self._pending)
        return {
            "serve": self.counters.as_dict(),
            "cache": self.cache.stats(),
            "workers": workers,
            "pending": pending,
        }

    # -- prewarm ------------------------------------------------------------

    def prewarm(
        self,
        items: Sequence[Tuple],
        lanes: Optional[int] = None,
        block: bool = False,
    ) -> None:
        """Compile bucket runners for expected traffic ahead of
        arrival.  ``items`` is a sequence of ``(dcop, algo)`` or
        ``(dcop, algo, algo_params)`` tuples describing the shapes the
        service expects; one runner compiles per (algo, params, shape
        family) at the pooled serve target, on the compile cache's
        background thread (``block=True`` joins — tests and
        warm-before-open services).  Buckets opened later for fitting
        traffic resolve to the SAME cache key, so their admission is a
        hit, not a cold compile."""
        lanes = int(lanes or self.lanes)
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for it in items:
            dcop, algo = it[0], it[1]
            params = dict(it[2]) if len(it) > 2 and it[2] else {}
            if algo not in SUPPORTED_ALGOS:
                continue
            adapter = adapter_for(algo)
            spec = adapter.build_spec(
                BatchItem(dcop, algo, algo_params=params)
            )
            g = groups.setdefault(
                (algo, _params_key(params), spec.dims.family_key),
                {"adapter": adapter, "params": params, "dims": []},
            )
            g["dims"].append(spec.dims)
        from pydcop_tpu.algorithms.base import default_chunk

        entries = []
        for (algo, pkey, _fam), g in sorted(
            groups.items(), key=lambda kv: str(kv[0])
        ):
            target = serve_target(g["dims"])
            self._prewarmed.setdefault((algo, pkey), []).append(target)
            # the worker's own chunk policy (the PRNG stream depends on
            # it, so the prewarmed key must use the same)
            chunk = default_chunk(None, False, False, None,
                                  self.max_cycles)
            key = runner_cache_key(
                algo, pkey, bucket_signature(target, lanes), chunk
            )
            adapter, params = g["adapter"], g["params"]
            entries.append((
                key,
                lambda a=adapter, t=target, p=params, b=lanes, c=chunk:
                warm_bucket_runner(a, t, p, b, c),
            ))
        self.counters.inc("prewarmed_runners", len(entries))
        send_serve("prewarm.scheduled", {"runners": len(entries)})
        if entries:
            self.cache.prewarm(entries, block=block)

    # -- scheduler ----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop:
            busy = self.tick()
            if not busy:
                self._wake.wait(self.tick_interval)
                self._wake.clear()

    def tick(self) -> bool:
        """One synchronous scheduler pass: admissions, one chunk step
        per occupied bucket (completions + slot reuse at each
        boundary), then maintenance.  Returns True while work remains.
        The background thread just calls this in a loop; tests call it
        directly for deterministic schedules."""
        self._admit_pending()
        for w in list(self._workers):
            if w.occupied == 0:
                continue
            finished = w.step()
            for i, lane, status in finished:
                res = w.lane_result(i, lane, status)
                w.release(i)
                self._complete(lane.job, res)
            self._progress_events(w)
            self._checkpoint_worker(w)
        # boundary admissions into lanes just freed — this is the
        # continuous part of the batching
        self._admit_pending()
        self._maintain_workers()
        with self._lock:
            return bool(self._pending) or any(
                w.occupied for w in self._workers
            )

    def _admit_pending(self) -> None:
        with self._lock:
            pending = sorted(
                self._pending, key=lambda j: (-j.priority, j.seq)
            )
            self._pending.clear()
        leftover: List[ServeJob] = []
        not_ready: List[ServeJob] = []
        for job in pending:
            ready = self._prepare(job)
            if ready is False:
                continue
            if ready is None:  # spec still building in the background
                not_ready.append(job)
                continue
            if job.algo not in SUPPORTED_ALGOS:
                self._solve_fallback(job)
                continue
            if not self._try_admit(job):
                leftover.append(job)
        if not_ready:
            with self._lock:
                self._pending.extend(not_ready)
        # open new buckets for whatever could not fold in — bounded by
        # ``max_buckets``: beyond it jobs queue for the next freed lane
        # instead of growing the working set without limit
        while leftover:
            if (
                self.max_buckets is not None
                and len(self._workers) >= self.max_buckets
            ):
                with self._lock:
                    self._pending.extend(leftover)
                break
            leftover = self._open_worker_for(leftover)
        return

    @staticmethod
    def _build_spec(job: ServeJob):
        return adapter_for(job.algo).build_spec(BatchItem(
            job.dcop, job.algo, algo_params=job.algo_params,
            seed=job.seed, label=job.label,
        ))

    def _prepare(self, job: ServeJob) -> Optional[bool]:
        """Resolve the job's compiled spec.  True → ready; None → a
        background build is still in flight (the job stays pending,
        nothing blocks); False → the build failed and the job completed
        as ERROR instead of poisoning the scheduler."""
        if job.spec is not None or job.algo not in SUPPORTED_ALGOS:
            return True
        try:
            if job.spec_future is not None:
                if not job.spec_future.done():
                    return None
                job.spec = job.spec_future.result()
                job.spec_future = None
            else:
                job.spec = self._build_spec(job)
            return True
        except Exception as e:
            self._complete(job, SolveResult(
                status="ERROR", assignment={}, cost=None, violation=None,
                cycle=0, msg_count=0, msg_size=0.0,
                time=monotonic() - job.submitted_at,
            ), error=str(e))
            return False

    def _try_admit(self, job: ServeJob) -> bool:
        pkey = _params_key(job.algo_params)
        for w in self._workers:
            if not (w.matches(job.algo, pkey) and w.free > 0):
                continue
            if job.restore is not None:
                # a checkpointed job must re-seat at the exact target
                # it was padded at — state shapes are target-shaped
                if w.target != job.restore_target():
                    continue
            elif not fits(job.spec.dims, w.target):
                continue
            self._admit_into(w, job)
            return True
        return False

    def _admit_into(self, w: BucketWorker, job: ServeJob) -> None:
        midflight = w.steps > 0
        restore = None
        if job.restore is not None:
            restore = restore_lane_state(
                w.adapter, job.spec, w.target,
                job.restore[1], job.restore[0],
            )
            job.restore = None
            job.resumed = True
            self.counters.inc("jobs_resumed")
        lane = w.admit(job, job.spec, restore=restore)
        job.emit("job.admitted", {
            "jid": job.jid, "lane": lane, "midflight": midflight,
            "resumed": job.resumed,
            "signature": [str(s) for s in w.signature],
        })

    def _open_worker_for(self, jobs: List[ServeJob]) -> List[ServeJob]:
        """Open ONE bucket for the head job's group; admit every
        group-mate that fits; return the jobs still waiting (the
        caller loops)."""
        head = jobs[0]
        pkey = _params_key(head.algo_params)
        if head.restore is not None:
            target = head.restore_target()
        else:
            group_dims = [
                j.spec.dims for j in jobs
                if j.algo == head.algo
                and _params_key(j.algo_params) == pkey
                and j.restore is None
                and j.spec.dims.family_key == head.spec.dims.family_key
            ]
            target = self._pick_target(head.algo, pkey, group_dims)
        w = BucketWorker(
            head.algo, head.algo_params, target, self.lanes,
            self.cache, counters=self.counters, limit=self.max_cycles,
        )
        self._workers.append(w)
        self.counters.inc("buckets_opened")
        send_serve("bucket.opened", {
            "algo": w.algo, "lanes": w.B, "warm": w.runner_was_warm,
            "signature": [str(s) for s in w.signature],
        })
        leftover = []
        for job in jobs:
            if (
                w.free > 0
                and w.matches(job.algo, _params_key(job.algo_params))
                and (
                    (job.restore is not None
                     and w.target == job.restore_target())
                    or (job.restore is None
                        and fits(job.spec.dims, w.target))
                )
            ):
                self._admit_into(w, job)
            else:
                leftover.append(job)
        return leftover

    def _pick_target(self, algo: str, pkey: Tuple,
                     dims: List[InstanceDims]) -> InstanceDims:
        """Prefer a prewarmed or already-compiled signature that fits
        the whole group — admission then hits the warm runner — else
        the group's own pooled target."""
        candidates = list(self._prewarmed.get((algo, pkey), []))
        candidates += [
            w.target for w in self._workers if w.matches(algo, pkey)
        ]
        for t in candidates:
            if all(fits(d, t) for d in dims):
                return t
        return serve_target(dims)

    def _maintain_workers(self) -> None:
        # merge under-filled same-signature buckets (smaller → larger)
        by_sig: Dict[Tuple, List[BucketWorker]] = {}
        for w in self._workers:
            if 0 < w.occupied <= max(1, int(w.B * self.merge_below)):
                by_sig.setdefault(
                    (w.algo, w.pkey) + w.signature, []
                ).append(w)
        for _sig, ws in by_sig.items():
            if len(ws) < 2:
                continue
            ws.sort(key=lambda w: -w.occupied)
            dst = ws[0]
            for src in ws[1:]:
                if dst.free < src.occupied:
                    continue
                moved = dst.migrate_from(src)
                if moved:
                    self.counters.inc("buckets_merged")
                    send_serve("bucket.merged", {
                        "algo": dst.algo, "moved": moved,
                        "signature": [str(s) for s in dst.signature],
                    })
        # close drained buckets (their compiled runner stays cached)
        for w in list(self._workers):
            if w.occupied == 0 and w.steps > 0:
                self._workers.remove(w)
                self.counters.inc("buckets_closed")
                send_serve("bucket.closed", {
                    "algo": w.algo,
                    "signature": [str(s) for s in w.signature],
                })

    def _progress_events(self, w: BucketWorker) -> None:
        """Anytime assignments at the chunk boundary, for jobs that
        asked to stream (or any bus subscriber).  Gated so a service
        with nobody listening pays zero extra host pulls."""
        for i, lane in enumerate(w.lanes):
            if lane is None:
                continue
            if not (lane.job.stream or event_bus.enabled):
                continue
            cost, cycle = w.lane_cost(i, lane)
            lane.job.emit("job.progress", {
                "jid": lane.job.jid, "cycle": cycle, "cost": cost,
            })

    def _solve_fallback(self, job: ServeJob) -> None:
        """Algorithms outside the batched set solve sequentially on
        the scheduler thread — counted, never silently dropped."""
        from pydcop_tpu.runtime.run import solve_result

        self.counters.inc("jobs_fallback")
        try:
            res = solve_result(
                job.dcop, job.algo, algo_params=job.algo_params,
                seed=job.seed,
            )
        except Exception as e:
            self._complete(job, SolveResult(
                status="ERROR", assignment={}, cost=None, violation=None,
                cycle=0, msg_count=0, msg_size=0.0,
                time=monotonic() - job.submitted_at,
            ), error=str(e))
            return
        res.time = monotonic() - job.submitted_at
        self._complete(job, res)

    def _complete(self, job: ServeJob, res: SolveResult,
                  error: Optional[str] = None) -> None:
        job.result = res
        self.counters.inc("jobs_completed")
        if res.status == "TIMEOUT" and job.deadline_at is not None:
            self.counters.inc("jobs_preempted")
        self._journal_done(job.jid)
        self._drop_checkpoint(job.jid)
        payload = {
            "jid": job.jid, "status": res.status, "cycle": res.cycle,
            "cost": res.cost, "latency": round(res.time, 4),
        }
        if error:
            payload["error"] = error
        job.emit("job.done", payload)
        job.done.set()

    # -- journal / crash resume --------------------------------------------

    def _journal_submit(self, job: ServeJob) -> None:
        if not self.journal_dir:
            return
        rec = {
            "jid": job.jid, "file": job.source_file, "algo": job.algo,
            "algo_params": job.algo_params, "seed": job.seed,
            "tenant": job.tenant, "priority": job.priority,
            "deadline_s": job.deadline_s, "label": job.label,
        }
        path = os.path.join(self.journal_dir, JOBS_JOURNAL)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def _journal_done(self, jid: str) -> None:
        self._done_jids.add(jid)
        if not self.journal_dir:
            return
        # the batch command's JID resume protocol: append + fsync per
        # job, so a kill -9 loses at most the in-flight work
        path = os.path.join(self.journal_dir, PROGRESS_FILE)
        with open(path, "a", encoding="utf-8") as f:
            f.write(f"JID: {jid}\n")
            f.flush()
            os.fsync(f.fileno())

    def _load_done_jids(self) -> set:
        path = os.path.join(self.journal_dir, PROGRESS_FILE)
        if not os.path.exists(path):
            return set()
        with open(path, encoding="utf-8") as f:
            return {
                line[5:].strip() for line in f if line.startswith("JID: ")
            }

    def _ckpt_path(self, jid: str) -> str:
        return os.path.join(self.journal_dir, CKPT_SUBDIR, f"{jid}.npz")

    def _checkpoint_worker(self, w: BucketWorker) -> None:
        if not self.journal_dir or self.checkpoint_every <= 0:
            return
        if w.steps % self.checkpoint_every != 0:
            return
        from pydcop_tpu.runtime.checkpoint import write_state_npz

        for i, lane in enumerate(w.lanes):
            if lane is None or lane.job.source_file is None:
                continue
            arrays, meta = w.lane_checkpoint(i, lane)
            write_state_npz(self._ckpt_path(lane.job.jid), arrays, meta)
            self.counters.inc("checkpoints_saved")

    def _drop_checkpoint(self, jid: str) -> None:
        if not self.journal_dir:
            return
        try:
            os.unlink(self._ckpt_path(jid))
        except OSError:
            pass

    def resume(self) -> int:
        """Re-submit every journaled job that never registered its
        ``JID:`` completion line.  Jobs with a valid per-lane
        checkpoint re-seat at their last chunk boundary (their PRNG
        key, age and stability counters restored — the continuation is
        bit-identical to an uninterrupted run); jobs without one
        restart from cycle 0.  Returns the number of jobs re-queued."""
        if not self.journal_dir:
            return 0
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.runtime.checkpoint import read_state_npz

        path = os.path.join(self.journal_dir, JOBS_JOURNAL)
        if not os.path.exists(path):
            return 0
        n = 0
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                jid = rec["jid"]
                if jid in self._done_jids or jid in self._jobs:
                    continue
                if not rec.get("file"):
                    continue  # not resumable without a source
                try:
                    dcop = load_dcop_from_file([rec["file"]])
                except Exception:
                    continue
                self.submit(
                    dcop, rec["algo"],
                    algo_params=rec.get("algo_params") or {},
                    seed=int(rec.get("seed", 0)),
                    tenant=rec.get("tenant", "default"),
                    priority=int(rec.get("priority", 0)),
                    deadline_s=rec.get("deadline_s"),
                    label=rec.get("label"),
                    source_file=rec["file"],
                    _jid=jid, _journal=False,
                )
                job = self._jobs[jid]
                ck = self._ckpt_path(jid)
                if os.path.exists(ck):
                    try:
                        meta, arrays = read_state_npz(ck)
                        job.restore = (meta, arrays)
                    except ValueError:
                        job.restore = None  # corrupt: restart from 0
                n += 1
        send_serve("resume.done", {"jobs": n})
        self._wake.set()
        return n
