"""Signature routing — placing fleet traffic on already-warm replicas.

The expensive artifact a serving fleet must protect is the *warm
compiled runner*, not the process around it (the PGMax compile-once
discipline, arXiv:2202.04110): a replica that is merely *alive* still
costs a cold XLA compile for every shape family it has never seen.  So
the router keys placement on the SAME identifiers the compile cache
keys runners by (batch/cache.py, engine.runner_cache_key):

* a job's **routing key** is the leading fields of its runner cache
  key — ``(algo, params-key) + family`` where the family is the
  instance's :attr:`~pydcop_tpu.batch.bucketing.InstanceDims.family_key`
  (graph type + arity set).  It is computed host-side from the DCOP
  alone (:func:`job_routing_key`), no tensor compilation needed, so the
  fleet front door stays cheap;
* a replica is **warm** for a key when the router saw it prewarm or
  serve that key before, or — ground truth — when the replica's
  in-memory compile cache holds a runner for it
  (:meth:`~pydcop_tpu.batch.cache.CompileCache.has`, consulted through
  the per-replica ``warm_probe``; checkpointed re-seats probe their
  exact runner cache key).

Placement policy, in order: (1) among routable replicas (up, not
partitioned, not stalled) that are warm for the key, the least-loaded
wins; (2) otherwise the least-loaded routable replica wins and the key
is recorded as warming there — so the NEXT job of that family co-lands
on the same replica and folds into the same continuously-batched
bucket instead of paying a second compile elsewhere.  Ties break by
replica order (deterministic placement for a deterministic trace).

Warm affinity is bounded by **load spill**: when the best warm replica
is ``spill_load`` open jobs ahead of the emptiest routable peer, the
job spills to that peer — it pays ONE compile there, after which the
peer is warm too and the family's traffic splits.  Without spill a
single hot signature would pin a whole fleet's traffic to one replica
forever; with it, warmth decides placement at the margin and load
decides it in the bulk, which is what makes jobs/s scale with replica
count (the ``fleet`` bench leg).

Replicas that lose mesh devices (a ``kill_device`` fault with a
``replica`` — ISSUE 14) advertise **reduced capacity**: the router
scales each replica's load by its remaining device fraction when
ranking, so a half-capacity replica looks twice as loaded and traffic
drains toward whole peers without marking the shrunk one down.

The router is crossed by two threads — the fleet front door places
jobs while the supervisor thread flips health/capacity state — so it
owns its own lock and every state access goes through it (the
lock-discipline lint covers this file).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from pydcop_tpu.batch.engine import _params_key
from pydcop_tpu.runtime.events import send_fleet

#: algorithm families compiled on the factor-graph path (BP); the rest
#: of the batch-eligible set compiles constraint hypergraphs — mirrors
#: the batch adapters' graph types (engine.adapter_for)
_FACTOR_GRAPH_ALGOS = ("maxsum", "amaxsum")


def job_routing_key(dcop, algo: str,
                    algo_params: Optional[Dict[str, Any]] = None
                    ) -> Tuple:
    """The routing key of one job: ``(algo, params-key, graph type,
    arity set)`` — exactly the leading fields of the compile-cache key
    its bucket runner will resolve to, computed from the DCOP's host
    structure alone (no tensor compilation on the front-door path).
    Two jobs with the same routing key pool into the same padded serve
    target on a replica, so routing by it is routing to the runner."""
    arities = tuple(sorted({
        len(c.dimensions) for c in dcop.constraints.values()
    }))
    graph_type = (
        "factor_graph" if algo in _FACTOR_GRAPH_ALGOS
        else "constraints_hypergraph"
    )
    return (algo, _params_key(algo_params or {}), graph_type, arities)


@dataclasses.dataclass
class _ReplicaState:
    """Router-side view of one replica."""

    name: str
    up: bool = True
    stalled: bool = False
    partitioned: bool = False
    load: int = 0  # open (placed-but-unfinished) jobs
    #: remaining device fraction (1.0 = whole mesh); a replica that
    #: lost devices advertises < 1 and its load is scaled up by the
    #: inverse when ranking placements (ISSUE 14)
    capacity: float = 1.0
    warm: set = dataclasses.field(default_factory=set)
    #: ground-truth warmth probe (the replica's CompileCache.has),
    #: consulted for exact runner cache keys on re-seat placement
    warm_probe: Optional[Callable[[Tuple], bool]] = None

    @property
    def routable(self) -> bool:
        return self.up and not self.stalled and not self.partitioned

    def is_warm(self, key: Tuple) -> bool:
        if key in self.warm:
            return True
        return bool(self.warm_probe is not None and self.warm_probe(key))

    @property
    def effective_load(self) -> float:
        """Open jobs scaled by the inverse remaining capacity — the
        ranking metric: a replica at half capacity with 2 jobs is as
        loaded as a whole one with 4."""
        return self.load / max(self.capacity, 1e-6)


class FleetRouter:
    """Places jobs on replicas by compile-cache routing key.

    ``spill_load`` bounds warm affinity: a warm replica that is this
    many open jobs ahead of the emptiest routable peer loses the
    placement to that peer (None = never spill).  The fleet passes its
    per-bucket lane count — spill exactly when the warm replica has a
    whole bucket's worth of extra queue."""

    def __init__(self, spill_load: Optional[int] = None):
        self.spill_load = spill_load
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {}

    # -- membership ---------------------------------------------------------

    def add_replica(self, name: str,
                    warm_probe: Optional[Callable[[Tuple], bool]] = None
                    ) -> None:
        with self._lock:
            self._replicas[name] = _ReplicaState(
                name=name, warm_probe=warm_probe
            )

    def mark_down(self, name: str) -> None:
        with self._lock:
            self._replicas[name].up = False

    def mark_up(self, name: str) -> None:
        with self._lock:
            r = self._replicas[name]
            r.up, r.stalled, r.partitioned = True, False, False
            r.capacity = 1.0

    def set_stalled(self, name: str, stalled: bool) -> None:
        with self._lock:
            self._replicas[name].stalled = stalled

    def set_partitioned(self, name: str, partitioned: bool) -> None:
        with self._lock:
            self._replicas[name].partitioned = partitioned

    def set_capacity(self, name: str, capacity: float) -> None:
        """Advertise a replica's remaining device fraction (ISSUE 14):
        the fleet supervisor pushes this after a ``kill_device`` fault
        so placement drains toward whole peers WITHOUT marking the
        shrunk replica down (it still serves — just less)."""
        with self._lock:
            self._replicas[name].capacity = max(
                0.0, min(1.0, float(capacity))
            )

    # -- load accounting (one open job = one unit) --------------------------

    def job_placed(self, name: str) -> None:
        with self._lock:
            self._replicas[name].load += 1

    def job_finished(self, name: str) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is not None and r.load > 0:
                r.load -= 1

    def note_warm(self, name: str, key: Tuple) -> None:
        """Record that ``name`` holds (or is compiling) a runner for
        ``key`` — called on prewarm and on every placement."""
        with self._lock:
            self._replicas[name].warm.add(key)

    # -- queries ------------------------------------------------------------

    def routable(self) -> List[str]:
        with self._lock:
            return [n for n, r in self._replicas.items() if r.routable]

    def up(self) -> List[str]:
        with self._lock:
            return [n for n, r in self._replicas.items() if r.up]

    def load(self, name: str) -> int:
        with self._lock:
            return self._replicas[name].load

    def capacity(self, name: str) -> float:
        with self._lock:
            return self._replicas[name].capacity

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                n: {
                    "up": r.up, "stalled": r.stalled,
                    "partitioned": r.partitioned, "load": r.load,
                    "capacity": r.capacity,
                    "warm_keys": len(r.warm),
                }
                for n, r in self._replicas.items()
            }

    # -- placement ----------------------------------------------------------

    def place(self, key: Tuple, jid: Optional[str] = None,
              exclude: Optional[str] = None,
              prefer_emptiest: bool = False,
              ) -> Optional[Tuple[str, bool]]:
        """Pick the replica for one job and account the placement.
        Returns ``(name, was_warm)``, or None when no replica is
        routable (the fleet front door turns that into a structured
        overload/stopped error).  ``exclude`` bars one replica (the
        dead one, during re-seat).

        ``prefer_emptiest`` inverts the policy for ONE placement:
        least-loaded healthy replica first, warmth ignored — the SLO
        ladder's rung-3 lever (a protected gold job buys the shortest
        queue even at the price of a compile; scenario/slo.py).
        Routable already excludes down/stalled/partitioned replicas,
        so "emptiest" is always also "healthy"."""
        with self._lock:
            candidates = [
                r for n, r in self._replicas.items()
                if r.routable and n != exclude
            ]
            if not candidates:
                return None
            warm = [r for r in candidates if r.is_warm(key)]
            if prefer_emptiest:
                best = min(candidates, key=lambda r: r.effective_load)
                warm = [best] if best.is_warm(key) else []
            else:
                pool = warm if warm else candidates
                # ranking is by EFFECTIVE load (load / remaining
                # capacity): a replica that lost half its devices
                # looks twice as loaded, so traffic drains toward
                # whole peers (ISSUE 14)
                best = min(pool, key=lambda r: r.effective_load)
                if warm and self.spill_load is not None:
                    emptiest = min(candidates,
                                   key=lambda r: r.effective_load)
                    if (best.effective_load - emptiest.effective_load
                            >= self.spill_load):
                        # warm affinity loses at the margin: spill to
                        # the emptiest peer, which warms up and splits
                        # the family
                        best = emptiest
                        warm = [best] if best.is_warm(key) else []
            best.load += 1
            best.warm.add(key)
            name = best.name
        send_fleet("router.placed", {
            "jid": jid, "replica": name,
            "key": [str(k) for k in key], "warm": bool(warm),
            "emptiest": bool(prefer_emptiest),
        })
        return name, bool(warm)
