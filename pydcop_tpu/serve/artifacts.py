"""AOT runner artifacts — serialized compiled executables keyed by
``runner_cache_key``, so a relaunched or newly joined replica serves
its first job with ZERO XLA compiles.

The two-level compile cache (batch/cache.py) already skips the
*expensive half* of a cold start via the persistent XLA cache, but a
fresh process still pays tracing and cache plumbing per runner, and
the XLA cache is keyed by HLO fingerprint — it cannot answer "what do
I need to be warm for this routing key?".  This module closes that
gap with explicit, addressable artifacts:

* a runner compiled ahead-of-time (``jax.jit(...).lower().compile()``)
  serializes through ``jax.experimental.serialize_executable`` into a
  ``(payload, in_tree, out_tree)`` triple;
* :class:`ArtifactStore` persists that triple under a filename derived
  from the exact compile-cache key, as a self-describing file: one
  JSON header line (format version, ABI tag, CRC32 + size of the
  blob, printable key) followed by the pickled triple;
* a loading replica verifies format, ABI (jax/jaxlib versions and
  backend — serialized executables are machine-specific) and CRC
  before deserializing.  A stale artifact raises
  :class:`StaleArtifactError`, a damaged one
  :class:`CorruptArtifactError`; the cache layer logs both loudly,
  counts them, and falls back to a fresh compile that OVERWRITES the
  bad file — rejection is never silent and never fatal.

Writes are atomic (tmp + fsync + rename), matching the checkpoint
discipline (PR 6): a kill mid-export can leave a tmp file around but
never a half-written artifact under the real name.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
from typing import Any, Dict, Optional, Tuple

log = logging.getLogger(__name__)

#: bumped when the on-disk layout changes
ARTIFACT_FORMAT = 1


class ArtifactError(RuntimeError):
    """Base for artifact rejections (never raised past the cache)."""


class StaleArtifactError(ArtifactError):
    """ABI/format mismatch: built by a different jax/jaxlib/backend
    (or an older store layout) — unusable here, must recompile."""


class CorruptArtifactError(ArtifactError):
    """Damaged bytes: bad header, CRC mismatch, or an unpicklable
    blob — rejected loudly, recompiled, overwritten."""


def abi_tag() -> Dict[str, str]:
    """The compatibility fingerprint stamped into every artifact.
    Serialized executables are tied to the exact XLA build and target
    backend, so all three components must match to load."""
    import jax
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.version.__version__,
        "backend": jax.default_backend(),
    }


class AotRunner:
    """A compiled bucket runner plus its serialized form.

    Callable exactly like the jitted runner it replaces (the bucket
    worker cannot tell them apart); carries the serialization triple
    so exporting to the store never re-serializes, and a loaded
    runner can be re-exported to a peer without a round-trip."""

    def __init__(self, compiled: Any,
                 triple: Tuple[bytes, Any, Any]):
        self._compiled = compiled
        self.triple = triple

    def __call__(self, arrays, state, xs, n_active, done_mask):
        return self._compiled(arrays, state, xs, n_active, done_mask)


def _serialize_compiled(compiled: Any) -> Tuple[bytes, Any, Any]:
    from jax.experimental import serialize_executable as se

    return se.serialize(compiled)


def _deserialize(triple: Tuple[bytes, Any, Any]) -> Any:
    from jax.experimental import serialize_executable as se

    return se.deserialize_and_load(*triple)


def artifact_name(key: Tuple) -> str:
    """Stable filename for a compile-cache key (keys are tuples of
    primitives + nested shape tuples — ``repr`` is deterministic)."""
    return hashlib.sha1(repr(key).encode("utf-8")).hexdigest() + ".rnr"


class ArtifactStore:
    """Directory of serialized runner executables, one per compile-
    cache key.  Shared by every replica process of a fleet (it lives
    under the fleet's journal directory), so one replica's compile is
    every FUTURE replica's free bring-up.

    Thread-safe: the owning service's scheduler and prewarm threads
    both reach it through the compile cache; a lock serializes the
    read-verify-load and write-fsync-rename sections."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.saved = 0
        self.rejected_stale = 0
        self.rejected_corrupt = 0
        self.save_verify_failed = 0

    def path_for(self, key: Tuple) -> str:
        return os.path.join(self.root, artifact_name(key))

    # -- export --------------------------------------------------------------

    def save(self, key: Tuple, runner: Any) -> Optional[str]:
        """Persist a runner's executable.  Only AOT-built runners
        carry a serialization triple; anything else is skipped (the
        fleet decides at build time which runners export)."""
        triple = getattr(runner, "triple", None)
        if triple is None:
            return None
        try:
            blob = pickle.dumps(triple)
        except Exception as e:  # never fail the solve over an export
            log.warning("artifact export failed for %r: %s", key, e)
            return None
        # self-verify BEFORE publishing: some executables serialize
        # into payloads that cannot be loaded back (notably ones whose
        # compile was satisfied from the persistent XLA cache — the
        # payload lacks its kernel symbols).  A broken artifact must
        # never reach the store; a cold replica trusting it would die.
        try:
            _deserialize(triple)
        except Exception as e:
            with self._lock:
                self.save_verify_failed += 1
            log.warning("artifact for %r failed save-time verification "
                        "(%s) — NOT exported", key, e)
            self._send_reject(self.path_for(key), "unverifiable", str(e))
            return None
        import zlib

        header = json.dumps({
            "format": ARTIFACT_FORMAT,
            "abi": abi_tag(),
            "crc": zlib.crc32(blob) & 0xFFFFFFFF,
            "size": len(blob),
            "key": [str(k) for k in key],
        }, sort_keys=True).encode("utf-8") + b"\n"
        path = self.path_for(key)
        tmp = path + ".tmp"
        with self._lock:
            try:
                with open(tmp, "wb") as f:
                    f.write(header)
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError as e:
                log.warning("artifact write failed for %r: %s", key, e)
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
            self.saved += 1
        from pydcop_tpu.runtime.events import send_batch

        send_batch("artifact.saved", {"path": path})
        return path

    # -- import --------------------------------------------------------------

    def load(self, key: Tuple) -> Optional[AotRunner]:
        """Deserialize the runner for ``key`` if a usable artifact
        exists.  Returns None on a plain miss; stale/corrupt files are
        rejected LOUDLY (warning log + counter + event) and also
        return None so the caller recompiles and overwrites."""
        path = self.path_for(key)
        try:
            with self._lock:
                triple = self._read_verified(path)
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
            return None
        except StaleArtifactError as e:
            with self._lock:
                self.rejected_stale += 1
            log.warning("STALE runner artifact rejected (%s): %s "
                        "— recompiling", path, e)
            self._send_reject(path, "stale", str(e))
            return None
        except CorruptArtifactError as e:
            with self._lock:
                self.rejected_corrupt += 1
            log.warning("CORRUPT runner artifact rejected (%s): %s "
                        "— recompiling", path, e)
            self._send_reject(path, "corrupt", str(e))
            return None
        try:
            compiled = _deserialize(triple)
        except Exception as e:
            with self._lock:
                self.rejected_corrupt += 1
            log.warning("runner artifact failed to deserialize (%s): "
                        "%s — recompiling", path, e)
            self._send_reject(path, "corrupt", str(e))
            return None
        with self._lock:
            self.hits += 1
        return AotRunner(compiled, triple)

    def _read_verified(self, path: str) -> Tuple[bytes, Any, Any]:
        import zlib

        with open(path, "rb") as f:
            raw = f.read()
        nl = raw.find(b"\n")
        if nl < 0:
            raise CorruptArtifactError("no header line")
        try:
            header = json.loads(raw[:nl].decode("utf-8"))
        except ValueError as e:
            raise CorruptArtifactError(f"unparseable header: {e}")
        if not isinstance(header, dict):
            raise CorruptArtifactError("header is not an object")
        if header.get("format") != ARTIFACT_FORMAT:
            raise StaleArtifactError(
                f"format {header.get('format')!r} != {ARTIFACT_FORMAT}"
            )
        abi = header.get("abi")
        here = abi_tag()
        if abi != here:
            raise StaleArtifactError(f"abi {abi!r} != {here!r}")
        blob = raw[nl + 1:]
        if len(blob) != header.get("size"):
            raise CorruptArtifactError(
                f"size {len(blob)} != declared {header.get('size')}"
            )
        if zlib.crc32(blob) & 0xFFFFFFFF != header.get("crc"):
            raise CorruptArtifactError("blob CRC mismatch")
        try:
            triple = pickle.loads(blob)
        except Exception as e:
            raise CorruptArtifactError(f"unpicklable blob: {e}")
        if not (isinstance(triple, tuple) and len(triple) == 3):
            raise CorruptArtifactError("blob is not a (payload, "
                                       "in_tree, out_tree) triple")
        return triple

    def _send_reject(self, path: str, why: str, detail: str) -> None:
        from pydcop_tpu.runtime.events import send_batch

        send_batch("artifact.rejected",
                   {"path": path, "why": why, "detail": detail})

    # -- introspection -------------------------------------------------------

    def entries(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.root)
                       if n.endswith(".rnr"))
        except OSError:
            return 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "saved": self.saved,
                "rejected_stale": self.rejected_stale,
                "rejected_corrupt": self.rejected_corrupt,
                "save_verify_failed": self.save_verify_failed,
                "entries": self.entries(),
            }


def corrupt_artifact_file(path: str, seed: int = 0) -> bool:
    """Flip one byte inside an artifact's blob (the ``corrupt_artifact``
    fault's hand): a deterministic, seeded bit of damage the CRC check
    must catch.  Returns False when the file is missing or too short
    to damage safely."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return False
    nl = raw.find(b"\n")
    if nl < 0 or len(raw) <= nl + 2:
        return False
    # pick a deterministic offset inside the blob
    span = len(raw) - (nl + 1)
    off = nl + 1 + (seed * 2654435761 + 17) % span
    flipped = raw[:off] + bytes([raw[off] ^ 0xFF]) + raw[off + 1:]
    with open(path, "wb") as f:
        f.write(flipped)
    return True
