"""Cross-request solution cache with embedding-matched warm starts.

ISSUE 18 tentpole.  Millions of users do not submit a million *novel*
DCOPs — they submit duplicates and k-edit variants, yet every request
used to pay a full solve.  This layer sits ABOVE the compile cache
(which only reuses *shapes*) and makes repeated traffic structurally
cheaper:

* **exact hit** — the submitted instance is canonicalized
  (:mod:`pydcop_tpu.dcop.canonical`) and content-hashed; a hash match
  within the (tenant, algo, params, seed) namespace replays the cached
  result bit-identically, zero device work.
* **variant hit** — on a miss the instance is embedded with the PR 10
  featurizer (portfolio/features) and matched to the nearest cached
  solved instance under a feasibility gate: identical variable/domain
  skeleton (:func:`~pydcop_tpu.dcop.canonical.shape_signature`) and a
  factor diff of at most ``max_edits``.  The diff is replayed as an
  EditFactor/AddFactor/RemoveFactor mutation stream through the PR 8
  headroom/warm machinery (runtime/repair.WarmRepairController), the
  cached assignment seeds the solver state, and the repair converges
  in a handful of cycles — a k-edit variant costs k warm repairs
  instead of a cold solve.
* **never-worse guarantee** — a warm-started result is served only
  when its final cost is no worse than the cached seed assignment
  evaluated on the new problem; otherwise (or when the run dies) the
  caller falls back to a cold solve, so a cache hit can never degrade
  solution quality.  Pinned per warm-capable algo in
  tests/unit/test_memo.py and the ``memo`` bench leg.

Entries live in memory and — when a cache directory is configured —
as CRC'd npz containers (runtime/checkpoint.write_state_npz) beside
the job journal, so ``SolveService.resume()`` rehydrates the cache
after a crash; a corrupt entry is skipped-and-counted
(``corrupt_cache_entry`` fault kind, docs/resilience.rst), never
served.  Results expire after ``ttl_s`` and a churn event drops the
affected tenant's namespace outright.  The fleet tier shares entries
through its journal stream (thread fleet taps ``on_insert``; the
PR 16 socket wire forwards ``memo`` frames), peers adopting them
read-only.

Lock discipline (analysis/lint RACE_SCOPE): completion taps, the
scheduler thread and fleet adoption callbacks all touch one cache, so
every mutable map lives behind ``self._lock``; the expensive work
(canonicalization, featurizing, the warm repair itself) runs outside
the lock on purpose.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from pydcop_tpu.dcop.canonical import (
    FactorDiff,
    canonical_hash,
    constraint_digests,
    factor_diff,
    params_key,
    shape_signature,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.runtime.events import send_memo
from pydcop_tpu.runtime.stats import MemoCounters

__all__ = ["MemoCache", "MemoConfig", "MemoEntry", "MemoProbe"]

#: cache directory name under a service's journal dir
MEMO_SUBDIR = "memo"


@dataclass
class MemoConfig:
    """Solution-cache policy knobs (docs/serving.rst)."""

    #: entry time-to-live; expired entries are dropped lazily at the
    #: next lookup (0 disables expiry)
    ttl_s: float = 3600.0
    #: variant feasibility gate: max factor-diff size replayed warm
    max_edits: int = 8
    #: LRU capacity (per cache = per replica)
    max_entries: int = 512
    #: skip the featurizer embedding above this many variables (exact
    #: hits still work; variants rank by diff size only)
    featurize_max_vars: int = 20000
    #: warm-solver build knobs (ops/headroom seeding)
    warm_headroom: float = 0.25
    warm_min_free: int = 4
    #: cycle budget for the warm repair run
    warm_max_cycles: int = 300
    #: numeric slack for the never-worse cost gate
    cost_slack: float = 1e-6


@dataclass
class MemoEntry:
    """One cached solved instance (content-addressed)."""

    key: str                    # exact-hit key (hashed namespace+content)
    tenant: str
    algo: str
    pkey: str                   # canonical algo-params string
    seed: int
    chash: str                  # canonical instance hash
    shape_sig: str              # variable/domain skeleton digest
    digests: Dict[str, str]     # constraint name → content digest
    assignment: Dict[str, Any]
    status: str
    cost: Optional[float]
    violation: Optional[int]
    cycle: int
    msg_count: int
    msg_size: float
    yaml: str                   # cached instance, canonical YAML
    features: Optional[np.ndarray]
    created_at: float
    last_used: float = 0.0
    path: Optional[str] = None  # on-disk npz (None = memory only)
    owned: bool = True          # False for entries adopted from a peer
    #: lazily-cached parse of ``yaml`` (memory only, never persisted)
    _parsed: Optional[DCOP] = field(default=None, repr=False)

    def parsed_dcop(self) -> DCOP:
        """Parse the cached canonical YAML once, then hand out a
        shallow clone per serve: the warm controller rebinds
        constraint/variable slots on its instance, so sharing the
        parse across serves would drift it.  The Variable/Domain/
        Constraint objects themselves are immutable under replay and
        safe to share — this turns the dominant per-variant cost
        (re-parsing a multi-hundred-KB YAML) into a dict copy."""
        if self._parsed is None:
            from pydcop_tpu.dcop.yamldcop import load_dcop

            self._parsed = load_dcop(self.yaml)
        src = self._parsed
        clone = DCOP(src.name, objective=src.objective,
                     description=src.description)
        clone.domains = dict(src.domains)
        clone.variables = dict(src.variables)
        clone.constraints = dict(src.constraints)
        clone.agents = dict(src.agents)
        clone.external_variables = dict(src.external_variables)
        clone.dist_hints = src.dist_hints
        return clone

    def meta_dict(self) -> Dict[str, Any]:
        """JSON-safe persistence form (the npz ``__meta__`` payload)."""
        return {
            "key": self.key, "tenant": self.tenant, "algo": self.algo,
            "pkey": self.pkey, "seed": int(self.seed),
            "chash": self.chash, "shape_sig": self.shape_sig,
            "digests": dict(self.digests),
            "assignment": dict(self.assignment),
            "status": self.status,
            "cost": None if self.cost is None else float(self.cost),
            "violation": (None if self.violation is None
                          else int(self.violation)),
            "cycle": int(self.cycle),
            "msg_count": int(self.msg_count),
            "msg_size": float(self.msg_size),
            "yaml": self.yaml,
            "created_at": float(self.created_at),
            "has_features": self.features is not None,
        }


@dataclass
class MemoProbe:
    """One lookup's verdict + the canonicalization artifacts, so a
    later :meth:`MemoCache.memoize` never recomputes them."""

    kind: str                   # "exact" | "variant" | "miss"
    tenant: str
    algo: str
    pkey: str
    seed: int
    chash: str
    key: str
    shape_sig: Optional[str] = None
    digests: Optional[Dict[str, str]] = None
    features: Optional[np.ndarray] = None
    entry: Optional[MemoEntry] = None
    diff: Optional[FactorDiff] = None
    distance: Optional[float] = None
    #: a variant hit whose warm repair was discarded (never-worse
    #: guarantee) — the job was solved cold instead
    cold_fallback: bool = False

    def provenance(self) -> Dict[str, Any]:
        """The ``metrics()["memo"]`` seed for this lookup."""
        out: Dict[str, Any] = {"hit": self.kind}
        if self.cold_fallback:
            out["cold_fallback"] = True
        if self.entry is not None:
            out["key"] = self.entry.key[:16]
        if self.diff is not None:
            out.update(self.diff.as_dict())
        if self.distance is not None and np.isfinite(self.distance):
            out["distance"] = round(float(self.distance), 6)
        return out

    def decorate(self, res) -> None:
        """Attach this lookup's provenance to a result that does not
        already carry one (cache-served results are stamped richer at
        serve time — don't overwrite)."""
        if res.memo is None:
            res.memo = self.provenance()


def _exact_key(tenant: str, algo: str, pkey: str, seed: int,
               chash: str) -> str:
    import hashlib

    blob = "\x1f".join([tenant, algo, pkey, str(int(seed)), chash])
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class MemoCache:
    """Content-addressed solution cache (one per service replica).

    Thread-safe: probe/insert/adopt/invalidate run under ``_lock``;
    :meth:`serve_variant` (the warm repair) deliberately touches no
    shared state beyond counters.
    """

    def __init__(
        self,
        config: Optional[MemoConfig] = None,
        directory: Optional[str] = None,
        counters: Optional[MemoCounters] = None,
        on_insert: Optional[Callable[[MemoEntry], None]] = None,
    ):
        self.config = config or MemoConfig()
        self.directory = directory
        self.counters = counters or MemoCounters()
        #: fleet-sharing tap: called (outside the lock) with every
        #: locally-inserted entry after it is persisted
        self.on_insert = on_insert
        self._lock = threading.Lock()
        self._entries: Dict[str, MemoEntry] = {}
        #: (tenant, algo, pkey, shape_sig) → [exact keys] — the
        #: variant candidate index
        self._buckets: Dict[Tuple[str, str, str, str], List[str]] = {}
        if directory:
            os.makedirs(directory, exist_ok=True)

    # -- canonicalization helpers (no shared state) -------------------------

    def _features_of(self, dcop: DCOP) -> Optional[np.ndarray]:
        if len(dcop.variables) > self.config.featurize_max_vars:
            return None
        from pydcop_tpu.portfolio.features import featurize

        return np.asarray(featurize(dcop), dtype=np.float32)

    # -- lookup --------------------------------------------------------------

    def probe(self, dcop: DCOP, algo: str, algo_params=None,
              seed: int = 0, tenant: str = "default") -> MemoProbe:
        """Classify one submission: exact / variant / miss.

        Heavy canonicalization happens before the lock; the lock only
        covers the index lookups and TTL sweep.
        """
        pkey = params_key(algo_params)
        chash = canonical_hash(dcop)
        key = _exact_key(tenant, algo, pkey, seed, chash)
        now = time.time()
        with self._lock:
            self._expire_locked(now)
            hit = self._entries.get(key)
            if hit is not None:
                hit.last_used = now
                self.counters.inc("hits_exact")
                send_memo("hit.exact", {"tenant": tenant,
                                        "key": key[:16]})
                return MemoProbe("exact", tenant, algo, pkey, seed,
                                 chash, key, entry=hit)
        # exact miss: build the variant-match artifacts outside the lock
        ssig = shape_signature(dcop)
        digs = constraint_digests(dcop)
        feats = self._features_of(dcop)
        probe = MemoProbe("miss", tenant, algo, pkey, seed, chash, key,
                          shape_sig=ssig, digests=digs, features=feats)
        from pydcop_tpu.algorithms.warm import WARM_ALGOS

        if algo in WARM_ALGOS:
            with self._lock:
                self._match_variant_locked(probe, now)
        if probe.kind == "miss":
            self.counters.inc("misses")
            send_memo("miss", {"tenant": tenant, "key": key[:16]})
        return probe

    def _match_variant_locked(self, probe: MemoProbe, now: float) -> None:
        bucket = self._buckets.get(
            (probe.tenant, probe.algo, probe.pkey, probe.shape_sig))
        if not bucket:
            return
        entries = [
            e for e in (self._entries.get(k) for k in bucket)
            if e is not None
        ]
        if not entries:
            return
        # ONE [B, F] distance computation replaces the per-entry norm
        # loop.  Entries lacking features rank last at +inf, and the
        # STABLE argsort keeps bucket insertion order among equal
        # distances — the same tie-break the stable per-entry sort
        # produced, so the matched entry is identical to the scan
        # this replaces (pinned by test)
        dists = np.full(len(entries), np.inf, dtype=np.float64)
        if probe.features is not None:
            with_f = [
                i for i, e in enumerate(entries)
                if e.features is not None
            ]
            if with_f:
                mat = np.stack([
                    entries[i].features.astype(np.float32)
                    for i in with_f
                ])
                delta = mat - probe.features[None, :]
                dists[with_f] = np.sqrt(
                    np.sum(np.square(delta, dtype=np.float64), axis=1)
                )
        order = np.argsort(dists, kind="stable")
        ranked = [(float(dists[i]), entries[i]) for i in order]
        for d, e in ranked:
            diff = factor_diff(e.digests, None, probe.digests)
            if diff.edits <= self.config.max_edits:
                e.last_used = now
                probe.kind = "variant"
                probe.entry, probe.diff, probe.distance = e, diff, d
                self.counters.inc("hits_variant")
                send_memo("hit.variant", {
                    "tenant": probe.tenant, "key": e.key[:16],
                    "edits": diff.edits,
                    "distance": None if not np.isfinite(d) else
                    round(d, 6),
                })
                return
            self.counters.inc("variant_rejected_gate")

    # -- serving -------------------------------------------------------------

    def result_from_entry(self, entry: MemoEntry, probe: MemoProbe):
        """A fresh SolveResult replaying ``entry`` bit-identically."""
        from pydcop_tpu.algorithms.base import SolveResult

        return SolveResult(
            status=entry.status,
            assignment=dict(entry.assignment),
            cost=entry.cost,
            violation=entry.violation,
            cycle=entry.cycle,
            msg_count=entry.msg_count,
            msg_size=entry.msg_size,
            time=0.0,
            memo=probe.provenance(),
        )

    def serve_variant(self, probe: MemoProbe, dcop: DCOP,
                      algo_params=None, max_cycles: Optional[int] = None):
        """Warm-repair the cached nearest instance into ``dcop``.

        Returns a SolveResult (with ``memo`` provenance) or ``None``
        when the warm path cannot uphold the never-worse guarantee —
        the caller then solves cold.
        """
        import jax.numpy as jnp

        from pydcop_tpu.algorithms import DEFAULT_INFINITY, AlgorithmDef
        from pydcop_tpu.runtime.repair import WarmRepairController

        entry, diff = probe.entry, probe.diff
        cfg = self.config
        try:
            old = entry.parsed_dcop()
            algo_def = AlgorithmDef.build_with_default_params(
                probe.algo, dict(algo_params or {}), mode=old.objective)
            ctrl = WarmRepairController(
                old, probe.algo, algo_def=algo_def, seed=probe.seed,
                headroom=cfg.warm_headroom, min_free=cfg.warm_min_free)
            solver = ctrl.solver
            # seed the cached assignment into the warm state (the
            # repack_solver by-slot value-copy pattern)
            state = solver.initial_state()
            vals = np.asarray(solver.values_of(state)).copy()
            for name, val in entry.assignment.items():
                if name in old.variables:
                    slot = solver.layout.var_slot(name)
                    vals[slot] = old.variables[name].domain.index(val)
            seeded = jnp.asarray(vals).astype(
                solver.values_of(state).dtype)
            if len(state) == 4:      # WarmMaxSumSolver (q, r, vals, ops)
                state = (state[0], state[1], seeded, state[3])
            else:                    # WarmLocalSearchSolver (x, ops)
                state = (seeded, state[1])
            solver._last_state = state
            # replay the factor diff as a warm mutation stream; the
            # controller absorbs HeadroomExhausted with ONE repack
            for name in diff.changed:
                ctrl.edit_factor(dcop.constraints[name])
            for name in diff.added:
                ctrl.add_constraint(dcop.constraints[name])
            for name in diff.removed:
                ctrl.remove_constraint(name)
            res = ctrl.solver.run(
                max_cycles=max_cycles or cfg.warm_max_cycles,
                resume=True)
        except Exception as e:  # warm path is best-effort by contract
            self.counters.inc("variant_cold_fallbacks")
            send_memo("fallback.cold", {"key": entry.key[:16],
                                        "reason": repr(e)})
            return None
        repacks = ctrl.counters.counts.get("headroom_exhausted_repacks", 0)
        if repacks:
            self.counters.inc("variant_repacks", repacks)
        # never-worse gate: final cost must not regress the cached
        # assignment evaluated on the NEW problem (the warm seed)
        viol_seed, c_seed = dcop.solution_cost(
            dict(entry.assignment), DEFAULT_INFINITY)
        ok = res.cost is not None and np.isfinite(res.cost)
        if ok:
            if dcop.objective == "max":
                ok = res.cost >= c_seed - cfg.cost_slack
            else:
                ok = res.cost <= c_seed + cfg.cost_slack
        if ok and res.violation is not None:
            ok = res.violation <= viol_seed
        if not ok:
            self.counters.inc("variant_cold_fallbacks")
            send_memo("fallback.cold", {
                "key": entry.key[:16],
                "reason": f"converged worse than seed "
                          f"(cost={res.cost} seed={c_seed})",
            })
            return None
        res.memo = probe.provenance()
        res.memo["seed_cost"] = float(c_seed)
        res.memo["repacks"] = int(repacks)
        return res

    # -- insertion / persistence ---------------------------------------------

    def memoize(self, probe: MemoProbe, dcop: DCOP,
                res) -> Optional[MemoEntry]:
        """Cache one solved instance (miss or variant-served lookups;
        exact hits are already present).  Named ``memoize`` rather
        than ``insert`` deliberately: the race lint counts mutator
        verbs through a held attribute as writes to the holder."""
        if probe.kind == "exact" or not res.assignment:
            return None
        if res.cost is None or not np.isfinite(res.cost):
            return None
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        now = time.time()
        entry = MemoEntry(
            key=probe.key, tenant=probe.tenant, algo=probe.algo,
            pkey=probe.pkey, seed=probe.seed, chash=probe.chash,
            shape_sig=probe.shape_sig or shape_signature(dcop),
            digests=probe.digests or constraint_digests(dcop),
            assignment=dict(res.assignment), status=res.status,
            cost=float(res.cost), violation=res.violation,
            cycle=res.cycle, msg_count=res.msg_count,
            msg_size=res.msg_size, yaml=dcop_yaml(dcop),
            features=(probe.features if probe.features is not None
                      else self._features_of(dcop)),
            created_at=now, last_used=now,
            # the solved instance doubles as the parse cache: a later
            # variant serve clones it instead of re-parsing the YAML
            # (rehydrated/adopted entries still parse lazily, once)
            _parsed=dcop,
        )
        if self.directory:
            entry.path = os.path.join(self.directory,
                                      f"{entry.key[:24]}.npz")
            self._write_entry(entry)
        evicted = self._adopt(entry, counter="inserts")
        send_memo("insert", {"tenant": entry.tenant,
                             "key": entry.key[:16],
                             "cost": entry.cost})
        for old in evicted:
            self._unlink(old)
        if self.on_insert is not None:
            self.on_insert(entry)
        return entry

    def _write_entry(self, entry: MemoEntry) -> None:
        from pydcop_tpu.runtime.checkpoint import write_state_npz

        feats = (entry.features if entry.features is not None
                 else np.zeros(0, dtype=np.float32))
        write_state_npz(entry.path, {"features": feats},
                        {"memo": entry.meta_dict()})

    def _adopt(self, entry: MemoEntry, counter: str) -> List[MemoEntry]:
        """Index ``entry``; returns LRU-evicted entries (files are the
        caller's to unlink, outside the lock)."""
        evicted: List[MemoEntry] = []
        with self._lock:
            prior = self._entries.get(entry.key)
            if prior is not None:
                self._unindex_locked(prior)
            self._entries[entry.key] = entry
            self._buckets.setdefault(
                (entry.tenant, entry.algo, entry.pkey, entry.shape_sig),
                []).append(entry.key)
            self.counters.inc(counter)
            while len(self._entries) > self.config.max_entries:
                lru = min(self._entries.values(),
                          key=lambda e: e.last_used)
                self._unindex_locked(lru)
                self.counters.inc("evicted_lru")
                evicted.append(lru)
        return evicted

    def _unindex_locked(self, entry: MemoEntry) -> None:
        self._entries.pop(entry.key, None)
        bucket = self._buckets.get(
            (entry.tenant, entry.algo, entry.pkey, entry.shape_sig))
        if bucket and entry.key in bucket:
            bucket.remove(entry.key)

    def _unlink(self, entry: MemoEntry) -> None:
        if entry.path and entry.owned:
            try:
                os.unlink(entry.path)
            except OSError:
                pass

    # -- invalidation ---------------------------------------------------------

    def _expire_locked(self, now: float) -> None:
        ttl = self.config.ttl_s
        if not ttl:
            return
        dead = [e for e in self._entries.values()
                if now - e.created_at > ttl]
        for e in dead:
            self._unindex_locked(e)
            self._unlink(e)
            self.counters.inc("expired_ttl")
        if dead:
            send_memo("invalidate", {"reason": "ttl",
                                     "dropped": len(dead)})

    def churn_event(self, tenant: Optional[str] = None) -> int:
        """A churn event makes cached results stale: drop the
        tenant's namespace (or everything when ``tenant`` is None)."""
        with self._lock:
            dead = [e for e in self._entries.values()
                    if tenant is None or e.tenant == tenant]
            for e in dead:
                self._unindex_locked(e)
                self._unlink(e)
            self.counters.inc("invalidated_churn", len(dead))
        if dead:
            send_memo("invalidate", {"reason": "churn",
                                     "tenant": tenant,
                                     "dropped": len(dead)})
        return len(dead)

    # -- persistence: rehydrate / fleet adoption ------------------------------

    def _load_file(self, path: str) -> MemoEntry:
        """Read + verify one npz entry (ValueError on any corruption)."""
        from pydcop_tpu.runtime.checkpoint import read_state_npz

        meta, arrays = read_state_npz(path)
        m = meta.get("memo")
        if not isinstance(m, dict):
            raise ValueError(f"{path!r} is not a memo entry")
        feats = None
        if m.get("has_features"):
            feats = np.asarray(arrays["features"], dtype=np.float32)
        return MemoEntry(
            key=m["key"], tenant=m["tenant"], algo=m["algo"],
            pkey=m["pkey"], seed=int(m["seed"]), chash=m["chash"],
            shape_sig=m["shape_sig"], digests=dict(m["digests"]),
            assignment=dict(m["assignment"]), status=m["status"],
            cost=m["cost"], violation=m["violation"],
            cycle=int(m["cycle"]), msg_count=int(m["msg_count"]),
            msg_size=float(m["msg_size"]), yaml=m["yaml"],
            features=feats, created_at=float(m["created_at"]),
            last_used=float(m["created_at"]), path=path,
        )

    def rehydrate(self) -> int:
        """Reload persisted entries (the ``resume()`` path).  Corrupt
        files are skipped-and-counted — never served."""
        if not self.directory or not os.path.isdir(self.directory):
            return 0
        n = 0
        for fn in sorted(os.listdir(self.directory)):
            if not fn.endswith(".npz"):
                continue
            path = os.path.join(self.directory, fn)
            try:
                entry = self._load_file(path)
            except ValueError as e:
                self.counters.inc("corrupt_skipped")
                send_memo("corrupt.skipped", {"path": path,
                                              "reason": str(e)})
                continue
            for old in self._adopt(entry, counter="rehydrated"):
                self._unlink(old)
            n += 1
        return n

    def adopt_file(self, path: str) -> bool:
        """Adopt a peer replica's persisted entry (fleet sharing).
        The peer keeps ownership of the file; corrupt frames are
        skipped-and-counted."""
        try:
            entry = self._load_file(path)
        except ValueError as e:
            self.counters.inc("corrupt_skipped")
            send_memo("corrupt.skipped", {"path": path,
                                          "reason": str(e)})
            return False
        entry.owned = False
        return self.adopt_entry(entry)

    def adopt_entry(self, entry: MemoEntry) -> bool:
        """Adopt an in-memory entry from a peer (thread-fleet tap)."""
        with self._lock:
            if entry.key in self._entries:
                return False
        clone = MemoEntry(**{**entry.__dict__})
        clone.owned = False
        for old in self._adopt(clone, counter="adopted"):
            self._unlink(old)
        return True

    # -- fault injection / introspection --------------------------------------

    def corrupt_entry(self, key: Optional[str] = None) -> Optional[str]:
        """Flip bytes in one persisted entry (the
        ``corrupt_cache_entry`` fault): models silent disk corruption —
        the CRC check at rehydrate/adopt time must refuse it."""
        with self._lock:
            victims = [e for e in self._entries.values()
                       if e.path and (key is None or e.key == key)]
            victim = max(victims, key=lambda e: e.created_at,
                         default=None)
            path = victim.path if victim is not None else None
        if path is None or not os.path.exists(path):
            return None
        with open(path, "r+b") as f:
            f.seek(max(0, os.path.getsize(path) // 2))
            f.write(b"\xde\xad\xbe\xef")
        return path

    def entry(self, key: str) -> Optional[MemoEntry]:
        with self._lock:
            return self._entries.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        """The ``metrics()["memo"]`` scorecard."""
        with self._lock:
            out = self.counters.as_dict()
            out["entries"] = len(self._entries)
            out["tenants"] = len({e.tenant
                                  for e in self._entries.values()})
        return out
