"""ProcessFleet — the solve fleet with REAL failure domains.

:class:`~pydcop_tpu.serve.fleet.SolveFleet` (PR 11) hosts its N
replicas as threads in one process: one GIL, one address space, one
way to die.  This module promotes each replica to a child OS process
and keeps everything else — routing, admission, re-seat, RTO
accounting — by reusing the fleet base class over a process-shaped
replica handle:

* **processes** — each replica is ``python -m pydcop_tpu
  serve-replica`` (commands/serve_replica.py), spawned and supervised
  with the PR 1 watchdog protocol: a file heartbeat beaten by the
  child's scheduler tick, death detected via heartbeat staleness +
  ``waitpid`` (``Popen.poll``), the exit-code taxonomy of
  runtime/process.py (signal death / ``KILL_EXIT_CODE`` = retryable →
  exponential-backoff relaunch under a fresh incarnation name;
  nonzero = permanent, not relaunched), and stderr to a per-replica
  file, never a blockable pipe;
* **socket journal** — control frames and journal records ride ONE
  length-prefixed, CRC-framed stream per replica (serve/wire.py).
  Completion records are applied exactly once at the head (per-sender
  sequence dedup survives reconnects — a completion sent just before
  a connection loss replays but never double-applies) and fsynced
  into ``fleet.jsonl`` by the head's :class:`FleetJournal`;
* **kill -9 for real** — ``kill_process`` SIGKILLs the whole child:
  every lane, thread and socket dies at once.  The supervisor detects
  it, re-seats the in-flight jobs on surviving processes through the
  PR 6 resume protocol (checkpoints and ``JID:`` completion lines
  live on the shared filesystem), bit-identically and with a finite
  RTO — the same guarantees the thread fleet pins, now across an OS
  boundary;
* **zero-compile bring-up** — replicas share an
  :class:`~pydcop_tpu.serve.artifacts.ArtifactStore` under the
  journal directory: the first process to compile a runner exports
  its serialized executable keyed by ``runner_cache_key``; a
  relaunched or cold-joining replica loads it (ABI-checked,
  CRC-verified) and serves its first job with zero XLA compiles;
* **stall vs death vs process-exit** — a stale heartbeat with a live
  process is a STALL (route around, never re-seat: the process may
  finish its work); a dead process is a death (re-seat + maybe
  relaunch); a severed socket (``partition_socket``) is neither —
  in-flight jobs keep running, frames buffer child-side and replay on
  the healed reconnect.

Tick-driven tests drive :meth:`SolveFleet.tick` exactly like the
thread fleet — the hub is pumped inside supervision, so schedules
stay deterministic.  The child side, :class:`ReplicaWorker`, is
importable and loop-drivable so protocol tests can host it on a
thread over a real socket without paying process spawn.
"""
from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import threading
import time
from collections import deque
from time import monotonic
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.algorithms.base import SolveResult
from pydcop_tpu.batch.bucketing import InstanceDims
from pydcop_tpu.batch.cache import CompileCache
from pydcop_tpu.runtime.events import send_fleet
from pydcop_tpu.runtime.faults import (
    ENV_FAULT_ATTEMPT,
    ENV_FAULT_PLAN,
    KILL_EXIT_CODE,
    FaultPlan,
)
from pydcop_tpu.runtime.stats import FleetCounters, ServeCounters
from pydcop_tpu.serve.artifacts import (
    ArtifactStore,
    abi_tag,
    corrupt_artifact_file,
)
from pydcop_tpu.serve.errors import ServiceStopped
from pydcop_tpu.serve.fleet import ReplicaHandle, SolveFleet
from pydcop_tpu.serve.wire import JournalClient, JournalHub

#: shared artifact directory under the fleet journal dir
ARTIFACT_SUBDIR = "artifacts"
#: re-seat spill directory: checkpoint state recovered from a dead
#: replica's disk, re-written for the surviving replica to restore
SPILL_SUBDIR = "spill"


def _json_safe(v: Any) -> Any:
    """Numpy scalars → plain Python so result frames round-trip the
    JSON wire exactly (int is exact; float survives as an IEEE-754
    double both ways — bit-identity holds)."""
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return v


def _dims_to_wire(d: InstanceDims) -> Dict[str, Any]:
    return {
        "graph_type": d.graph_type, "D": d.D,
        "arities": list(d.arities), "V": d.V,
        "F": list(d.F), "M": d.M,
    }


def _dims_from_wire(d: Dict[str, Any]) -> InstanceDims:
    return InstanceDims(
        graph_type=d["graph_type"], D=int(d["D"]),
        arities=tuple(int(a) for a in d["arities"]), V=int(d["V"]),
        F=tuple(int(f) for f in d["F"]), M=int(d["M"]),
    )


# --------------------------------------------------------------------------
# head side: the service-shaped proxy + process handle
# --------------------------------------------------------------------------


class _ProxyCounters:
    """Mirror of a child's ServeCounters, refreshed by stats frames."""

    def __init__(self, replica: str):
        self._replica = replica
        self._last: Dict[str, Any] = {"replica": replica}
        self._lock = threading.Lock()

    def update(self, d: Dict[str, Any]) -> None:
        with self._lock:
            self._last = dict(d)

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._last)


class _ProxyCache:
    """Mirror of a child's CompileCache: stats from stats frames,
    warmth probed against the key strings the child streamed."""

    def __init__(self):
        self._stats: Dict[str, Any] = {}
        self._warm: set = set()
        self._lock = threading.Lock()

    def update(self, stats: Dict[str, Any],
               keys: Optional[Sequence[str]] = None) -> None:
        with self._lock:
            if stats:
                self._stats = dict(stats)
            if keys:
                self._warm.update(keys)

    def has(self, key: Tuple) -> bool:
        printable = "/".join(str(k) for k in key)
        with self._lock:
            return printable in self._warm

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._stats)


class ReplicaProxy:
    """The slice of the SolveService surface the fleet base class
    touches, re-implemented over the journal socket.  TCP ordering +
    the wire layer's apply-exactly-once contract make the command
    stream behave like in-order method calls on the child: a
    ``prewarm_targets`` frame sent before a ``submit`` frame warms the
    child's cache before that job's admission, exactly like the
    blocking call the thread fleet makes."""

    def __init__(self, fleet: "ProcessFleet", name: str):
        self._fleet = fleet
        self.name = name
        self.handle: Optional["ProcessReplicaHandle"] = None
        self.counters = _ProxyCounters(name)
        self.cache = _ProxyCache()
        self.ready = False
        self._open = 0  # jobs handed over and not yet completed
        self._lock = threading.Lock()
        #: mirrors SolveService._failure for the base class's
        #: ``ReplicaHandle.dead`` — the process fleet detects death
        #: via waitpid/heartbeat instead, so this stays None
        self._failure = None

    # -- bookkeeping called by the fleet on frames ---------------------------

    def job_opened(self) -> None:
        with self._lock:
            self._open += 1

    def job_closed(self) -> None:
        with self._lock:
            self._open = max(0, self._open - 1)

    @property
    def _backlog(self) -> int:
        with self._lock:
            return self._open

    # -- SolveService surface ------------------------------------------------

    def submit(self, dcop, algo: str,
               algo_params: Optional[Dict[str, Any]] = None,
               seed: int = 0, tenant: str = "default",
               priority: int = 0, deadline_s: Optional[float] = None,
               label: Optional[str] = None,
               source_file: Optional[str] = None,
               stream: bool = False, spec: Any = None,
               _jid: Optional[str] = None, _journal: bool = True,
               _restore: Optional[Tuple] = None) -> str:
        if self.handle is not None and self.handle.dead:
            raise ServiceStopped(
                f"replica process {self.name} is down"
            )
        if not source_file:
            # no DCOP→YAML dumper exists: jobs cross the process
            # boundary by path, so the front door must have one
            raise ValueError(
                "process-fleet jobs need a source_file: the replica "
                "process re-loads the DCOP from its YAML path"
            )
        restore_path = None
        if _restore is not None:
            # spill the recovered checkpoint state back to disk (CRC'd
            # npz, PR 6 format) and ship the PATH — the filesystem is
            # the shared medium, the socket carries the pointer
            from pydcop_tpu.runtime.checkpoint import write_state_npz

            meta, arrays = _restore
            restore_path = os.path.join(
                self._fleet.spill_dir, f"{_jid}.npz"
            )
            write_state_npz(restore_path, arrays, dict(meta))
        self._fleet.hub.send(self.name, {
            "cmd": "submit", "jid": _jid, "algo": algo,
            "algo_params": _json_safe(dict(algo_params or {})),
            "seed": int(seed), "tenant": tenant,
            "priority": int(priority), "deadline_s": deadline_s,
            "label": label, "source_file": source_file,
            "stream": bool(stream), "restore": restore_path,
        })
        self.job_opened()
        return _jid or ""

    def prewarm_targets(self, items: Sequence[Tuple], block: bool = False
                        ) -> int:
        entries = [
            [algo, _json_safe(dict(params or {})), _dims_to_wire(dims)]
            for algo, params, dims in items
        ]
        if not entries:
            return 0
        self._fleet.hub.send(self.name, {
            "cmd": "prewarm_targets", "entries": entries,
        })
        return len(entries)

    def prewarm(self, items: Sequence[Tuple], block: bool = False
                ) -> None:
        """Ship a prewarm by source path.  Items whose first element
        is a DCOP object are resolved to the path of a fleet job that
        carries the same object (the re-seat path); unresolvable items
        are skipped — prewarming is an optimization, never fatal."""
        wire_items = []
        for it in items:
            head, algo = it[0], it[1]
            params = dict(it[2]) if len(it) > 2 and it[2] else {}
            path = head if isinstance(head, str) \
                else self._fleet.source_file_for(head)
            if path:
                wire_items.append([path, algo, _json_safe(params)])
        if wire_items:
            self._fleet.hub.send(self.name, {
                "cmd": "prewarm", "items": wire_items,
            })

    def set_deadline_pressure(self, factor: float,
                              exempt_priority: Optional[int] = None
                              ) -> None:
        self._fleet.hub.send(self.name, {
            "cmd": "pressure", "factor": float(factor),
            "exempt_priority": exempt_priority,
        })

    def stall_for(self, duration: float) -> None:
        self._fleet.hub.send(self.name, {
            "cmd": "stall", "duration": float(duration),
        })

    def halt(self) -> None:
        """The real kill -9 lives on the handle (SIGKILL); the proxy
        has nothing to halt locally."""

    def start(self) -> None:  # the child runs its own scheduler
        pass

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        self._fleet.hub.send(self.name, {"cmd": "stop"})

    def tick(self) -> bool:
        return self._backlog > 0


@dataclasses.dataclass
class ProcessReplicaHandle(ReplicaHandle):
    """A replica that is a child OS process.  ``service`` is a
    :class:`ReplicaProxy`; liveness is the process itself."""

    proc: Optional[subprocess.Popen] = None
    attempt: int = 0
    stderr_path: Optional[str] = None

    def kill(self) -> None:
        """The REAL kill -9: SIGKILL the whole child process."""
        self.killed = True
        self.killed_at = monotonic()
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass

    @property
    def dead(self) -> bool:
        return self.killed or (
            self.proc is not None and self.proc.poll() is not None
        )

    @property
    def returncode(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    @property
    def retryable(self) -> bool:
        """The PR 1 exit-code taxonomy: signal death (kill -9, OOM,
        preemption) and the injected-kill exit code are retryable —
        the watchdog relaunches; a clean exit or a nonzero config
        failure is not."""
        rc = self.returncode
        if self.killed:
            return True
        return rc is not None and (rc < 0 or rc == KILL_EXIT_CODE)

    @property
    def down_reason(self) -> str:
        rc = self.returncode
        if self.killed:
            return "injected kill (SIGKILL)"
        if rc is None:
            return "scheduler died"
        if rc < 0:
            return f"process died by signal {-rc}"
        if rc == KILL_EXIT_CODE:
            return "process injected kill"
        if rc == 0:
            return "process exited"
        return f"process failed (rc={rc})"


# --------------------------------------------------------------------------
# the process fleet
# --------------------------------------------------------------------------


class ProcessFleet(SolveFleet):
    """N replica child processes behind the fleet front door.

    Reuses the whole SolveFleet contract — admission, warm-first
    routing, re-seat, RTO records, metrics — over process-shaped
    handles.  ``journal_dir`` is REQUIRED: it is the shared medium
    (per-replica journals + checkpoints, the artifact store, re-seat
    spills) and the home of the head-fsynced ``fleet.jsonl``.

    ``relaunch_max`` bounds watchdog relaunches per replica slot;
    relaunched incarnations get a FRESH name (``replica-1r1``) and
    journal directory so a stale incarnation's journal can never be
    mistaken for the live one's, and bootstrap warm from the shared
    artifact store (zero XLA compiles, pinned in tests)."""

    _INJECT_KINDS = SolveFleet._INJECT_KINDS + (
        "kill_process", "partition_socket", "corrupt_artifact",
    )

    def __init__(
        self,
        replicas: int = 2,
        lanes: int = 4,
        max_cycles: int = 0,
        journal_dir: Optional[str] = None,
        checkpoint_every: int = 4,
        max_buckets: Optional[int] = None,
        max_pending: Optional[int] = None,
        tenant_quota: Optional[int] = None,
        fault_plan: Optional[FaultPlan] = None,
        heartbeat_timeout: float = 2.0,
        supervise_interval: float = 0.05,
        counters: Optional[FleetCounters] = None,
        devices_per_replica: int = 8,
        relaunch: bool = True,
        relaunch_max: int = 2,
        backoff_base: float = 0.25,
        backoff_max: float = 4.0,
        python: Optional[str] = None,
        child_env: Optional[Dict[str, str]] = None,
        memo=None,
    ):
        if not journal_dir:
            raise ValueError(
                "ProcessFleet requires a journal_dir: it is the "
                "shared filesystem medium of the whole deployment"
            )
        os.makedirs(journal_dir, exist_ok=True)
        # everything the spawning _add_replica override needs must
        # exist BEFORE the base __init__ spawns the initial replicas
        self.artifact_dir = os.path.join(journal_dir, ARTIFACT_SUBDIR)
        os.makedirs(self.artifact_dir, exist_ok=True)
        self.spill_dir = os.path.join(journal_dir, SPILL_SUBDIR)
        os.makedirs(self.spill_dir, exist_ok=True)
        self.relaunch = bool(relaunch)
        self.relaunch_max = int(relaunch_max)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._python = python or sys.executable
        self._child_env = dict(child_env or {})
        self._checkpoint_every = int(checkpoint_every)
        self._pending_relaunch: List[Dict[str, Any]] = []
        self.hub = JournalHub(on_record=self._on_frame)
        if max_cycles <= 0:
            from pydcop_tpu.batch.engine import DEFAULT_MAX_CYCLES

            max_cycles = DEFAULT_MAX_CYCLES
        super().__init__(
            replicas=replicas, lanes=lanes, max_cycles=max_cycles,
            journal_dir=journal_dir,
            checkpoint_every=checkpoint_every,
            max_buckets=max_buckets, max_pending=max_pending,
            tenant_quota=tenant_quota, fault_plan=fault_plan,
            heartbeat_timeout=heartbeat_timeout,
            supervise_interval=supervise_interval,
            shared_xla_cache=False, counters=counters,
            devices_per_replica=devices_per_replica,
            memo=memo,
        )
        # child heartbeats beat regardless of how the head runs: judge
        # staleness in tick-driven mode too
        self._hb_check_always = True

    def _injector_faults(self, fault_plan: Optional[FaultPlan]):
        if fault_plan is None:
            return []
        return fault_plan.fleet_faults() + fault_plan.process_faults()

    # -- spawning ------------------------------------------------------------

    def _add_replica(self, index: int, checkpoint_every: int,
                     attempt: int = 0) -> ProcessReplicaHandle:
        name = (f"replica-{index}" if attempt == 0
                else f"replica-{index}r{attempt}")
        jd = os.path.join(self.journal_dir, name)
        os.makedirs(jd, exist_ok=True)
        hb = os.path.join(self.journal_dir, f"{name}.hb")
        err_path = os.path.join(self.journal_dir, f"{name}.err")
        proxy = ReplicaProxy(self, name)
        proc = self._spawn(name, jd, hb, err_path, checkpoint_every,
                           attempt)
        handle = ProcessReplicaHandle(
            name=name, index=index, service=proxy,
            journal_dir=jd, hb_path=hb,
            devices_total=self.devices_per_replica,
            proc=proc, attempt=attempt, stderr_path=err_path,
        )
        proxy.handle = handle
        self._handles[name] = handle
        self.router.add_replica(name, warm_probe=proxy.cache.has)
        self.counters.inc("replicas_up")
        send_fleet("replica.up", {
            "name": name, "pid": proc.pid, "attempt": attempt,
        })
        if self.journal is not None:
            self.journal.append({
                "kind": "replica", "event": "up", "name": name,
                "pid": proc.pid, "attempt": attempt,
            })
        return handle

    def _spawn(self, name: str, jd: str, hb: str, err_path: str,
               checkpoint_every: int, attempt: int
               ) -> subprocess.Popen:
        cmd = [
            self._python, "-m", "pydcop_tpu", "serve-replica",
            "--connect", f"127.0.0.1:{self.hub.port}",
            "--name", name,
            "--journal-dir", jd,
            "--heartbeat-file", hb,
            "--artifact-dir", self.artifact_dir,
            "--lanes", str(self.lanes),
            "--max-cycles", str(self.max_cycles),
            "--checkpoint-every", str(checkpoint_every),
        ]
        if self.max_buckets is not None:
            cmd += ["--max-buckets", str(self.max_buckets)]
        if self.memo_cfg is not None:
            cmd += ["--memo"]
        env = {**os.environ, **self._child_env}
        # the artifact store replaces the persistent XLA cache in the
        # children — and the two must not coexist: an executable that
        # COMPILES from the disk cache serializes without its kernel
        # symbols, i.e. into an artifact no peer can deserialize
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env[ENV_FAULT_ATTEMPT] = str(attempt)
        if self._fault_plan is not None \
                and self._fault_plan.serve_faults():
            env[ENV_FAULT_PLAN] = self._fault_plan.to_json()
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", ""
        )
        # stderr to a FILE (the exit-code taxonomy reads it), never a
        # pipe a busy child could block on — the PR 1 discipline
        err_file = open(err_path, "wb")
        try:
            return subprocess.Popen(
                cmd, stdout=subprocess.DEVNULL, stderr=err_file,
                env=env,
            )
        finally:
            err_file.close()

    def add_replica(self) -> str:
        """Cold-join one more replica process to the running fleet.
        It bootstraps warm from the shared artifact store — its first
        job pays zero XLA compiles (the bring-up pin)."""
        with self._lock:
            index = 1 + max(
                (h.index for h in self._handles.values()), default=-1
            )
        h = self._add_replica(index, self._checkpoint_every)
        return h.name

    def handle(self, name_or_index) -> ReplicaHandle:
        """Index lookups resolve to the NEWEST incarnation of that
        replica slot (relaunches rename), preferring a live one."""
        if isinstance(name_or_index, int):
            cands = [h for h in self._handles.values()
                     if h.index == name_or_index]
            if not cands:
                raise KeyError(f"no replica with index {name_or_index}")
            live = [h for h in cands if h.up]
            return (live or cands)[-1]
        return self._handles[name_or_index]

    def source_file_for(self, dcop) -> Optional[str]:
        """The YAML path of a fleet job carrying this DCOP object —
        how object-shaped prewarm requests cross the process
        boundary."""
        with self._lock:
            for fj in self._jobs.values():
                if fj.dcop is dcop and fj.source_file:
                    return fj.source_file
        return None

    def prewarm(self, items: Sequence[Tuple],
                block: bool = False) -> Dict[str, int]:
        """Path-shaped fleet prewarm: items are ``(yaml_path | dcop,
        algo, params)``.  Routing keys are computed head-side (paths
        load once); the chosen replica receives the PATH over the
        socket, since DCOP objects don't cross the process boundary.
        Unresolvable object-shaped items are skipped — prewarming is
        an optimization, never fatal."""
        from pydcop_tpu.dcop import load_dcop_from_file
        from pydcop_tpu.serve.router import job_routing_key

        loaded: Dict[str, Any] = {}
        groups: Dict[Tuple, List[Tuple]] = {}
        for it in items:
            head, algo = it[0], it[1]
            params = dict(it[2]) if len(it) > 2 and it[2] else {}
            if isinstance(head, str):
                path = head
                if path not in loaded:
                    loaded[path] = load_dcop_from_file([path])
                dcop = loaded[path]
            else:
                dcop, path = head, self.source_file_for(head)
            if not path:
                continue
            groups.setdefault(
                job_routing_key(dcop, algo, params), []
            ).append((path, algo, params))
        out: Dict[str, int] = {}
        names = self.router.routable()
        if not names:
            return out
        for i, (key, group) in enumerate(
            sorted(groups.items(), key=lambda kv: str(kv[0]))
        ):
            name = names[i % len(names)]
            self.router.note_warm(name, key)
            self._handles[name].service.prewarm(group, block=block)
            out[name] = out.get(name, 0) + 1
        return out

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block until every live replica process has connected and
        reported ready (its scheduler is up and beating)."""
        deadline = monotonic() + timeout
        while monotonic() < deadline:
            with self._lock:
                pending = [
                    h for h in self._handles.values()
                    if h.up and not h.dead
                    and not getattr(h.service, "ready", True)
                ]
            if not pending:
                return True
            if self._started:
                time.sleep(0.05)
            else:
                self.hub.pump(0.05)
        return False

    # -- the frame tap -------------------------------------------------------

    def _on_frame(self, client: str, body: Dict[str, Any]) -> None:
        """Apply one EXACTLY-ONCE frame from a replica process (the
        wire layer deduplicated replays already)."""
        h = self._handles.get(client)
        if h is None or not isinstance(h.service, ReplicaProxy):
            return
        proxy: ReplicaProxy = h.service
        evt = body.get("evt")
        if evt == "ready":
            proxy.ready = True
            send_fleet("replica.ready", {
                "name": client, "pid": body.get("pid"),
                "abi": body.get("abi"),
            })
            if self.journal is not None:
                self.journal.append({
                    "kind": "replica", "event": "ready",
                    "name": client, "abi": body.get("abi"),
                })
        elif evt == "complete":
            self._on_child_complete(h, proxy, body)
        elif evt == "stats":
            proxy.counters.update(body.get("serve") or {})
            proxy.cache.update(body.get("cache") or {},
                               body.get("cache_keys"))
        elif evt == "warm":
            # router warmth rides note_warm at placement time; the key
            # set feeds the warm_probe (proxy.cache.has) directly
            proxy.cache.update({}, body.get("keys"))
        elif evt == "reject":
            self._on_child_reject(h, proxy, body)
        elif evt == "memo":
            self._on_child_memo(h, body)
        elif evt == "journal":
            rec = body.get("record")
            if self.journal is not None and isinstance(rec, dict):
                self.journal.append(rec)

    def _on_child_memo(self, h: ProcessReplicaHandle,
                       body: Dict[str, Any]) -> None:
        """A child memoised a freshly-solved instance: journal the
        record and tell every OTHER child to adopt the persisted entry
        off the shared filesystem (``memo_adopt`` command → child-side
        :meth:`MemoCache.adopt_file`, CRC-checked — a corrupt frame is
        skipped-and-counted child-side, never served).  The socket-wire
        twin of the thread fleet's in-memory adoption tap."""
        path = body.get("path")
        if self.journal is not None:
            self.journal.append({
                "kind": "memo", "key": body.get("key"),
                "tenant": body.get("tenant"),
                "algo": body.get("algo"),
                "replica": h.name, "path": path,
            })
        if not path:
            return
        shared = 0
        with self._lock:
            peers = [
                p.name for p in self._handles.values()
                if p.name != h.name and p.up and not p.dead
            ]
        for peer in peers:
            try:
                self.hub.send(peer, {
                    "cmd": "memo_adopt", "path": path,
                })
                shared += 1
            except Exception:
                pass  # a severed peer just misses this adoption
        if shared:
            self.counters.inc("memo_shared", shared)
            send_fleet("memo.shared", {
                "key": body.get("key"), "from": h.name,
                "peers": shared,
            })

    def _on_child_complete(self, h: ProcessReplicaHandle,
                           proxy: ReplicaProxy,
                           body: Dict[str, Any]) -> None:
        r = body.get("result") or {}
        res = SolveResult(
            status=r.get("status", "ERROR"),
            assignment=r.get("assignment") or {},
            cost=r.get("cost"), violation=r.get("violation"),
            cycle=int(r.get("cycle", 0)),
            msg_count=int(r.get("msg_count", 0)),
            msg_size=float(r.get("msg_size", 0.0)),
            time=float(r.get("time", 0.0)),
        )
        res.serve = r.get("serve")
        res.harness = r.get("harness")
        res.config = r.get("config")
        res.memo = r.get("memo")
        proxy.job_closed()
        job = _RemoteJobView(
            jid=body.get("jid", ""), tenant=body.get("tenant", ""),
            service_stopped=bool(body.get("service_stopped", False)),
        )
        self._on_replica_complete(h, job, res)

    def _on_child_reject(self, h: ProcessReplicaHandle,
                         proxy: ReplicaProxy,
                         body: Dict[str, Any]) -> None:
        """A replica refused a handed-over job (bad source file, dead
        admission): the process-mode twin of the _place_on exception
        path — re-place once on a peer, else fail structuredly."""
        jid = body.get("jid")
        with self._lock:
            fj = self._jobs.get(jid)
            if fj is None or fj.done.is_set():
                return
        proxy.job_closed()
        self.router.job_finished(h.name)
        placed = self.router.place(fj.key, jid=fj.jid, exclude=h.name)
        if placed is None:
            self._fail_job(
                fj, f"replica {h.name} rejected the job and no peer "
                f"is routable: {body.get('error')}"
            )
            return
        with self._lock:
            fj.replica = placed[0]
        self._place_on(fj, placed[0])

    # -- supervision ---------------------------------------------------------

    def _supervise(self) -> None:
        self.hub.pump(0)
        super()._supervise()
        self._fire_due_relaunches()

    def _inject(self, kind: str, fault, now: float) -> None:
        if kind == "kill_process":
            h = self.handle(int(fault.replica))
            self.counters.inc("faults_injected")
            send_fleet("fault.injected", {
                "kind": kind, "replica": h.name, "tick": self._ticks,
            })
            with self._lock:
                live = h.up and not h.killed
            if live:
                h.kill()
        elif kind == "partition_socket":
            h = self.handle(int(fault.replica))
            self.counters.inc("faults_injected")
            send_fleet("fault.injected", {
                "kind": kind, "replica": h.name, "tick": self._ticks,
            })
            self.hub.partition(
                h.name,
                fault.duration if fault.duration > 0 else float("inf"),
            )
            with self._lock:
                h.partition_until = (
                    now + fault.duration if fault.duration > 0
                    else float("inf")
                )
            self.router.set_partitioned(h.name, True)
            self.counters.inc("replicas_partitioned")
            self.counters.inc("socket_partitions")
            send_fleet("replica.partitioned", {
                "name": h.name, "duration": fault.duration,
                "socket": True,
            })
        elif kind == "corrupt_artifact":
            self.counters.inc("faults_injected")
            path = fault.path
            if path is None:
                arts = sorted(
                    n for n in os.listdir(self.artifact_dir)
                    if n.endswith(".rnr")
                )
                if not arts:
                    return
                pick = (self._fault_plan.seed + self._ticks) % len(arts)
                path = os.path.join(self.artifact_dir, arts[pick])
            if corrupt_artifact_file(path, seed=self._fault_plan.seed):
                self.counters.inc("artifacts_corrupted")
                send_fleet("fault.injected", {
                    "kind": kind, "path": path, "tick": self._ticks,
                })
                if self.journal is not None:
                    self.journal.append({
                        "kind": "artifact", "event": "corrupted",
                        "path": path,
                    })
        else:
            super()._inject(kind, fault, now)

    def _replica_down(self, h: ReplicaHandle, reason: str,
                      t_detect: float) -> None:
        if isinstance(h, ProcessReplicaHandle) and h.proc is not None:
            try:  # reap the zombie: waitpid is the death ground truth
                h.proc.wait(timeout=5)
            except (subprocess.TimeoutExpired, OSError):
                pass
        super()._replica_down(h, reason, t_detect)
        if (
            isinstance(h, ProcessReplicaHandle)
            and self.relaunch and h.retryable
            and h.attempt < self.relaunch_max
        ):
            delay = min(self.backoff_max,
                        self.backoff_base * (2 ** h.attempt))
            with self._lock:
                self._pending_relaunch.append({
                    "index": h.index, "attempt": h.attempt + 1,
                    "due": monotonic() + delay, "from": h.name,
                })
            send_fleet("replica.relaunch_scheduled", {
                "name": h.name, "attempt": h.attempt + 1,
                "delay_s": round(delay, 3),
            })

    def _fire_due_relaunches(self) -> None:
        now = monotonic()
        with self._lock:
            if self._stopped or not self._pending_relaunch:
                return
            due = [r for r in self._pending_relaunch if r["due"] <= now]
            self._pending_relaunch = [
                r for r in self._pending_relaunch if r["due"] > now
            ]
        for r in due:
            h = self._add_replica(r["index"], self._checkpoint_every,
                                  attempt=r["attempt"])
            self.counters.inc("replicas_relaunched")
            send_fleet("replica.relaunched", {
                "name": h.name, "from": r["from"],
                "attempt": r["attempt"],
            })
            if self.journal is not None:
                self.journal.append({
                    "kind": "replica", "event": "relaunched",
                    "name": h.name, "from": r["from"],
                })

    # -- lifecycle -----------------------------------------------------------

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        super().stop(drain=drain, timeout=timeout)
        for h in self._handles.values():
            if not isinstance(h, ProcessReplicaHandle) \
                    or h.proc is None:
                continue
            try:
                h.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                try:
                    h.proc.terminate()
                    h.proc.wait(timeout=3)
                except (subprocess.TimeoutExpired, OSError):
                    try:
                        h.proc.kill()
                        h.proc.wait(timeout=3)
                    except (subprocess.TimeoutExpired, OSError):
                        pass
            except OSError:
                pass
        self.hub.stop()

    def metrics(self) -> Dict[str, Any]:
        m = super().metrics()
        m["hub"] = self.hub.stats()
        m["artifacts"] = ArtifactStore(self.artifact_dir).stats() \
            if os.path.isdir(self.artifact_dir) else None
        with self._lock:
            m["pending_relaunches"] = len(self._pending_relaunch)
        return m


@dataclasses.dataclass
class _RemoteJobView:
    """The completion tap's view of a job that lives in another
    process — just the fields _on_replica_complete reads."""

    jid: str
    tenant: str = ""
    service_stopped: bool = False


# --------------------------------------------------------------------------
# child side
# --------------------------------------------------------------------------


class ReplicaWorker:
    """The replica child process body: a REAL :class:`SolveService`
    (own scheduler thread, journal, heartbeat, compile cache backed by
    the shared artifact store) driven by command frames from the
    head's hub.

    The main loop is the socket's single owner: completions produced
    on the scheduler thread queue into an outbox the loop drains, so
    the :class:`JournalClient` never crosses threads.  Importable and
    loop-drivable — protocol tests host it on a thread over a real
    socket without paying a process spawn."""

    def __init__(
        self,
        connect: Tuple[str, int],
        name: str,
        journal_dir: Optional[str] = None,
        heartbeat_path: Optional[str] = None,
        artifact_dir: Optional[str] = None,
        lanes: int = 4,
        max_cycles: int = 0,
        checkpoint_every: int = 4,
        max_buckets: Optional[int] = None,
        fault_plan: Optional[FaultPlan] = None,
        stats_interval: float = 0.25,
        memo: bool = False,
    ):
        from pydcop_tpu.serve.service import SolveService

        if max_cycles <= 0:
            from pydcop_tpu.batch.engine import DEFAULT_MAX_CYCLES

            max_cycles = DEFAULT_MAX_CYCLES
        self.name = name
        store = ArtifactStore(artifact_dir) if artifact_dir else None
        if store is not None:
            # the artifact store IS this process's cross-process
            # compile cache; the XLA persistent cache must be OFF in
            # an exporting replica — an executable satisfied from the
            # disk cache serializes without its kernel symbols and the
            # resulting artifact is undeserializable ("Symbols not
            # found" at load).  One-time config, before any compile.
            try:
                import jax

                jax.config.update("jax_compilation_cache_dir", None)
                # the config alone is ignored once the cache singleton
                # is memoized by an earlier compile; reset to be sure
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # older jax without the option: fine
                pass
        self.cache = CompileCache(artifacts=store)
        memo_cache = None
        if memo:
            # persisted under THIS child's journal subdir (the shared
            # filesystem): peers adopt the npz by path on memo_adopt
            from pydcop_tpu.serve.memo import (
                MEMO_SUBDIR,
                MemoCache,
                MemoConfig,
            )

            memo_cache = MemoCache(
                MemoConfig(),
                directory=(
                    os.path.join(journal_dir, MEMO_SUBDIR)
                    if journal_dir else None
                ),
                on_insert=self._queue_memo,
            )
        self.service = SolveService(
            lanes=lanes, cache=self.cache,
            counters=ServeCounters(replica=name),
            max_cycles=max_cycles, journal_dir=journal_dir,
            checkpoint_every=checkpoint_every,
            max_buckets=max_buckets, max_pending=None,
            tenant_quota=None, replica=name,
            heartbeat_path=heartbeat_path, fault_plan=fault_plan,
            on_complete=self._queue_complete, memo=memo_cache,
        )
        self.client = JournalClient(
            connect, name, on_record=self._on_command,
            max_retries=1,
        )
        self.stats_interval = float(stats_interval)
        self._outbox: deque = deque()
        self._outlock = threading.Lock()
        self._dcops: Dict[str, Any] = {}
        self._stop = False
        self._ppid = os.getppid()

    # -- completion tap (scheduler thread) -----------------------------------

    def _queue_complete(self, job, res: SolveResult) -> None:
        body = {
            "evt": "complete", "jid": job.jid, "tenant": job.tenant,
            "service_stopped": bool(
                getattr(job, "service_stopped", False)
            ),
            "result": {
                "status": res.status,
                "assignment": _json_safe(res.assignment or {}),
                "cost": _json_safe(res.cost),
                "violation": _json_safe(res.violation),
                "cycle": int(res.cycle),
                "msg_count": int(res.msg_count),
                "msg_size": float(res.msg_size),
                "time": float(res.time),
                "serve": _json_safe(res.serve or {}),
                "harness": _json_safe(res.harness),
                "config": _json_safe(res.config),
                "memo": _json_safe(res.memo),
            },
        }
        with self._outlock:
            self._outbox.append(body)

    def _queue_memo(self, entry) -> None:
        """Memo insert tap (scheduler thread): announce the persisted
        entry to the head so peers adopt it.  An unpersisted entry
        (no journal dir) has no shared-filesystem medium — skip."""
        if not entry.path:
            return
        with self._outlock:
            self._outbox.append({
                "evt": "memo", "key": entry.key,
                "tenant": entry.tenant, "algo": entry.algo,
                "path": entry.path,
            })

    # -- command dispatch (main loop) ----------------------------------------

    def _dcop(self, source_file: str):
        dcop = self._dcops.get(source_file)
        if dcop is None:
            from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

            dcop = load_dcop_from_file([source_file])
            self._dcops[source_file] = dcop
        return dcop

    def _on_command(self, body: Dict[str, Any]) -> None:
        cmd = body.get("cmd")
        if cmd == "submit":
            self._do_submit(body)
        elif cmd == "prewarm_targets":
            items = [
                (algo, dict(params or {}), _dims_from_wire(dims))
                for algo, params, dims in body.get("entries") or []
            ]
            self.service.prewarm_targets(items, block=True)
            self._send_warm()
        elif cmd == "prewarm":
            items = []
            for path, algo, params in body.get("items") or []:
                try:
                    items.append(
                        (self._dcop(path), algo, dict(params or {}))
                    )
                except Exception:
                    pass  # prewarm is an optimization, never fatal
            if items:
                self.service.prewarm(items, block=True)
                self._send_warm()
        elif cmd == "stall":
            self.service.stall_for(float(body.get("duration", 0.0)))
        elif cmd == "pressure":
            self.service.set_deadline_pressure(
                float(body.get("factor", 1.0)),
                exempt_priority=body.get("exempt_priority"),
            )
        elif cmd == "memo_adopt":
            path = body.get("path")
            if path and self.service.memo is not None:
                # CRC-checked load: a corrupt entry is skipped-and-
                # counted inside adopt_file, never served
                self.service.memo.adopt_file(path)
        elif cmd == "stats":
            self._send_stats()
        elif cmd == "stop":
            self._stop = True

    def _do_submit(self, body: Dict[str, Any]) -> None:
        jid = body.get("jid")
        try:
            dcop = self._dcop(body["source_file"])
            restore = None
            if body.get("restore"):
                from pydcop_tpu.runtime.checkpoint import (
                    read_state_npz,
                )

                meta, arrays = read_state_npz(body["restore"])
                restore = (meta, arrays)
            self.service.submit(
                dcop, body["algo"],
                algo_params=dict(body.get("algo_params") or {}),
                seed=int(body.get("seed", 0)),
                tenant=body.get("tenant", "default"),
                priority=int(body.get("priority", 0)),
                deadline_s=body.get("deadline_s"),
                label=body.get("label"),
                source_file=body["source_file"],
                stream=bool(body.get("stream", False)),
                _jid=jid, _restore=restore,
            )
        except Exception as e:
            with self._outlock:
                self._outbox.append({
                    "evt": "reject", "jid": jid, "error": str(e),
                })

    # -- outbound ------------------------------------------------------------

    def _flush_outbox(self) -> None:
        while True:
            with self._outlock:
                if not self._outbox:
                    return
                body = self._outbox.popleft()
            self.client.send(body)

    def _send_stats(self) -> None:
        self.client.send({
            "evt": "stats",
            "serve": _json_safe(self.service.counters.as_dict()),
            "cache": _json_safe(self.cache.stats()),
            "cache_keys": self.cache.key_strings(),
            "backlog": self.service._backlog,
        })

    def _send_warm(self) -> None:
        self.client.send({
            "evt": "warm", "keys": self.cache.key_strings(),
        })

    # -- main loop -----------------------------------------------------------

    def run(self) -> int:
        self.service.start()
        self.client.send({
            "evt": "ready", "pid": os.getpid(), "abi": abi_tag(),
        })
        last_stats = 0.0
        try:
            while not self._stop:
                self.client.pump(0.05)
                self._flush_outbox()
                now = monotonic()
                if now - last_stats >= self.stats_interval:
                    self._send_stats()
                    last_stats = now
                if os.getppid() != self._ppid:
                    break  # orphaned: the head died, exit cleanly
        finally:
            self._flush_outbox()
            try:
                self.service.stop(drain=False)
            except Exception:
                pass
            self.client.close()
        return 0
