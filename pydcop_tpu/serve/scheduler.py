"""Slot scheduler — continuous batching over the batch engine's buckets.

The batch engine (pydcop_tpu.batch) solves a *static* list of
instances: every bucket is formed once, runs to completion, and the
lanes of converged instances sit frozen until the slowest lane
finishes.  A serving workload is the opposite shape — jobs arrive as a
stream — so this module keeps each bucket *open*:

* a :class:`BucketWorker` owns ONE compiled fixed-shape runner (the
  engine's own, via the shared compile cache) and ``B`` lanes;
* each lane independently carries one job at its own age: the runner's
  per-lane ``n_active`` vector lets lane *i* advance ``n_i`` cycles
  per step while its neighbors advance a different count;
* when a lane's job converges (or its deadline expires) the lane is
  released at the chunk boundary and the next queued job is written
  into the freed slot — the LLM-serving trick called continuous
  batching;
* deadline-pressured lanes shrink their own per-step cycle count via
  the harness's :func:`algorithms.base.clamp_chunk_to_deadline`, so a
  tenant's job never overruns its budget by a whole chunk.

Bit-identity is the load-bearing contract: a lane's PRNG stream is its
OWN key advanced by the harness's exact per-chunk policy at the job's
TRUE shape, its convergence accounting (first-chunk skip, two stable
chunks) is the harness's own, and padding is inert by routing — so a
job admitted into a running bucket, a job that joins a freed lane, and
a job migrated between same-signature buckets all produce the SAME
bits as a standalone ``solver.run``.  (The one documented exception:
deadline-shrunk lanes change their own chunk boundaries — and with
them their own stream — exactly like a standalone solve under a
``timeout``; other lanes are unaffected.)
"""
from __future__ import annotations

import dataclasses
from time import monotonic, perf_counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from pydcop_tpu.algorithms import DEFAULT_INFINITY
from pydcop_tpu.algorithms.base import (
    SolveResult,
    clamp_chunk_to_deadline,
    default_chunk,
)
from pydcop_tpu.batch.bucketing import InstanceDims, bucket_signature
from pydcop_tpu.batch.engine import (
    DEFAULT_MAX_CYCLES,
    BucketMeta,
    _params_key,
    adapter_for,
    build_bucket_runner,
    pad_instance,
    runner_cache_key,
)
from pydcop_tpu.runtime.stats import ServeCounters

#: key object for idle lanes — never advanced, never drawn from
_IDLE_KEY_SEED = 0


def serve_target(members: Sequence[InstanceDims]) -> InstanceDims:
    """Element-wise max of the members' dims with the dummy-variable
    slot ALWAYS reserved: a serving bucket outlives its founding jobs,
    and any later arrival smaller than the target needs the dummy to
    route its factor/pair padding to (engine.pad_instance)."""
    first = members[0]
    return InstanceDims(
        graph_type=first.graph_type,
        D=max(m.D for m in members),
        arities=first.arities,
        V=max(m.V for m in members) + 1,
        F=tuple(
            max(m.F[i] for m in members)
            for i in range(len(first.arities))
        ),
        M=max(m.M for m in members),
    )


def fits(dims: InstanceDims, target: InstanceDims) -> bool:
    """Can an instance with ``dims`` be padded into ``target``?  The
    arity set must match exactly (a missing arity bucket cannot be
    padded in); everything else pads up, with one variable slot held
    back for the dummy."""
    return (
        dims.graph_type == target.graph_type
        and dims.arities == target.arities
        and dims.D <= target.D
        and dims.V <= target.V - 1
        and all(f <= tf for f, tf in zip(dims.F, target.F))
        and dims.M <= target.M
    )


def dummy_bucket_inputs(algo: str, target: InstanceDims, B: int,
                        chunk: int):
    """(arrays, state, xs) filler for a worker's idle lanes, at the
    exact shapes the compiled runner expects.  Values are inert-by-
    construction (mask selects one valid value per variable, zero cost
    tables): idle lanes are additionally frozen by the done mask, this
    just keeps the vmapped math NaN-free and gives prewarming concrete
    buffers to compile against."""
    Vp, Dp = target.V, target.D
    mask = np.zeros((B, Vp, Dp), np.float32)
    mask[:, :, 0] = 1.0
    arrays: Dict[str, jnp.ndarray] = {
        "mask": jnp.asarray(mask),
        "unary": jnp.zeros((B, Vp, Dp), jnp.float32),
    }
    edges = 0
    for i, (a, f) in enumerate(zip(target.arities, target.F)):
        arrays[f"bt{i}"] = jnp.zeros((B, f) + (Dp,) * a, jnp.float32)
        arrays[f"bv{i}"] = jnp.zeros((B, f, a), jnp.int32)
        edges += f * a
    arrays["edge_var"] = jnp.zeros((B, edges), jnp.int32)
    if target.graph_type == "constraints_hypergraph":
        arrays["nsrc"] = jnp.zeros((B, target.M), jnp.int32)
        arrays["ndst"] = jnp.zeros((B, target.M), jnp.int32)
    if algo == "gdba":
        for i, f in enumerate(target.F):
            arrays[f"fmin{i}"] = jnp.zeros((B, f), jnp.float32)
            arrays[f"fmax{i}"] = jnp.zeros((B, f), jnp.float32)

    x0 = jnp.zeros((B, Vp), jnp.int32)
    if algo == "gdba":
        ws = tuple(
            jnp.zeros((B, f) + (Dp,) * a, jnp.float32)
            for a, f in zip(target.arities, target.F)
        )
        state: Any = (x0, ws)
    elif algo == "maxsum":
        zq = jnp.zeros((B, edges, Dp), jnp.float32)
        state = (zq, zq, x0)
    else:  # mgm / dsa / adsa
        state = (x0,)

    if algo == "dsa":
        xs: Any = jnp.ones((B, chunk, Vp), jnp.float32)
    elif algo == "adsa":
        ones = jnp.ones((B, chunk, Vp), jnp.float32)
        xs = (ones, ones)
    else:
        xs = None
    return arrays, state, xs


def warm_bucket_runner(adapter, target: InstanceDims,
                       params: Dict[str, Any], B: int, chunk: int,
                       aot: bool = False):
    """Build AND compile one bucket runner.  ``jax.jit`` alone defers
    tracing and XLA compilation to the first call, so a prewarm that
    stopped at the wrapper would still pay the cold compile at
    admission time — this executes the runner once at the real shapes
    (all lanes idle: ``n_active=0``, all done) so the executable is
    resident before the first job arrives.

    With ``aot=True`` the runner is compiled ahead-of-time
    (``lower().compile()``) and returned as a serializable
    :class:`~pydcop_tpu.serve.artifacts.AotRunner` — the same compile,
    paid once, but its executable can be exported to the fleet's
    artifact store so future replica processes skip it entirely."""
    runner = build_bucket_runner(
        adapter, BucketMeta.of(target), params, chunk
    )
    arrays, state, xs = dummy_bucket_inputs(adapter.algo, target, B, chunk)
    n0 = jnp.zeros((B,), jnp.int32)
    done = jnp.ones((B,), bool)
    if aot:
        from pydcop_tpu.serve.artifacts import (
            AotRunner, _serialize_compiled,
        )

        compiled = runner.lower(arrays, state, xs, n0, done).compile()
        runner = AotRunner(compiled, _serialize_compiled(compiled))
    out = runner(arrays, state, xs, n0, done)
    jax.block_until_ready(out)
    return runner


@dataclasses.dataclass
class _Lane:
    """One occupied lane: a job plus its private harness accounting."""

    job: Any  # serve.service.ServeJob
    spec: Any  # engine._Spec
    key: Any  # per-lane PRNG key (the standalone harness's own stream)
    age: int = 0  # cycles this job has run (its stop_cycle when done)
    stable: int = 0  # consecutive stable chunks (2 → converged)
    first_chunk: bool = True  # harness parity: skip the first conv flag
    converged: bool = False


class BucketWorker:
    """One continuously-batched bucket: ``B`` lanes stepping through
    one compiled fixed-shape runner, chunk by chunk.

    The worker itself is single-threaded by contract (the service's
    scheduler thread is its only caller); cross-thread safety lives in
    the compile cache and the service's queues."""

    def __init__(
        self,
        algo: str,
        params: Optional[Dict[str, Any]],
        target: InstanceDims,
        lanes: int,
        cache,
        counters: Optional[ServeCounters] = None,
        limit: int = DEFAULT_MAX_CYCLES,
        chunk: Optional[int] = None,
    ):
        self.algo = algo
        self.params = dict(params or {})
        self.adapter = adapter_for(algo)
        self.target = target
        self.meta = BucketMeta.of(target)
        self.B = int(lanes)
        self.limit = int(limit)
        # the harness's exact chunk policy: the per-chunk PRNG stream
        # depends on it, so serve may not choose its own
        self.chunk = (
            chunk if chunk is not None
            else default_chunk(None, False, False, None, self.limit)
        )
        self.counters = counters if counters is not None else ServeCounters()
        self.pkey = _params_key(self.params)
        self.signature = bucket_signature(target, self.B)
        key = runner_cache_key(algo, self.pkey, self.signature, self.chunk)
        self.runner, self.runner_was_warm = cache.get_or_build(
            key,
            lambda: warm_bucket_runner(
                self.adapter, target, self.params, self.B, self.chunk,
                aot=getattr(cache, "exports_artifacts", False),
            ),
        )
        self.arrays, self.state, _ = dummy_bucket_inputs(
            algo, target, self.B, self.chunk
        )
        self.lanes: List[Optional[_Lane]] = [None] * self.B
        self._used = [False] * self.B  # slot hosted a previous job
        self._idle_key = jax.random.PRNGKey(_IDLE_KEY_SEED)
        self.steps = 0  # chunk boundaries crossed
        self.rate: Optional[float] = None  # measured cycles/sec (EMA)
        #: lanes whose float state went NaN/Inf on the LAST step —
        #: refreshed per step from the runner's finiteness flags; the
        #: service quarantines them before reading results
        self.nonfinite: List[int] = []
        #: quarantine isolation tag: a worker only admits jobs carrying
        #: the SAME tag (None = regular traffic), so bisected suspect
        #: groups cannot re-contaminate healthy buckets
        self.isolate_key: Optional[str] = None
        #: deadline-pressure scaling of the chunk clamp (the SLO
        #: ladder's rung-2 lever, SolveService.set_deadline_pressure):
        #: < 1 makes deadline lanes below ``pressure_exempt_priority``
        #: see only that fraction of their remaining budget, so they
        #: hit chunk boundaries — the only admission points — sooner
        self.deadline_pressure: float = 1.0
        self.pressure_exempt_priority: Optional[int] = None

    # -- occupancy ----------------------------------------------------------

    @property
    def occupied(self) -> int:
        return sum(1 for ln in self.lanes if ln is not None)

    @property
    def free(self) -> int:
        return self.B - self.occupied

    def matches(self, algo: str, pkey: Tuple) -> bool:
        return self.algo == algo and self.pkey == pkey

    # -- admission / release ------------------------------------------------

    def admit(self, job, spec, restore: Optional[Tuple] = None) -> int:
        """Fold one job into a free lane at the current chunk boundary.

        ``restore = (state, key, age, stable, first_chunk)`` re-seats a
        journal-checkpointed job exactly where it stopped; otherwise
        the lane starts the job's own fresh harness stream
        (``PRNGKey(seed)``, cycle 0)."""
        i = self.lanes.index(None)
        arrs = {
            **pad_instance(spec.tensors, self.target),
            **self.adapter.extra_arrays(spec, self.target),
        }
        for k, v in arrs.items():
            self.arrays[k] = self.arrays[k].at[i].set(jnp.asarray(v))
        if restore is not None:
            st, key, age, stable, first = restore
        else:
            st = self.adapter.initial_state(spec, self.target)
            key = jax.random.PRNGKey(job.seed)
            age, stable, first = 0, 0, True
        self.state = jax.tree_util.tree_map(
            lambda L, s: L.at[i].set(jnp.asarray(s)), self.state, st
        )
        self.lanes[i] = _Lane(job=job, spec=spec, key=key, age=age,
                              stable=stable, first_chunk=first)
        self.counters.inc("jobs_admitted")
        if self._used[i]:
            self.counters.inc("lanes_reused")
        self._used[i] = True
        if self.steps > 0:
            self.counters.inc("midflight_admissions")
        return i

    def release(self, i: int) -> None:
        self.lanes[i] = None

    def poison_lane(self, i: int) -> bool:
        """Overwrite lane ``i``'s float state leaves with NaN — the
        chaos-injection hook behind runtime/faults ``nan_lane``, so the
        device-side finiteness check (and everything downstream of it:
        quarantine, retry escalation, counters) is exercised exactly as
        a real numerical blow-up would.  Returns False when the state
        has no float leaf (the pure-integer local-search families) —
        the caller then quarantines the lane directly instead."""
        hit = False

        def poison(L):
            nonlocal hit
            if jnp.issubdtype(L.dtype, jnp.floating):
                hit = True
                return L.at[i].set(jnp.nan)
            return L

        poisoned = jax.tree_util.tree_map(poison, self.state)
        if hit:
            self.state = poisoned
        return hit

    def migrate_from(self, other: "BucketWorker") -> int:
        """Fold ``other``'s occupied lanes into this worker's free
        lanes — the under-filled-bucket merge.  Only legal between
        workers of the SAME signature (identical padded shapes): state
        rows then copy verbatim and every lane's stream continues
        bit-identically."""
        assert other.signature == self.signature
        assert other.matches(self.algo, self.pkey)
        moved = 0
        for j, lane in enumerate(other.lanes):
            if lane is None:
                continue
            try:
                i = self.lanes.index(None)
            except ValueError:
                break
            for k in self.arrays:
                self.arrays[k] = self.arrays[k].at[i].set(
                    other.arrays[k][j]
                )
            self.state = jax.tree_util.tree_map(
                lambda L, S: L.at[i].set(S[j]), self.state, other.state
            )
            self.lanes[i] = lane
            if self._used[i]:
                self.counters.inc("lanes_reused")
            self._used[i] = True
            other.lanes[j] = None
            moved += 1
        return moved

    # -- the chunk step -----------------------------------------------------

    def step(self) -> List[Tuple[int, _Lane, str]]:
        """Advance every occupied lane one chunk; returns the lanes
        that finished this boundary as ``(index, lane, status)``.  The
        caller reads results / releases lanes / admits replacements —
        all at this boundary, which is what makes the batching
        continuous."""
        t0 = perf_counter()
        now = monotonic()
        ns: List[int] = []
        keys: List[Any] = []
        specs: List[Optional[Any]] = []
        for lane in self.lanes:
            if lane is None or lane.converged:
                ns.append(0)
                keys.append(lane.key if lane else self._idle_key)
                specs.append(None)
                continue
            n = min(self.chunk, self.limit - lane.age)
            if lane.job.deadline_at is not None:
                remaining = lane.job.deadline_at - now
                if self.deadline_pressure < 1.0 and (
                    self.pressure_exempt_priority is None
                    or lane.job.priority < self.pressure_exempt_priority
                ):
                    remaining *= self.deadline_pressure
                n2 = clamp_chunk_to_deadline(n, self.rate, remaining)
                if n2 < n:
                    self.counters.inc("deadline_shrunk_lanes")
                n = n2
            ns.append(n)
            keys.append(lane.key)
            specs.append(lane.spec)
        new_keys, xs = self.adapter.chunk_xs_per_lane(
            keys, ns, specs, self.target, self.chunk
        )
        done_mask = np.array(
            [ln is None or ln.converged for ln in self.lanes], bool
        )
        self.state, flags = self.runner(
            self.arrays, self.state, xs,
            jnp.asarray(np.asarray(ns, np.int32)),
            jnp.asarray(done_mask),
        )
        flags_np = np.asarray(flags)  # the step's ONE device→host read
        conv_np, finite_np = flags_np[0], flags_np[1]
        self.nonfinite = [
            i for i, ln in enumerate(self.lanes)
            if ln is not None and not ln.converged and ns[i] > 0
            and not finite_np[i]
        ]
        wall = perf_counter() - t0
        self.steps += 1
        advanced = max(ns) if ns else 0
        if wall > 0 and advanced:
            inst = advanced / wall
            self.rate = (
                inst if self.rate is None else 0.5 * self.rate + 0.5 * inst
            )

        finished: List[Tuple[int, _Lane, str]] = []
        deadline_now = monotonic()
        for i, lane in enumerate(self.lanes):
            if lane is None or lane.converged:
                continue
            lane.key = new_keys[i]
            lane.age += int(ns[i])
            status = None
            if lane.first_chunk:
                # harness parity: the first chunk's flag compares
                # against the initial state and is skipped
                lane.first_chunk = False
            else:
                lane.stable = lane.stable + 1 if conv_np[i] else 0
                if lane.stable >= 2:
                    status = "FINISHED"
                    lane.converged = True
            if status is None and lane.age >= self.limit:
                status = "FINISHED"
            if (
                status is None
                and lane.job.deadline_at is not None
                and deadline_now >= lane.job.deadline_at
            ):
                status = "TIMEOUT"
            if status is not None:
                finished.append((i, lane, status))
        return finished

    # -- results / inspection ----------------------------------------------

    def lane_values(self, i: int, lane: _Lane) -> np.ndarray:
        """Host copy of lane ``i``'s TRUE-shape value indices."""
        lane_state = jax.tree_util.tree_map(lambda L: L[i], self.state)
        vals = np.asarray(self.adapter.values_np(lane_state))
        return vals[: lane.spec.dims.V]

    def lane_result(self, i: int, lane: _Lane, status: str) -> SolveResult:
        assignment = lane.spec.tensors.assignment_from_indices(
            self.lane_values(i, lane)
        )
        violation, cost = lane.job.dcop.solution_cost(
            assignment, DEFAULT_INFINITY
        )
        solver = lane.spec.solver
        n_cyc = int(lane.age)
        return SolveResult(
            status=status,
            assignment=assignment,
            cost=cost,
            violation=violation,
            cycle=n_cyc,
            msg_count=solver.msgs_per_cycle * n_cyc,
            msg_size=(solver.msgs_per_cycle * n_cyc
                      * solver.msg_size_per_msg),
            time=monotonic() - lane.job.submitted_at,
        )

    def lane_cost(self, i: int, lane: _Lane) -> Tuple[float, int]:
        """(cost, cycle) of the lane's current anytime assignment —
        the per-boundary progress stream."""
        assignment = lane.spec.tensors.assignment_from_indices(
            self.lane_values(i, lane)
        )
        _violation, cost = lane.job.dcop.solution_cost(
            assignment, DEFAULT_INFINITY
        )
        return cost, int(lane.age)

    # -- checkpointing ------------------------------------------------------

    def lane_checkpoint(self, i: int, lane: _Lane):
        """(arrays, meta) snapshot of one lane at the current chunk
        boundary, for runtime/checkpoint.write_state_npz.  The graph
        arrays are NOT stored — they recompile deterministically from
        the job's source file + seed; only the lane's state leaves,
        key and harness accounting are."""
        lane_state = jax.tree_util.tree_map(
            lambda L: np.asarray(L[i]), self.state
        )
        leaves, _treedef = jax.tree_util.tree_flatten(lane_state)
        arrays = {f"leaf_{j}": np.asarray(l) for j, l in enumerate(leaves)}
        arrays["prng_key"] = np.asarray(lane.key)
        meta = {
            "jid": lane.job.jid,
            "algo": self.algo,
            "age": int(lane.age),
            "stable": int(lane.stable),
            "first_chunk": bool(lane.first_chunk),
            "n_leaves": len(leaves),
            "target": dataclasses.asdict(self.target),
        }
        return arrays, meta


def restore_lane_state(adapter, spec, target: InstanceDims,
                       arrays: Dict[str, np.ndarray], meta: Dict) -> Tuple:
    """Rebuild a lane's ``(state, key, age, stable, first_chunk)``
    restore tuple from a checkpoint container.  The leaf order/shapes
    come from the adapter's own initial-state structure at the SAME
    target the checkpoint was taken at (the caller guarantees the
    match), so a schema drift fails loudly instead of mis-seating."""
    ref = adapter.initial_state(spec, target)
    ref_leaves, treedef = jax.tree_util.tree_flatten(ref)
    n = int(meta["n_leaves"])
    if n != len(ref_leaves):
        raise ValueError(
            f"checkpoint for {meta.get('jid')!r} has {n} state leaves, "
            f"solver expects {len(ref_leaves)}"
        )
    leaves = []
    for j, ref_leaf in enumerate(ref_leaves):
        leaf = np.asarray(arrays[f"leaf_{j}"])
        if leaf.shape != np.asarray(ref_leaf).shape:
            raise ValueError(
                f"checkpoint leaf {j} shape {leaf.shape} does not match "
                f"solver state shape {np.asarray(ref_leaf).shape}"
            )
        leaves.append(leaf)
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    key = jnp.asarray(arrays["prng_key"])
    return (
        state,
        key,
        int(meta["age"]),
        int(meta["stable"]),
        bool(meta["first_chunk"]),
    )
